#include "storage/rebalance.h"

#include <string.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <fstream>
#include <map>
#include <sstream>

#include "common/bytes.h"
#include "common/eventlog.h"
#include "common/jumphash.h"
#include "common/log.h"
#include "common/threadreg.h"
#include "common/net.h"
#include "common/protocol_gen.h"
#include "storage/binlog.h"
#include "storage/tracker_client.h"

namespace fdfs {

namespace {

constexpr int kRpcTimeoutMs = 30000;
// Loopback + peer payloads: a single file's bytes plus framing.
constexpr int64_t kMaxRpcBody = 1LL << 31;

std::string PackGroup16(const std::string& group) {
  std::string out(16, '\0');
  memcpy(out.data(), group.data(), std::min<size_t>(group.size(), 16));
  return out;
}

// Extension carried into the new file id, from the old remote name
// ("M00/aa/bb/xxx.bin" -> "bin"); the 6-byte field every upload wire
// uses.
std::string Ext6(const std::string& remote) {
  std::string ext;
  size_t dot = remote.rfind('.');
  if (dot != std::string::npos && remote.size() - dot - 1 <= 6 &&
      remote.find('/', dot) == std::string::npos)
    ext = remote.substr(dot + 1);
  std::string out(6, '\0');
  memcpy(out.data(), ext.data(), std::min<size_t>(ext.size(), 6));
  return out;
}

}  // namespace

RebalanceManager::Conn::~Conn() { Close(); }

void RebalanceManager::Conn::Reset(const std::string& h, int p) {
  if (h == host && p == port) return;
  Close();
  host = h;
  port = p;
}

void RebalanceManager::Conn::Close() {
  if (fd >= 0) {
    close(fd);
    fd = -1;
  }
}

bool RebalanceManager::Conn::Call(uint8_t cmd, const std::string& body,
                                  std::string* resp, uint8_t* status) {
  for (int attempt = 0; attempt < 2; ++attempt) {
    if (fd < 0) {
      std::string err;
      fd = TcpConnect(host, port, kRpcTimeoutMs, &err);
      if (fd < 0) return false;
    }
    if (NetRpc(fd, cmd, body, resp, status, kMaxRpcBody, kRpcTimeoutMs))
      return true;
    Close();  // stale keepalive: one reconnect
  }
  return false;
}

RebalanceManager::RebalanceManager(RebalanceOptions opts,
                                   TrackerReporter* reporter, EventLog* events)
    : opts_(std::move(opts)), reporter_(reporter), events_(events) {
  self_.Reset("127.0.0.1", opts_.port);
}

RebalanceManager::~RebalanceManager() { Stop(); }

void RebalanceManager::Start() {
  thread_ = std::thread([this] { ThreadMain(); });
}

void RebalanceManager::Stop() {
  {
    std::lock_guard<RankedMutex> lk(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  if (thread_.joinable()) thread_.join();
}

void RebalanceManager::Kick() {
  {
    std::lock_guard<RankedMutex> lk(mu_);
    kicked_ = true;
  }
  cv_.notify_all();
}

bool RebalanceManager::Stopped() {
  std::lock_guard<RankedMutex> lk(mu_);
  return stop_;
}

void RebalanceManager::ThreadMain() {
  ScopedThreadName ledger("rebalance");
  std::unique_lock<RankedMutex> lk(mu_);
  while (!stop_) {
    BeatThreadHeartbeat();
    // Sliced to <= 1s waits so the thread heartbeat stays fresh for the
    // watchdog (threadreg.h) while parked between polls.
    for (int waited = 0, total = std::max(1, opts_.poll_interval_s);
         waited < total; ++waited) {
      if (cv_.wait_for(lk, std::chrono::seconds(1),
                       [this] { return stop_ || kicked_; }))
        break;
      BeatThreadHeartbeat();
    }
    if (stop_) return;
    kicked_ = false;
    // Drop mu_ (rank 34) before touching the reporter: group_state()
    // takes the reporter mutex (rank 20), which must never be acquired
    // under a higher-ranked lock.
    lk.unlock();
    int state = reporter_ != nullptr ? reporter_->group_state() : 0;
    if (state == 1) {  // draining: migrate
      RunPass();
    } else if (state == 0) {
      // Reactivated (or never drained): a stale done/pending report
      // would re-trigger the tracker's auto-retire the moment the
      // group drains again, before any pass ran.
      done_.store(0);
      files_pending_.store(0);
    }
    // state 2 (retired): nothing left to do; done_ stays truthful.
    lk.lock();
  }
}

std::vector<std::string> RebalanceManager::LoadInventory() {
  // Replay the whole binlog: the last non-delete op's case decides
  // ownership (uppercase = this member was the op's source — the sync
  // threads' partitioning, so exactly one live member migrates each
  // file).  Deletes drop the entry, which is also how finished
  // migrations leave the inventory (the loopback DELETE_FILE logs D).
  std::map<std::string, bool> files;  // filename -> we are source
  for (int idx = 0;; ++idx) {
    char name[32];
    std::snprintf(name, sizeof(name), "/binlog.%03d", idx);
    std::ifstream in(opts_.sync_dir + name);
    if (!in) break;
    std::string line;
    while (std::getline(in, line)) {
      auto rec = ParseBinlogRecord(line);
      if (!rec) continue;
      char op = rec->op;
      if (op == 'D' || op == 'd') {
        files.erase(rec->filename);
      } else {
        files[rec->filename] = (op >= 'A' && op <= 'Z');
      }
    }
  }
  std::vector<std::string> out;
  for (const auto& [fn, source] : files)
    if (source) out.push_back(fn);
  return out;
}

bool RebalanceManager::FetchPlacement(std::vector<TargetGroup>* active) {
  for (const std::string& addr : opts_.trackers) {
    size_t c = addr.rfind(':');
    if (c == std::string::npos) continue;
    Conn t;
    t.Reset(addr.substr(0, c), atoi(addr.c_str() + c + 1));
    std::string resp;
    uint8_t status = 0;
    if (!t.Call(static_cast<uint8_t>(TrackerCmd::kQueryPlacement), "", &resp,
                &status) ||
        status != 0 || resp.size() < 16)
      continue;
    const uint8_t* p = reinterpret_cast<const uint8_t*>(resp.data());
    int64_t entries = GetInt64BE(p + 8);
    size_t off = 16;
    active->clear();
    bool ok = true;
    for (int64_t i = 0; i < entries && ok; ++i) {
      if (resp.size() < off + 25) {
        ok = false;
        break;
      }
      TargetGroup g;
      g.name = GetFixedField(p + off, 16);
      uint8_t state = p[off + 16];
      int64_t members = GetInt64BE(p + off + 17);
      off += 25;
      if (members < 0 ||
          static_cast<size_t>(members) > (resp.size() - off) / 24) {
        ok = false;
        break;
      }
      for (int64_t m = 0; m < members; ++m) {
        g.members.emplace_back(
            GetFixedField(p + off, 16),
            static_cast<int>(GetInt64BE(p + off + 16)));
        off += 24;
      }
      // Only ACTIVE groups (and never this draining one) receive
      // migrated files; epoch ORDER is the jump-hash bucket order.
      if (state == 0 && g.name != opts_.group_name && !g.members.empty())
        active->push_back(std::move(g));
    }
    if (ok) return true;
  }
  return false;
}

void RebalanceManager::Pace(int64_t bytes_done, int64_t pass_start_us) {
  int64_t bw = static_cast<int64_t>(bandwidth_mb_s_.load()) * 1024 * 1024;
  if (bw <= 0) return;
  // Divide before scaling to microseconds (the scrub overflow lesson).
  int64_t budget_us =
      bytes_done / bw * 1000000 + (bytes_done % bw) * 1000000 / bw;
  int64_t ahead_us = budget_us - (MonoUs() - pass_start_us);
  while (ahead_us > 0) {
    if (Stopped()) return;
    BeatThreadHeartbeat();  // pacing sleep, not a stall
    usleep(static_cast<useconds_t>(std::min<int64_t>(ahead_us, 50000)));
    ahead_us = budget_us - (MonoUs() - pass_start_us);
  }
}

bool RebalanceManager::UploadToTarget(Conn* target, const std::string& remote,
                                      const std::string& bytes,
                                      std::string* new_id) {
  std::string ext = Ext6(remote);
  uint8_t num[8];
  // Negotiated path: if the source stored a recipe, offer it to the
  // target so only chunks its store lacks cross the wire (a dup-heavy
  // drain moves ~unique bytes).  Any refusal — no chunk store
  // (ENOTSUP), an old daemon (EINVAL), a died session (ENOENT), or a
  // recipe/byte mismatch — falls through to the flat upload.
  std::string recipe;
  uint8_t status = 0;
  if (self_.Call(static_cast<uint8_t>(StorageCmd::kFetchRecipe),
                 PackGroup16(opts_.group_name) + remote, &recipe, &status) &&
      status == 0 && recipe.size() >= 16) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(recipe.data());
    int64_t logical = GetInt64BE(p);
    int64_t count = GetInt64BE(p + 8);
    if (logical == static_cast<int64_t>(bytes.size()) && count > 0 &&
        static_cast<size_t>(count) <= (recipe.size() - 16) / 28) {
      std::string req;
      req.push_back(static_cast<char>(0xFF));  // server picks store path
      req += ext;
      PutInt64BE(Crc32(bytes.data(), bytes.size()), num);
      req.append(reinterpret_cast<char*>(num), 8);
      PutInt64BE(logical, num);
      req.append(reinterpret_cast<char*>(num), 8);
      req.append(reinterpret_cast<const char*>(p + 8), 8);  // chunk count
      req.append(recipe, 16, static_cast<size_t>(count) * 28);
      std::string resp;
      if (target->Call(static_cast<uint8_t>(StorageCmd::kUploadRecipe), req,
                       &resp, &status) &&
          status == 0 && resp.size() >= 8 + static_cast<size_t>(count)) {
        std::string payloads;
        int64_t off = 0;
        bool sliced = true;
        for (int64_t i = 0; i < count; ++i) {
          int64_t len = GetInt64BE(p + 16 + i * 28 + 20);
          if (len < 0 || off + len > logical) {
            sliced = false;
            break;
          }
          if (resp[8 + i] != 0)
            payloads.append(bytes, static_cast<size_t>(off),
                            static_cast<size_t>(len));
          off += len;
        }
        if (sliced && off == logical) {
          std::string req2 = resp.substr(0, 8);  // session id
          PutInt64BE(static_cast<int64_t>(payloads.size()), num);
          req2.append(reinterpret_cast<char*>(num), 8);
          req2 += payloads;
          std::string resp2;
          if (target->Call(static_cast<uint8_t>(StorageCmd::kUploadChunks),
                           req2, &resp2, &status) &&
              status == 0 && resp2.size() > 16) {
            std::string g(resp2.c_str(), strnlen(resp2.c_str(), 16));
            *new_id = g + "/" + resp2.substr(16);
            return true;
          }
        }
      }
    }
  }
  // Flat upload: 1B spi + 8B size + 6B ext + payload.
  std::string req;
  req.reserve(15 + bytes.size());
  req.push_back(0);
  PutInt64BE(static_cast<int64_t>(bytes.size()), num);
  req.append(reinterpret_cast<char*>(num), 8);
  req += ext;
  req += bytes;
  std::string resp;
  if (!target->Call(static_cast<uint8_t>(StorageCmd::kUploadFile), req, &resp,
                    &status) ||
      status != 0 || resp.size() <= 16)
    return false;
  std::string g(resp.c_str(), strnlen(resp.c_str(), 16));
  *new_id = g + "/" + resp.substr(16);
  return true;
}

bool RebalanceManager::VerifyRemote(Conn* target, const std::string& new_id,
                                    const std::string& expect_bytes) {
  size_t slash = new_id.find('/');
  if (slash == std::string::npos) return false;
  std::string body(16, '\0');  // offset 0, length 0 (= to EOF)
  body += PackGroup16(new_id.substr(0, slash)) + new_id.substr(slash + 1);
  std::string resp;
  uint8_t status = 0;
  if (!target->Call(static_cast<uint8_t>(StorageCmd::kDownloadFile), body,
                    &resp, &status) ||
      status != 0)
    return false;
  return resp == expect_bytes;
}

void RebalanceManager::AppendMap(const std::string& old_id,
                                 const std::string& new_id) {
  std::ofstream out(opts_.base_path + "/data/rebalance.map",
                    std::ios::app);
  out << old_id << ' ' << new_id << '\n';
  out.flush();
}

bool RebalanceManager::MigrateOne(const std::string& remote,
                                  const std::vector<TargetGroup>& active,
                                  int64_t seq,
                                  const std::string& mapped_new_id) {
  const std::string old_id = opts_.group_name + "/" + remote;
  std::string body(16, '\0');
  body += PackGroup16(opts_.group_name) + remote;
  std::string bytes;
  uint8_t status = 0;
  if (!self_.Call(static_cast<uint8_t>(StorageCmd::kDownloadFile), body,
                  &bytes, &status))
    return false;
  if (status != 0) {
    // Gone locally (raced with a client delete): nothing to move.
    return status == 2;
  }
  pass_paced_ += static_cast<int64_t>(bytes.size());
  Pace(pass_paced_, pass_start_us_);

  const TargetGroup& tg =
      active[JumpHash(PlacementKey(old_id),
                      static_cast<int32_t>(active.size()))];
  std::string new_id = mapped_new_id;
  if (!new_id.empty()) {
    // Crash recovery: the map committed before the source delete did.
    // If the target copy verifies, finish the delete; if the target
    // never got the file, fall through and migrate afresh.
    size_t slash = new_id.find('/');
    std::string ngroup =
        slash == std::string::npos ? "" : new_id.substr(0, slash);
    for (const TargetGroup& g : active) {
      if (g.name != ngroup) continue;
      Conn& t = target_;
      t.Reset(g.members[seq % g.members.size()].first,
              g.members[seq % g.members.size()].second);
      if (VerifyRemote(&t, new_id, bytes)) {
        std::string resp;
        if (self_.Call(static_cast<uint8_t>(StorageCmd::kDeleteFile),
                       PackGroup16(opts_.group_name) + remote, &resp,
                       &status) &&
            (status == 0 || status == 2)) {
          files_moved_.fetch_add(1);
          return true;
        }
      }
      break;
    }
    new_id.clear();
  }

  const auto& member = tg.members[seq % tg.members.size()];
  target_.Reset(member.first, member.second);
  if (!UploadToTarget(&target_, remote, bytes, &new_id)) return false;
  pass_paced_ += static_cast<int64_t>(bytes.size());
  Pace(pass_paced_, pass_start_us_);
  // Byte identity BEFORE the source copy is touched: a migration that
  // cannot re-read what it wrote deletes nothing.
  if (!VerifyRemote(&target_, new_id, bytes)) {
    FDFS_LOG_ERROR("rebalance: %s -> %s failed verify, keeping source",
                   old_id.c_str(), new_id.c_str());
    return false;
  }
  AppendMap(old_id, new_id);
  std::string resp;
  if (!self_.Call(static_cast<uint8_t>(StorageCmd::kDeleteFile),
                  PackGroup16(opts_.group_name) + remote, &resp, &status) ||
      (status != 0 && status != 2))
    return false;
  files_moved_.fetch_add(1);
  bytes_moved_.fetch_add(static_cast<int64_t>(bytes.size()));
  return true;
}

void RebalanceManager::RunPass() {
  passes_.fetch_add(1);
  pass_start_us_ = MonoUs();
  pass_paced_ = 0;
  std::vector<std::string> inventory = LoadInventory();
  files_pending_.store(static_cast<int64_t>(inventory.size()));
  if (inventory.empty()) {
    if (done_.exchange(1) == 0) {
      FDFS_LOG_INFO("rebalance: drain of %s complete (%lld files moved)",
                    opts_.group_name.c_str(),
                    static_cast<long long>(files_moved_.load()));
      if (events_ != nullptr)
        events_->Record(EventSeverity::kInfo, "rebalance.done",
                        opts_.group_name,
                        "files_moved=" + std::to_string(files_moved_.load()) +
                            " bytes_moved=" +
                            std::to_string(bytes_moved_.load()));
    }
    return;
  }
  done_.store(0);
  std::vector<TargetGroup> active;
  if (!FetchPlacement(&active) || active.empty()) {
    // No reachable tracker / nowhere to put files: not per-file errors,
    // just retry the pass later.
    FDFS_LOG_WARN("rebalance: no active target groups visible, waiting");
    return;
  }
  // Crash-recovery record: old ids whose new id already committed.
  std::map<std::string, std::string> moved_map;
  {
    std::ifstream in(opts_.base_path + "/data/rebalance.map");
    std::string o, n;
    while (in >> o >> n) moved_map[o] = n;
  }
  int64_t seq = 0;
  int64_t pass_errors = 0;
  for (const std::string& remote : inventory) {
    if (Stopped()) return;
    auto it = moved_map.find(opts_.group_name + "/" + remote);
    bool ok = MigrateOne(remote, active, seq++,
                         it != moved_map.end() ? it->second : "");
    if (ok) {
      files_pending_.fetch_add(-1);
    } else {
      ++pass_errors;
      errors_.fetch_add(1);
    }
  }
  if (events_ != nullptr)
    events_->Record(EventSeverity::kInfo, "rebalance.pass", opts_.group_name,
                    "pending=" + std::to_string(files_pending_.load()) +
                        " errors=" + std::to_string(pass_errors));
  if (files_pending_.load() == 0 && done_.exchange(1) == 0) {
    FDFS_LOG_INFO("rebalance: drain of %s complete (%lld files moved)",
                  opts_.group_name.c_str(),
                  static_cast<long long>(files_moved_.load()));
    if (events_ != nullptr)
      events_->Record(EventSeverity::kInfo, "rebalance.done",
                      opts_.group_name,
                      "files_moved=" + std::to_string(files_moved_.load()) +
                          " bytes_moved=" +
                          std::to_string(bytes_moved_.load()));
  }
}

}  // namespace fdfs
