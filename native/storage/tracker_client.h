// Storage-side tracker reporting threads.
//
// Reference: storage/tracker_client_thread.c — one thread per tracker:
// JOIN on connect, heartbeats (TRACKER_PROTO_CMD_STORAGE_BEAT) carrying the
// stat blob, periodic disk-usage reports; the peer list in each response
// drives the sync threads (spawn/kill on membership change).
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <map>
#include <mutex>

#include "common/lockrank.h"
#include <string>
#include <thread>
#include <vector>

#include "common/heatwire.h"
#include "storage/config.h"

namespace fdfs {

struct PeerInfo {
  std::string ip;
  int port = 0;
  int status = 0;
  std::string Addr() const { return ip + ":" + std::to_string(port); }
  bool operator==(const PeerInfo& o) const {
    return ip == o.ip && port == o.port;
  }
};

// Thread-safe stat snapshot provider: fills kBeatStatCount slots
// (protocol_gen.h kBeatStatNames) for the beat blob.
using StatsSnapshotFn = std::function<void(int64_t* out)>;
using PeersCallback = std::function<void(const std::vector<PeerInfo>&)>;

class TrackerReporter {
 public:
  TrackerReporter(StorageConfig cfg, StatsSnapshotFn stats_fn,
                  PeersCallback peers_cb);
  ~TrackerReporter();

  void Start();
  void Stop();
  // Health trailer provider (common/healthmon.h PackBeatTrailer): bytes
  // appended AFTER the kBeatStatCount stat slots in every beat body.
  // The tracker's beat parser reads min(available, kBeatStatCount)
  // slots and ignores the rest, so the append is wire-compatible both
  // ways (append-only contract, the PR 10 discipline).  Set before
  // Start(); empty return = trailerless beat.
  void set_health_trailer_fn(std::function<std::string()> fn) {
    health_trailer_fn_ = std::move(fn);
  }
  // Heat trailer provider (common/heatwire.h PackHeatTrailer): the heat
  // sketch's cumulative top-K read counters, appended AFTER the health
  // trailer in every beat (either may be empty; same append-only
  // contract).  Set before Start().
  void set_heat_trailer_fn(std::function<std::string()> fn) {
    heat_trailer_fn_ = std::move(fn);
  }
  // Hot-replication tasking (ISSUE 20): invoked from the beat thread
  // whenever a beat response carries a hot-task trailer — this node is
  // the elected fan-out member for those keys.  tracker_addr is the
  // issuing tracker ("host:port"), where HOT_FANOUT_DONE acks go.
  // Set before Start().
  void set_hot_tasks_fn(
      std::function<void(const std::string& tracker_addr,
                         const std::vector<HotTask>&)> fn) {
    hot_tasks_fn_ = std::move(fn);
  }
  // Disk recovery in progress: JOINs carry the recovering flag (tracker
  // holds the node in WAIT_SYNC) and the join-time sync negotiation is
  // left to the recovery thread.  Cleared when the rebuild completes.
  void set_recovering(bool v) { recovering_ = v; }
  bool recovering() const { return recovering_; }
  // Source->tracker sync progress report (called by sync threads).
  void ReportSyncProgress(const std::string& dest_ip, int dest_port,
                          int64_t ts);
  std::string my_ip() const;
  std::vector<PeerInfo> peers() const;
  // Cluster-global params fetched from the tracker at join
  // (storage_param_getter.c analogue); empty until first successful join.
  std::map<std::string, std::string> cluster_params() const;
  // Group's elected trunk server from the latest beat ("" / 0 when none).
  std::pair<std::string, int> trunk_server() const;
  int64_t trunk_epoch() const;  // fencing token for trunk RPCs
  // This group's placement state from the latest beat trailer
  // (0 active / 1 draining / 2 retired; tracker/placement.h GroupState).
  // Draining means: refuse new client-facing writes, keep serving reads,
  // and the rebalance migrator should be moving files out.
  int group_state() const;
  int64_t placement_version() const;  // placement epoch seen in that beat

 private:
  void ThreadMain(std::string host, int port);
  // chlog_off: per-tracker changelog resume offset (each tracker keeps an
  // independent changelog file, so the cursor lives in its thread).
  bool DoJoin(int fd, int64_t* chlog_off);
  bool DoBeat(int fd, int64_t* chlog_off, const std::string& tracker_addr);
  bool DoDiskReport(int fd);
  void DoSyncDestReq(int fd);
  void DoParameterReq(int fd);
  // IP-changed dealer (storage_ip_changed_dealer.c): compare the
  // persisted identity with the current one and ask the tracker to
  // rewrite us before joining; afterwards persist the new identity.
  void CheckIpChanged(int fd);
  void PersistIdentity();
  // Apply the tracker's identity changelog: rename local sync-mark
  // cursors for peers whose IP moved (storage_changelog_req).  MUST run
  // before NotifyPeersChanged spawns a sync worker for a renamed peer —
  // a fresh zero-position mark would win over the rename and re-replay
  // the whole binlog.
  void DoChangelogReq(int fd, int64_t* chlog_off);
  bool ParsePeers(const std::string& body, bool* peers_changed = nullptr,
                  std::vector<HotTask>* hot_tasks = nullptr);
  void NotifyPeersChanged();

  StorageConfig cfg_;
  StatsSnapshotFn stats_fn_;
  PeersCallback peers_cb_;
  std::function<std::string()> health_trailer_fn_;  // set before Start()
  std::function<std::string()> heat_trailer_fn_;    // set before Start()
  std::function<void(const std::string&, const std::vector<HotTask>&)>
      hot_tasks_fn_;  // set before Start()
  std::atomic<bool> stop_{false};
  std::atomic<bool> recovering_{false};
  std::vector<std::thread> threads_;
  mutable RankedMutex mu_{LockRank::kTrackerReporter};
  std::string my_ip_;
  std::vector<PeerInfo> peers_;
  struct SyncProgress {
    std::string dest_ip;
    int dest_port;
    int64_t ts;
  };
  std::vector<SyncProgress> pending_sync_reports_;
  std::map<std::string, std::string> cluster_params_;
  std::string trunk_ip_;
  int trunk_port_ = 0;
  int64_t trunk_epoch_ = 0;
  int group_state_ = 0;           // GroupState numeric, 0 = active
  int64_t placement_version_ = 0;
  // Identity recorded at process start (read once, BEFORE any thread
  // rewrites the identity file): every tracker thread must send the
  // rename RPC from the same old->new view, or slower threads would read
  // the already-updated file and skip it.
  std::string recorded_ip_;
  int recorded_port_ = 0;
};

}  // namespace fdfs
