// Hot-replication fan-out worker (ISSUE 20): executes the tracker's
// replicate/drop assignments for keys this node was elected to handle
// (jump-hash over the home group's sorted ACTIVE members, tracker-side).
//
// Replicate: push the file to every ACTIVE member of each target group
// via the established sync-create path — with the TARGET group's name
// in the wire group field, so the receiver stores the copy in its own
// tree under the same remote name and serves it at
// "<target group>/<remote>" with zero read-path changes.  The receiver
// logs it as a replica op ('c'), so the copy never re-ships.  Then
// byte-verify: download each copy back and compare SHA-1 against the
// local bytes, and only after every assigned group verifies, ack the
// tracker (HOT_FANOUT_DONE) — which is what publishes the map entry
// (verify-then-publish: a routed read can never miss).
//
// Drop: SYNC_DELETE_FILE to every ACTIVE member of each listed group
// (ENOENT tolerated — the copy may predate a member), then ack.  The
// tracker only issues drops a full epoch after the tombstone, so no
// client still holds the route.
//
// Tasks arrive from the beat thread (TrackerReporter hot-task trailer)
// and are re-sent every beat until acked, so the queue dedups by
// (type, key) and failures simply wait for the next beat's re-delivery.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <optional>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "common/eventlog.h"
#include "common/heatwire.h"
#include "common/lockrank.h"
#include "storage/config.h"
#include "storage/sync.h"  // ContentHandle

namespace fdfs {

struct HotReplCallbacks {
  // Trunk/recipe-aware logical-content opener (the sync ReplayCreate
  // source); nullopt = the file is gone (task acked as failed — the
  // tracker keeps or retires the entry on its own evidence).
  std::function<std::optional<ContentHandle>(const std::string& remote)>
      open_content;
  EventLog* events = nullptr;
};

class HotReplManager {
 public:
  HotReplManager(const StorageConfig& cfg, HotReplCallbacks cbs);
  ~HotReplManager();

  void Start();
  void Stop();

  // Beat-thread entry: enqueue this beat's assignments.  Duplicates of
  // queued or in-flight work are ignored (at-least-once delivery from
  // the tracker, exactly-once execution here per cycle).
  void Enqueue(const std::string& tracker_addr,
               const std::vector<HotTask>& tasks);

  int64_t replicated_total() const {
    return replicated_total_.load(std::memory_order_relaxed);
  }
  int64_t dropped_total() const {
    return dropped_total_.load(std::memory_order_relaxed);
  }
  int64_t verify_failures() const {
    return verify_failures_.load(std::memory_order_relaxed);
  }
  int64_t failures_total() const {
    return failures_total_.load(std::memory_order_relaxed);
  }
  int64_t queue_depth() const;

 private:
  struct Job {
    std::string tracker_addr;
    HotTask task;
  };

  void ThreadMain();
  bool RunReplicate(const Job& job);
  bool RunDrop(const Job& job);
  // QUERY_PLACEMENT against the issuing tracker: ACTIVE members of one
  // group ("ip:port" pairs).
  bool QueryGroupMembers(const std::string& tracker_addr,
                         const std::string& group,
                         std::vector<std::pair<std::string, int>>* members);
  bool PushCopy(const std::string& ip, int port, const std::string& group,
                const std::string& remote);
  bool VerifyCopy(const std::string& ip, int port, const std::string& group,
                  const std::string& remote, const std::string& want_sha1,
                  int64_t want_size);
  bool AckTracker(const std::string& tracker_addr, uint8_t type,
                  const std::string& key,
                  const std::vector<std::string>& groups);

  StorageConfig cfg_;
  HotReplCallbacks cbs_;
  std::atomic<bool> stop_{false};
  std::thread thread_;
  mutable RankedMutex mu_{LockRank::kHotRepl};
  std::condition_variable_any cv_;
  std::deque<Job> queue_;
  std::set<std::string> inflight_;  // "<type>:<key>" dedup across beats
  std::atomic<int64_t> replicated_total_{0};
  std::atomic<int64_t> dropped_total_{0};
  std::atomic<int64_t> verify_failures_{0};
  std::atomic<int64_t> failures_total_{0};
};

}  // namespace fdfs
