#include "storage/scrub.h"

#include <string.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>

#include "common/bytes.h"
#include "common/eventlog.h"
#include "common/log.h"
#include "common/threadreg.h"
#include "common/net.h"

namespace fdfs {

namespace {

constexpr int kRpcTimeoutMs = 10000;
// Verify batch bounds: enough chunks per sidecar round-trip to amortize
// the RPC, small enough that a batch never holds more than a few MB.
constexpr size_t kBatchChunks = 64;
constexpr int64_t kBatchBytes = 4 << 20;

int64_t WallUs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

}  // namespace

ScrubManager::ScrubManager(ScrubOptions opts, std::string group_name,
                           std::vector<ChunkStore*> chunk_stores,
                           PeerListFn peers, DedupPlugin* plugin,
                           TraceRing* trace, EventLog* events)
    : opts_(opts), group_name_(std::move(group_name)),
      stores_(std::move(chunk_stores)), peers_(std::move(peers)),
      plugin_(plugin), trace_(trace), events_(events) {}

ScrubManager::~ScrubManager() { Stop(); }

void ScrubManager::Start() {
  thread_ = std::thread(&ScrubManager::ThreadMain, this);
}

void ScrubManager::Stop() {
  {
    std::lock_guard<RankedMutex> lk(mu_);
    if (stop_) return;
    stop_ = true;
  }
  cv_.notify_all();
  if (thread_.joinable()) thread_.join();
}

void ScrubManager::Kick() {
  {
    std::lock_guard<RankedMutex> lk(mu_);
    kicked_ = true;
  }
  cv_.notify_all();
}

void ScrubManager::NoteRecipeReclaimed(int64_t bytes) {
  recipes_reclaimed_.fetch_add(1, std::memory_order_relaxed);
  bytes_reclaimed_.fetch_add(bytes, std::memory_order_relaxed);
}

void ScrubManager::FillStats(int64_t* out) const {
  static_assert(kScrubStatCount == 18, "update StatValue + protocol.py");
  for (int i = 0; i < kScrubStatCount; ++i) out[i] = StatValue(i);
}

int64_t ScrubManager::StatValue(int i) const {
  switch (i) {  // kScrubStatNames order
    case 0: return running_.load() ? 1 : 0;
    case 1: return passes_.load();
    case 2: return pass_chunks_done_.load();
    case 3: return pass_chunks_total_.load();
    case 4: return chunks_verified_.load();
    case 5: return bytes_verified_.load();
    case 6: return chunks_corrupt_.load();
    case 7: return chunks_repaired_.load();
    case 8: return corrupt_unrepairable_.load();
    case 9: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->quarantined_chunks();
      return n;
    }
    case 10: return skipped_pinned_.load();
    case 11: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->gc_pending_chunks();
      return n;
    }
    case 12: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->gc_pending_bytes();
      return n;
    }
    case 13: return chunks_reclaimed_.load();
    case 14: return bytes_reclaimed_.load();
    case 15: return recipes_reclaimed_.load();
    case 16: return last_pass_unix_.load();
    case 17: return last_pass_dur_us_.load();
    default: return 0;
  }
}

void ScrubManager::ThreadMain() {
  ScopedThreadName ledger("scrub");
  std::unique_lock<RankedMutex> lk(mu_);
  while (!stop_) {
    bool due;
    if (opts_.interval_s > 0) {
      due = !cv_.wait_for(lk, std::chrono::seconds(opts_.interval_s),
                          [this] { return stop_ || kicked_; });
    } else {
      cv_.wait(lk, [this] { return stop_ || kicked_; });
      due = false;
    }
    if (stop_) return;
    due = due || kicked_;
    kicked_ = false;
    if (!due) continue;
    lk.unlock();
    RunPass();
    lk.lock();
  }
}

void ScrubManager::Pace(int64_t bytes_read, int64_t pass_start_us) {
  if (opts_.bandwidth_bytes_s <= 0) return;
  // Token bucket: the pass may only be `bytes_read / bw` seconds old.
  // Divide before scaling to microseconds — bytes_read is cumulative
  // over the pass, and `bytes * 1e6` would overflow int64 at ~9.2 TB
  // (a plausible store), silently disabling pacing.
  int64_t bw = opts_.bandwidth_bytes_s;
  int64_t budget_us =
      bytes_read / bw * 1000000 + (bytes_read % bw) * 1000000 / bw;
  int64_t ahead_us = budget_us - (WallUs() - pass_start_us);
  while (ahead_us > 0) {
    {
      std::lock_guard<RankedMutex> lk(mu_);
      if (stop_) return;
    }
    usleep(static_cast<useconds_t>(std::min<int64_t>(ahead_us, 50000)));
    ahead_us = budget_us - (WallUs() - pass_start_us);
  }
}

void ScrubManager::RunPass() {
  running_ = true;
  int64_t start_us = WallUs();
  pass_chunks_done_ = 0;
  pass_chunks_total_ = 0;
  pass_ctx_ = TraceCtx{};
  uint32_t root_span = 0;
  if (trace_ != nullptr) {
    pass_ctx_.trace_id = trace_->NewTraceId();
    pass_ctx_.flags = kTraceFlagSampled;
    root_span = trace_->NextSpanId();
    pass_ctx_.parent_span = root_span;
  }

  // The progress denominator is the live-chunk count at pass start
  // (approximate under churn — uploads and deletes move it).
  for (ChunkStore* cs : stores_)
    pass_chunks_total_ += cs->unique_chunks();

  int64_t paced = 0;
  bool aborted = false;
  for (size_t spi = 0; spi < stores_.size() && !aborted; ++spi) {
    ChunkStore* cs = stores_[spi];
    // Repair-retry targets from EARLIER passes, snapshotted before the
    // verify stage so a chunk quarantined in this pass (whose repair
    // already ran in HandleCorrupt) is not attempted twice per pass.
    auto retry = cs->SnapshotQuarantined();
    // Walk the store in 256 digest-prefix slices: each slice is one
    // short, allocation-light scan under a single stripe lock (slice
    // prefix pins the stripe since the PR 5 sharding — the scrubber
    // never contends with more than 1/16 of the foreground traffic),
    // and a many-million-chunk store never holds a full snapshot
    // resident across an hours-long paced pass.
    for (int prefix = 0; prefix < 256 && !aborted; ++prefix) {
      auto live = cs->SnapshotLive(prefix);
      size_t i = 0;
      while (i < live.size()) {
        {
          std::lock_guard<RankedMutex> lk(mu_);
          if (stop_) {
            aborted = true;
            break;
          }
        }
        // One bounded batch: read payloads, then verify them together.
        std::vector<ChunkStore::ChunkInfo> batch;
        std::vector<std::string> payloads;
        std::vector<char> bad;
        int64_t batch_bytes = 0;
        while (i < live.size() && batch.size() < kBatchChunks &&
               batch_bytes < kBatchBytes) {
          const auto& info = live[i++];
          batch.push_back(info);
          payloads.emplace_back();
          // A missing or short chunk file is corruption too (truncation,
          // lost write) — mark it bad without a digest round.
          bad.push_back(
              cs->ReadChunk(info.digest_hex, info.length, &payloads.back())
                  ? 0 : 1);
          batch_bytes += info.length;
        }
        paced += batch_bytes;
        Pace(paced, start_us);
        VerifyBatch(static_cast<int>(spi), batch, payloads, &bad);
        for (size_t b = 0; b < batch.size(); ++b)
          if (bad[b]) HandleCorrupt(static_cast<int>(spi), batch[b]);
        chunks_verified_.fetch_add(static_cast<int64_t>(batch.size()),
                                   std::memory_order_relaxed);
        bytes_verified_.fetch_add(batch_bytes, std::memory_order_relaxed);
        pass_chunks_done_.fetch_add(static_cast<int64_t>(batch.size()),
                                    std::memory_order_relaxed);
      }
    }
    if (aborted) break;

    // Repair retry: chunks quarantined by an earlier pass (no replica
    // had them then) get another chance every pass.
    for (const auto& info : retry)
      HandleCorrupt(static_cast<int>(spi), info, /*already_quarantined=*/true);

    // GC sweep: reclaim zero-ref chunks past the grace window.
    int64_t bytes = 0;
    int64_t n = cs->GcSweep(time(nullptr), &bytes);
    if (n > 0) {
      chunks_reclaimed_.fetch_add(n, std::memory_order_relaxed);
      bytes_reclaimed_.fetch_add(bytes, std::memory_order_relaxed);
      FDFS_LOG_INFO("scrub gc: reclaimed %lld chunks (%lld bytes) on "
                    "store path %zu",
                    static_cast<long long>(n),
                    static_cast<long long>(bytes), spi);
      if (events_ != nullptr) {
        char key[8], detail[64];
        snprintf(key, sizeof(key), "M%02zX", spi);
        snprintf(detail, sizeof(detail), "chunks=%lld bytes=%lld",
                 static_cast<long long>(n), static_cast<long long>(bytes));
        events_->Record(EventSeverity::kInfo, "gc.sweep", key, detail);
      }
    }

    // Slab compaction (ISSUE 9): right after GC marked slots dead, copy
    // the live records out of the deadest slabs and unlink them —
    // paced by the SAME token bucket as verify reads, so compaction IO
    // never starves foreground traffic either.  Records that fail the
    // copy-time re-verify come back here and ride the standard
    // quarantine -> replica-repair machinery (HandleCorrupt marks the
    // slot dead, so the next pass finishes the slab).
    std::vector<ChunkStore::ChunkInfo> slab_corrupt;
    int64_t slab_reclaimed = 0;
    int64_t compacted = cs->CompactSlabs(
        [&](int64_t b) {
          paced += b;
          Pace(paced, start_us);
        },
        [this]() {
          std::lock_guard<RankedMutex> lk(mu_);
          return stop_;
        },
        &slab_corrupt, &slab_reclaimed);
    for (const auto& info : slab_corrupt)
      HandleCorrupt(static_cast<int>(spi), info);
    if (compacted > 0)
      FDFS_LOG_INFO("scrub: compacted %lld slabs on store path %zu "
                    "(%lld bytes reclaimed)",
                    static_cast<long long>(compacted), spi,
                    static_cast<long long>(slab_reclaimed));
  }

  int64_t dur = WallUs() - start_us;
  if (!aborted) {
    passes_.fetch_add(1, std::memory_order_relaxed);
    last_pass_unix_ = time(nullptr);
    last_pass_dur_us_ = dur;
  }
  if (trace_ != nullptr && pass_ctx_.valid()) {
    TraceSpan s;
    s.trace_id = pass_ctx_.trace_id;
    s.span_id = root_span;
    s.parent_id = 0;
    s.start_us = TraceWallUs() - dur;
    s.dur_us = dur;
    s.status = aborted ? 4 /*EINTR*/ : 0;
    s.flags = kTraceFlagSampled;
    s.SetName("scrub.pass");
    trace_->Record(s);
  }
  running_ = false;
}

void ScrubManager::VerifyBatch(
    int spi, const std::vector<ChunkStore::ChunkInfo>& infos,
    const std::vector<std::string>& payloads, std::vector<char>* bad) {
  (void)spi;
  // Sidecar first: one DEDUP_VERIFY RPC hashes the whole batch with
  // ops/sha1.sha1_batch on the accelerator.  Unreadable entries are
  // already marked and excluded from the RPC.
  if (plugin_ != nullptr) {
    std::vector<ChunkFp> want;
    std::string concat;
    std::vector<size_t> idx;
    for (size_t i = 0; i < infos.size(); ++i) {
      if ((*bad)[i]) continue;
      ChunkFp fp;
      fp.length = infos[i].length;
      fp.digest_hex = infos[i].digest_hex;
      want.push_back(std::move(fp));
      concat += payloads[i];
      idx.push_back(i);
    }
    std::string mask;
    if (!want.empty() && plugin_->VerifyChunks(want, concat, &mask) &&
        mask.size() == want.size()) {
      for (size_t k = 0; k < idx.size(); ++k)
        if (mask[k]) (*bad)[idx[k]] = 1;
      return;
    }
  }
  // Serial host path (SHA-NI when the CPU has it).
  for (size_t i = 0; i < infos.size(); ++i) {
    if ((*bad)[i]) continue;
    if (Sha1(payloads[i].data(), payloads[i].size()).Hex() !=
        infos[i].digest_hex)
      (*bad)[i] = 1;
  }
}

void ScrubManager::HandleCorrupt(int spi, const ChunkStore::ChunkInfo& info,
                                 bool already_quarantined) {
  ChunkStore* cs = stores_[spi];
  int64_t t0 = TraceWallUs();
  int status = 0;
  bool attempted = false;
  if (already_quarantined && !cs->IsQuarantined(info.digest_hex))
    return;  // healed (re-upload/repair) since the retry snapshot
  if (!already_quarantined) {
    switch (cs->Quarantine(info.digest_hex)) {
      case ChunkStore::QuarantineResult::kGone:
        return;  // deleted since the snapshot — nothing was corrupt
      case ChunkStore::QuarantineResult::kClean:
        // False alarm: the lock-free verify read raced a delete +
        // re-upload; the authoritative under-lock re-hash is clean.
        return;
      case ChunkStore::QuarantineResult::kPinned:
        // An in-flight stream or upload session still holds the chunk:
        // repair-in-place under a reader is unsafe; retry next pass.
        chunks_corrupt_.fetch_add(1, std::memory_order_relaxed);
        skipped_pinned_.fetch_add(1, std::memory_order_relaxed);
        FDFS_LOG_WARN("scrub: corrupt chunk %s is pinned by an in-flight "
                      "stream; retrying next pass",
                      info.digest_hex.c_str());
        return;
      case ChunkStore::QuarantineResult::kQuarantined:
        chunks_corrupt_.fetch_add(1, std::memory_order_relaxed);
        FDFS_LOG_WARN("scrub: chunk %s failed verification on store path "
                      "%d — quarantined",
                      info.digest_hex.c_str(), spi);
        if (events_ != nullptr)
          events_->Record(EventSeverity::kWarn, "chunk.quarantined",
                          info.digest_hex,
                          "spi=" + std::to_string(spi) +
                              " bytes=" + std::to_string(info.length));
        break;
    }
  }
  std::string payload;
  if (FetchFromReplica(spi, info.digest_hex, info.length, &payload)) {
    attempted = true;
    std::string err;
    if (cs->RepairChunk(info.digest_hex, payload.data(), payload.size(),
                        &err)) {
      chunks_repaired_.fetch_add(1, std::memory_order_relaxed);
      FDFS_LOG_INFO("scrub: chunk %s repaired from replica",
                    info.digest_hex.c_str());
      if (events_ != nullptr)
        events_->Record(EventSeverity::kInfo, "chunk.repaired",
                        info.digest_hex,
                        "spi=" + std::to_string(spi) + " by=replica");
    } else {
      status = 5 /*EIO*/;
      corrupt_unrepairable_.fetch_add(1, std::memory_order_relaxed);
      FDFS_LOG_ERROR("scrub: chunk %s repair write failed: %s",
                     info.digest_hex.c_str(), err.c_str());
      if (events_ != nullptr)
        events_->Record(EventSeverity::kError, "chunk.unrepairable",
                        info.digest_hex, "spi=" + std::to_string(spi) +
                                             " reason=repair_write_failed");
    }
  } else {
    attempted = true;
    status = 2 /*ENOENT*/;
    corrupt_unrepairable_.fetch_add(1, std::memory_order_relaxed);
    FDFS_LOG_ERROR("scrub: chunk %s unrepairable — no replica serves it "
                   "(stays quarantined; downloads of its files will fail "
                   "rather than return bad bytes)",
                   info.digest_hex.c_str());
    if (events_ != nullptr)
      events_->Record(EventSeverity::kError, "chunk.unrepairable",
                      info.digest_hex,
                      "spi=" + std::to_string(spi) + " reason=no_replica");
  }
  if (attempted && trace_ != nullptr && pass_ctx_.valid()) {
    TraceSpan s;
    s.trace_id = pass_ctx_.trace_id;
    s.span_id = trace_->NextSpanId();
    s.parent_id = pass_ctx_.parent_span;
    s.start_us = t0;
    s.dur_us = TraceWallUs() - t0;
    s.status = status;
    s.flags = kTraceFlagSampled;
    s.SetName("scrub.repair");
    trace_->Record(s);
  }
}

bool ScrubManager::FetchFromReplica(int spi, const std::string& digest_hex,
                                    int64_t len, std::string* out) {
  if (len <= 0 || peers_ == nullptr) return false;
  char remote[16];
  // FETCH_CHUNK routes by the "Mxx/" prefix of the remote name; the
  // scrubber has no file name for a chunk, only its address, so a
  // synthetic name carries the store-path index.
  snprintf(remote, sizeof(remote), "M%02X/scrub", spi);
  std::string body;
  PutFixedField(&body, group_name_, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(strlen(remote)), num);
  body.append(reinterpret_cast<char*>(num), 8);
  body += remote;
  PutInt64BE(1, num);
  body.append(reinterpret_cast<char*>(num), 8);
  if (!HexToBytes(digest_hex, &body)) return false;
  PutInt64BE(len, num);
  body.append(reinterpret_cast<char*>(num), 8);

  for (const std::string& addr : peers_()) {
    size_t colon = addr.rfind(':');
    if (colon == std::string::npos) continue;
    std::string err;
    int fd = TcpConnect(addr.substr(0, colon),
                        atoi(addr.c_str() + colon + 1), 3000, &err);
    if (fd < 0) continue;
    std::string resp;
    uint8_t status = 0;
    bool ok = NetRpc(fd, static_cast<uint8_t>(StorageCmd::kFetchChunk), body,
                     &resp, &status, len + 1024, kRpcTimeoutMs);
    close(fd);
    if (!ok || status != 0 ||
        static_cast<int64_t>(resp.size()) != len)
      continue;
    // Trust nothing off the wire: the replica may carry the same rot.
    if (Sha1(resp.data(), resp.size()).Hex() != digest_hex) {
      FDFS_LOG_WARN("scrub: replica %s served a mismatched payload for %s",
                    addr.c_str(), digest_hex.c_str());
      continue;
    }
    out->swap(resp);
    return true;
  }
  return false;
}

}  // namespace fdfs
