#include "storage/scrub.h"

#include <string.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>

#include "common/bytes.h"
#include "common/eventlog.h"
#include "common/jumphash.h"
#include "common/log.h"
#include "common/threadreg.h"
#include "common/net.h"
#include "storage/ecstore.h"

namespace fdfs {

namespace {

constexpr int kRpcTimeoutMs = 10000;
// Verify batch bounds: enough chunks per sidecar round-trip to amortize
// the RPC, small enough that a batch never holds more than a few MB.
constexpr size_t kBatchChunks = 64;
constexpr int64_t kBatchBytes = 4 << 20;
// Demote batch bounds: a stripe wants enough chunks that the k-way
// split does not degenerate, but one batch must never pin more than a
// few MB of payloads in memory while encoding.
constexpr size_t kEcBatchChunks = 512;
constexpr int64_t kEcBatchBytes = 4 << 20;

int64_t WallUs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

// Jump-hash key for demote ownership: the first 8 raw digest bytes.
// Every group member derives the same key from the same digest, so the
// sorted-member-list jump hash names exactly one demoter per chunk.
uint64_t DigestOwnerKey(const std::string& digest_hex) {
  std::string raw;
  if (digest_hex.size() < 16 ||
      !HexToBytes(std::string_view(digest_hex).substr(0, 16), &raw) ||
      raw.size() != 8)
    return 0;
  uint64_t key = 0;
  for (int i = 0; i < 8; ++i)
    key = (key << 8) | static_cast<uint8_t>(raw[i]);
  return key;
}

}  // namespace

ScrubManager::ScrubManager(ScrubOptions opts, std::string group_name,
                           std::vector<ChunkStore*> chunk_stores,
                           PeerListFn peers, DedupPlugin* plugin,
                           TraceRing* trace, EventLog* events)
    : opts_(opts), group_name_(std::move(group_name)),
      stores_(std::move(chunk_stores)), peers_(std::move(peers)),
      plugin_(plugin), trace_(trace), events_(events) {}

ScrubManager::~ScrubManager() { Stop(); }

void ScrubManager::Start() {
  thread_ = std::thread(&ScrubManager::ThreadMain, this);
}

void ScrubManager::Stop() {
  {
    std::lock_guard<RankedMutex> lk(mu_);
    if (stop_) return;
    stop_ = true;
  }
  cv_.notify_all();
  if (thread_.joinable()) thread_.join();
}

void ScrubManager::Kick() {
  {
    std::lock_guard<RankedMutex> lk(mu_);
    kicked_ = true;
  }
  cv_.notify_all();
}

void ScrubManager::EcKick() {
  ec_kicked_ = true;
  Kick();
}

void ScrubManager::NoteRecipeReclaimed(int64_t bytes) {
  recipes_reclaimed_.fetch_add(1, std::memory_order_relaxed);
  bytes_reclaimed_.fetch_add(bytes, std::memory_order_relaxed);
}

void ScrubManager::FillStats(int64_t* out) const {
  static_assert(kScrubStatCount == 18, "update StatValue + protocol.py");
  for (int i = 0; i < kScrubStatCount; ++i) out[i] = StatValue(i);
}

int64_t ScrubManager::StatValue(int i) const {
  switch (i) {  // kScrubStatNames order
    case 0: return running_.load() ? 1 : 0;
    case 1: return passes_.load();
    case 2: return pass_chunks_done_.load();
    case 3: return pass_chunks_total_.load();
    case 4: return chunks_verified_.load();
    case 5: return bytes_verified_.load();
    case 6: return chunks_corrupt_.load();
    case 7: return chunks_repaired_.load();
    case 8: return corrupt_unrepairable_.load();
    case 9: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->quarantined_chunks();
      return n;
    }
    case 10: return skipped_pinned_.load();
    case 11: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->gc_pending_chunks();
      return n;
    }
    case 12: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->gc_pending_bytes();
      return n;
    }
    case 13: return chunks_reclaimed_.load();
    case 14: return bytes_reclaimed_.load();
    case 15: return recipes_reclaimed_.load();
    case 16: return last_pass_unix_.load();
    case 17: return last_pass_dur_us_.load();
    default: return 0;
  }
}

void ScrubManager::FillEcStats(int64_t* out) const {
  static_assert(kEcStatCount == 16, "update EcStatValue + protocol.py");
  for (int i = 0; i < kEcStatCount; ++i) out[i] = EcStatValue(i);
}

int64_t ScrubManager::EcStatValue(int i) const {
  switch (i) {  // kEcStatNames order
    case 0: {
      for (ChunkStore* cs : stores_)
        if (cs->ec_enabled()) return 1;
      return 0;
    }
    case 1: return opts_.ec_k;
    case 2: return opts_.ec_m;
    case 3: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->ec_stripes();
      return n;
    }
    case 4: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->ec_stripe_chunks();
      return n;
    }
    case 5: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->ec_data_bytes();
      return n;
    }
    case 6: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->ec_parity_bytes();
      return n;
    }
    case 7: return ec_demoted_chunks_.load();
    case 8: return ec_demoted_bytes_.load();
    case 9: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->released_chunks();
      return n;
    }
    case 10: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->released_bytes();
      return n;
    }
    case 11: return ec_reconstructed_shards_.load();
    case 12: return ec_reconstructed_bytes_.load();
    case 13: return ec_repair_fallback_chunks_.load();
    case 14: {
      int64_t n = 0;
      for (ChunkStore* cs : stores_) n += cs->ec_remote_reads();
      return n;
    }
    case 15: return ec_last_demote_unix_.load();
    default: return 0;
  }
}

void ScrubManager::ThreadMain() {
  ScopedThreadName ledger("scrub");
  std::unique_lock<RankedMutex> lk(mu_);
  while (!stop_) {
    BeatThreadHeartbeat();
    // Waits are sliced to <= 1s so the thread heartbeat stays fresh for
    // the watchdog (threadreg.h): an idle scrubber parked on its cv for
    // a day must not read as stalled.  due = the FULL interval elapsed
    // without a kick (same semantics as the old single wait_for).
    bool due;
    if (opts_.interval_s > 0) {
      due = true;
      for (int64_t waited_s = 0; waited_s < opts_.interval_s; ++waited_s) {
        if (cv_.wait_for(lk, std::chrono::seconds(1),
                         [this] { return stop_ || kicked_; })) {
          due = false;
          break;
        }
        BeatThreadHeartbeat();
      }
    } else {
      while (!cv_.wait_for(lk, std::chrono::seconds(1),
                           [this] { return stop_ || kicked_; }))
        BeatThreadHeartbeat();
      due = false;
    }
    if (stop_) return;
    due = due || kicked_;
    kicked_ = false;
    if (!due) continue;
    lk.unlock();
    RunPass();
    lk.lock();
  }
}

void ScrubManager::Pace(int64_t bytes_read, int64_t pass_start_us) {
  if (opts_.bandwidth_bytes_s <= 0) return;
  // Token bucket: the pass may only be `bytes_read / bw` seconds old.
  // Divide before scaling to microseconds — bytes_read is cumulative
  // over the pass, and `bytes * 1e6` would overflow int64 at ~9.2 TB
  // (a plausible store), silently disabling pacing.
  int64_t bw = opts_.bandwidth_bytes_s;
  int64_t budget_us =
      bytes_read / bw * 1000000 + (bytes_read % bw) * 1000000 / bw;
  int64_t ahead_us = budget_us - (WallUs() - pass_start_us);
  while (ahead_us > 0) {
    {
      std::lock_guard<RankedMutex> lk(mu_);
      if (stop_) return;
    }
    BeatThreadHeartbeat();  // pacing sleep, not a stall
    usleep(static_cast<useconds_t>(std::min<int64_t>(ahead_us, 50000)));
    ahead_us = budget_us - (WallUs() - pass_start_us);
  }
}

void ScrubManager::PaceEc(int64_t bytes, int64_t pass_start_us) {
  if (opts_.ec_bandwidth_bytes_s <= 0) return;
  int64_t bw = opts_.ec_bandwidth_bytes_s;
  int64_t budget_us = bytes / bw * 1000000 + (bytes % bw) * 1000000 / bw;
  int64_t ahead_us = budget_us - (WallUs() - pass_start_us);
  while (ahead_us > 0) {
    {
      std::lock_guard<RankedMutex> lk(mu_);
      if (stop_) return;
    }
    BeatThreadHeartbeat();  // pacing sleep, not a stall
    usleep(static_cast<useconds_t>(std::min<int64_t>(ahead_us, 50000)));
    ahead_us = budget_us - (WallUs() - pass_start_us);
  }
}

void ScrubManager::RunPass() {
  running_ = true;
  int64_t start_us = WallUs();
  pass_chunks_done_ = 0;
  pass_chunks_total_ = 0;
  pass_ctx_ = TraceCtx{};
  uint32_t root_span = 0;
  if (trace_ != nullptr) {
    pass_ctx_.trace_id = trace_->NewTraceId();
    pass_ctx_.flags = kTraceFlagSampled;
    root_span = trace_->NextSpanId();
    pass_ctx_.parent_span = root_span;
  }

  // The progress denominator is the live-chunk count at pass start
  // (approximate under churn — uploads and deletes move it).
  for (ChunkStore* cs : stores_)
    pass_chunks_total_ += cs->unique_chunks();

  int64_t paced = 0;
  int64_t ec_paced = 0;
  // EC_KICK's one-shot age-gate override is consumed ONCE per pass,
  // before the store loop, so every store path demotes under it.
  int64_t ec_age =
      ec_kicked_.exchange(false) ? 0 : opts_.ec_demote_age_s;
  bool aborted = false;
  for (size_t spi = 0; spi < stores_.size() && !aborted; ++spi) {
    ChunkStore* cs = stores_[spi];
    // Repair-retry targets from EARLIER passes, snapshotted before the
    // verify stage so a chunk quarantined in this pass (whose repair
    // already ran in HandleCorrupt) is not attempted twice per pass.
    auto retry = cs->SnapshotQuarantined();
    // Walk the store in 256 digest-prefix slices: each slice is one
    // short, allocation-light scan under a single stripe lock (slice
    // prefix pins the stripe since the PR 5 sharding — the scrubber
    // never contends with more than 1/16 of the foreground traffic),
    // and a many-million-chunk store never holds a full snapshot
    // resident across an hours-long paced pass.
    for (int prefix = 0; prefix < 256 && !aborted; ++prefix) {
      auto live = cs->SnapshotLive(prefix);
      size_t i = 0;
      while (i < live.size()) {
        BeatThreadHeartbeat();  // verifying at full speed, not stalled
        {
          std::lock_guard<RankedMutex> lk(mu_);
          if (stop_) {
            aborted = true;
            break;
          }
        }
        // One bounded batch: read payloads, then verify them together.
        std::vector<ChunkStore::ChunkInfo> batch;
        std::vector<std::string> payloads;
        std::vector<char> bad;
        int64_t batch_bytes = 0;
        while (i < live.size() && batch.size() < kBatchChunks &&
               batch_bytes < kBatchBytes) {
          const auto& info = live[i++];
          // Demoted chunks are NOT re-verified through the transparent
          // decode path: each such read rebuilds its whole stripe (k
          // shard reads + an RS decode per CHUNK — quadratic over a
          // stripe's chunks, and unpaced).  Their integrity engine is
          // stage 5: VerifyRepairStripe CRCs every shard (header +
          // payload) and repairs from parity under the ec bandwidth
          // budget.
          if (cs->ec_enabled() && cs->ec()->Has(info.digest_hex)) {
            pass_chunks_done_.fetch_add(1, std::memory_order_relaxed);
            continue;
          }
          batch.push_back(info);
          payloads.emplace_back();
          // A missing or short chunk file is corruption too (truncation,
          // lost write) — mark it bad without a digest round.
          bad.push_back(
              cs->ReadChunk(info.digest_hex, info.length, &payloads.back())
                  ? 0 : 1);
          batch_bytes += info.length;
        }
        paced += batch_bytes;
        Pace(paced, start_us);
        VerifyBatch(static_cast<int>(spi), batch, payloads, &bad);
        for (size_t b = 0; b < batch.size(); ++b)
          if (bad[b]) HandleCorrupt(static_cast<int>(spi), batch[b]);
        chunks_verified_.fetch_add(static_cast<int64_t>(batch.size()),
                                   std::memory_order_relaxed);
        bytes_verified_.fetch_add(batch_bytes, std::memory_order_relaxed);
        pass_chunks_done_.fetch_add(static_cast<int64_t>(batch.size()),
                                    std::memory_order_relaxed);
      }
    }
    if (aborted) break;

    // Repair retry: chunks quarantined by an earlier pass (no replica
    // had them then) get another chance every pass.
    for (const auto& info : retry)
      HandleCorrupt(static_cast<int>(spi), info, /*already_quarantined=*/true);

    // GC sweep: reclaim zero-ref chunks past the grace window.
    int64_t bytes = 0;
    int64_t n = cs->GcSweep(time(nullptr), &bytes);
    if (n > 0) {
      chunks_reclaimed_.fetch_add(n, std::memory_order_relaxed);
      bytes_reclaimed_.fetch_add(bytes, std::memory_order_relaxed);
      FDFS_LOG_INFO("scrub gc: reclaimed %lld chunks (%lld bytes) on "
                    "store path %zu",
                    static_cast<long long>(n),
                    static_cast<long long>(bytes), spi);
      if (events_ != nullptr) {
        char key[8], detail[64];
        snprintf(key, sizeof(key), "M%02zX", spi);
        snprintf(detail, sizeof(detail), "chunks=%lld bytes=%lld",
                 static_cast<long long>(n), static_cast<long long>(bytes));
        events_->Record(EventSeverity::kInfo, "gc.sweep", key, detail);
      }
    }

    // Slab compaction (ISSUE 9): right after GC marked slots dead, copy
    // the live records out of the deadest slabs and unlink them —
    // paced by the SAME token bucket as verify reads, so compaction IO
    // never starves foreground traffic either.  Records that fail the
    // copy-time re-verify come back here and ride the standard
    // quarantine -> replica-repair machinery (HandleCorrupt marks the
    // slot dead, so the next pass finishes the slab).
    std::vector<ChunkStore::ChunkInfo> slab_corrupt;
    int64_t slab_reclaimed = 0;
    int64_t compacted = cs->CompactSlabs(
        [&](int64_t b) {
          paced += b;
          Pace(paced, start_us);
        },
        [this]() {
          std::lock_guard<RankedMutex> lk(mu_);
          return stop_;
        },
        &slab_corrupt, &slab_reclaimed);
    for (const auto& info : slab_corrupt)
      HandleCorrupt(static_cast<int>(spi), info);
    if (compacted > 0)
      FDFS_LOG_INFO("scrub: compacted %lld slabs on store path %zu "
                    "(%lld bytes reclaimed)",
                    static_cast<long long>(compacted), spi,
                    static_cast<long long>(slab_reclaimed));

    // Stage 5 — erasure-coded cold tier (ISSUE 16).  Repair existing
    // stripes from parity FIRST (the cheapest path back to full
    // durability), then demote newly-cold chunks and hand the
    // replicated copies over for release.  Paced by the SEPARATE
    // ec_bandwidth bucket so stripe IO and verify reads do not fight
    // over one budget.
    if (cs->ec_enabled()) {
      RunEcRepair(static_cast<int>(spi), start_us, &ec_paced);
      RunEcDemote(static_cast<int>(spi), ec_age, start_us, &ec_paced);
    }
  }

  int64_t dur = WallUs() - start_us;
  if (!aborted) {
    passes_.fetch_add(1, std::memory_order_relaxed);
    last_pass_unix_ = time(nullptr);
    last_pass_dur_us_ = dur;
  }
  if (trace_ != nullptr && pass_ctx_.valid()) {
    TraceSpan s;
    s.trace_id = pass_ctx_.trace_id;
    s.span_id = root_span;
    s.parent_id = 0;
    s.start_us = TraceWallUs() - dur;
    s.dur_us = dur;
    s.status = aborted ? 4 /*EINTR*/ : 0;
    s.flags = kTraceFlagSampled;
    s.SetName("scrub.pass");
    trace_->Record(s);
  }
  running_ = false;
}

void ScrubManager::VerifyBatch(
    int spi, const std::vector<ChunkStore::ChunkInfo>& infos,
    const std::vector<std::string>& payloads, std::vector<char>* bad) {
  (void)spi;
  // Sidecar first: one DEDUP_VERIFY RPC hashes the whole batch with
  // ops/sha1.sha1_batch on the accelerator.  Unreadable entries are
  // already marked and excluded from the RPC.
  if (plugin_ != nullptr) {
    std::vector<ChunkFp> want;
    std::string concat;
    std::vector<size_t> idx;
    for (size_t i = 0; i < infos.size(); ++i) {
      if ((*bad)[i]) continue;
      ChunkFp fp;
      fp.length = infos[i].length;
      fp.digest_hex = infos[i].digest_hex;
      want.push_back(std::move(fp));
      concat += payloads[i];
      idx.push_back(i);
    }
    std::string mask;
    if (!want.empty() && plugin_->VerifyChunks(want, concat, &mask) &&
        mask.size() == want.size()) {
      for (size_t k = 0; k < idx.size(); ++k)
        if (mask[k]) (*bad)[idx[k]] = 1;
      return;
    }
  }
  // Serial host path (SHA-NI when the CPU has it).
  for (size_t i = 0; i < infos.size(); ++i) {
    if ((*bad)[i]) continue;
    if (Sha1(payloads[i].data(), payloads[i].size()).Hex() !=
        infos[i].digest_hex)
      (*bad)[i] = 1;
  }
}

void ScrubManager::HandleCorrupt(int spi, const ChunkStore::ChunkInfo& info,
                                 bool already_quarantined) {
  ChunkStore* cs = stores_[spi];
  int64_t t0 = TraceWallUs();
  int status = 0;
  bool attempted = false;
  if (already_quarantined && !cs->IsQuarantined(info.digest_hex))
    return;  // healed (re-upload/repair) since the retry snapshot
  if (!already_quarantined) {
    switch (cs->Quarantine(info.digest_hex)) {
      case ChunkStore::QuarantineResult::kGone:
        return;  // deleted since the snapshot — nothing was corrupt
      case ChunkStore::QuarantineResult::kClean:
        // False alarm: the lock-free verify read raced a delete +
        // re-upload; the authoritative under-lock re-hash is clean.
        return;
      case ChunkStore::QuarantineResult::kPinned:
        // An in-flight stream or upload session still holds the chunk:
        // repair-in-place under a reader is unsafe; retry next pass.
        chunks_corrupt_.fetch_add(1, std::memory_order_relaxed);
        skipped_pinned_.fetch_add(1, std::memory_order_relaxed);
        FDFS_LOG_WARN("scrub: corrupt chunk %s is pinned by an in-flight "
                      "stream; retrying next pass",
                      info.digest_hex.c_str());
        return;
      case ChunkStore::QuarantineResult::kQuarantined:
        chunks_corrupt_.fetch_add(1, std::memory_order_relaxed);
        FDFS_LOG_WARN("scrub: chunk %s failed verification on store path "
                      "%d — quarantined",
                      info.digest_hex.c_str(), spi);
        if (events_ != nullptr)
          events_->Record(EventSeverity::kWarn, "chunk.quarantined",
                          info.digest_hex,
                          "spi=" + std::to_string(spi) +
                              " bytes=" + std::to_string(info.length));
        break;
    }
  }
  std::string payload;
  if (FetchFromReplica(spi, info.digest_hex, info.length, &payload)) {
    attempted = true;
    std::string err;
    if (cs->RepairChunk(info.digest_hex, payload.data(), payload.size(),
                        &err)) {
      chunks_repaired_.fetch_add(1, std::memory_order_relaxed);
      FDFS_LOG_INFO("scrub: chunk %s repaired from replica",
                    info.digest_hex.c_str());
      if (events_ != nullptr)
        events_->Record(EventSeverity::kInfo, "chunk.repaired",
                        info.digest_hex,
                        "spi=" + std::to_string(spi) + " by=replica");
    } else {
      status = 5 /*EIO*/;
      corrupt_unrepairable_.fetch_add(1, std::memory_order_relaxed);
      FDFS_LOG_ERROR("scrub: chunk %s repair write failed: %s",
                     info.digest_hex.c_str(), err.c_str());
      if (events_ != nullptr)
        events_->Record(EventSeverity::kError, "chunk.unrepairable",
                        info.digest_hex, "spi=" + std::to_string(spi) +
                                             " reason=repair_write_failed");
    }
  } else {
    attempted = true;
    status = 2 /*ENOENT*/;
    corrupt_unrepairable_.fetch_add(1, std::memory_order_relaxed);
    FDFS_LOG_ERROR("scrub: chunk %s unrepairable — no replica serves it "
                   "(stays quarantined; downloads of its files will fail "
                   "rather than return bad bytes)",
                   info.digest_hex.c_str());
    if (events_ != nullptr)
      events_->Record(EventSeverity::kError, "chunk.unrepairable",
                      info.digest_hex,
                      "spi=" + std::to_string(spi) + " reason=no_replica");
  }
  if (attempted && trace_ != nullptr && pass_ctx_.valid()) {
    TraceSpan s;
    s.trace_id = pass_ctx_.trace_id;
    s.span_id = trace_->NextSpanId();
    s.parent_id = pass_ctx_.parent_span;
    s.start_us = t0;
    s.dur_us = TraceWallUs() - t0;
    s.status = status;
    s.flags = kTraceFlagSampled;
    s.SetName("scrub.repair");
    trace_->Record(s);
  }
}

bool ScrubManager::FetchFromReplica(int spi, const std::string& digest_hex,
                                    int64_t len, std::string* out) {
  if (len <= 0 || peers_ == nullptr) return false;
  char remote[16];
  // FETCH_CHUNK routes by the "Mxx/" prefix of the remote name; the
  // scrubber has no file name for a chunk, only its address, so a
  // synthetic name carries the store-path index.
  snprintf(remote, sizeof(remote), "M%02X/scrub", spi);
  std::string body;
  PutFixedField(&body, group_name_, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(strlen(remote)), num);
  body.append(reinterpret_cast<char*>(num), 8);
  body += remote;
  PutInt64BE(1, num);
  body.append(reinterpret_cast<char*>(num), 8);
  if (!HexToBytes(digest_hex, &body)) return false;
  PutInt64BE(len, num);
  body.append(reinterpret_cast<char*>(num), 8);

  for (const std::string& addr : peers_()) {
    size_t colon = addr.rfind(':');
    if (colon == std::string::npos) continue;
    std::string err;
    int fd = TcpConnect(addr.substr(0, colon),
                        atoi(addr.c_str() + colon + 1), 3000, &err);
    if (fd < 0) continue;
    std::string resp;
    uint8_t status = 0;
    bool ok = NetRpc(fd, static_cast<uint8_t>(StorageCmd::kFetchChunk), body,
                     &resp, &status, len + 1024, kRpcTimeoutMs);
    close(fd);
    if (!ok || status != 0 ||
        static_cast<int64_t>(resp.size()) != len)
      continue;
    // Trust nothing off the wire: the replica may carry the same rot.
    if (Sha1(resp.data(), resp.size()).Hex() != digest_hex) {
      FDFS_LOG_WARN("scrub: replica %s served a mismatched payload for %s",
                    addr.c_str(), digest_hex.c_str());
      continue;
    }
    out->swap(resp);
    return true;
  }
  return false;
}

void ScrubManager::RunEcRepair(int spi, int64_t pass_start_us,
                               int64_t* ec_paced) {
  ChunkStore* cs = stores_[spi];
  EcStore* ec = cs->ec();
  if (ec == nullptr) return;
  for (int64_t id : ec->StripeIds()) {
    {
      std::lock_guard<RankedMutex> lk(mu_);
      if (stop_) return;
    }
    std::vector<EcStore::ChunkRef> lost;
    int64_t rebuilt = 0, rebuilt_bytes = 0, bytes_read = 0;
    EcStore::StripeHealth h =
        ec->VerifyRepairStripe(id, &lost, &rebuilt, &rebuilt_bytes,
                               &bytes_read);
    if (h == EcStore::StripeHealth::kRepaired) {
      // Publish the counters BEFORE paying the bandwidth debt: the
      // rebuilt shards are already durable on disk, and a paced sleep
      // here would leave EC_STATUS under-reporting finished repairs
      // for seconds.
      ec_reconstructed_shards_.fetch_add(rebuilt,
                                         std::memory_order_relaxed);
      ec_reconstructed_bytes_.fetch_add(rebuilt_bytes,
                                        std::memory_order_relaxed);
      FDFS_LOG_INFO("scrub ec: stripe %lld on store path %d rebuilt "
                    "%lld shards (%lld bytes) from parity",
                    static_cast<long long>(id), spi,
                    static_cast<long long>(rebuilt),
                    static_cast<long long>(rebuilt_bytes));
    }
    *ec_paced += bytes_read + rebuilt_bytes;
    PaceEc(*ec_paced, pass_start_us);
    if (h == EcStore::StripeHealth::kLost) {
      // More than m shards gone: parity cannot help.  Re-promote every
      // live chunk to the replicated tier via FETCH_CHUNK (the released
      // peers fall through to OTHER stripes or remote owners), and only
      // drop the carcass once every chunk is safe again.
      FDFS_LOG_ERROR("scrub ec: stripe %lld on store path %d lost more "
                     "than %d shards — re-promoting %zu chunks from "
                     "replicas",
                     static_cast<long long>(id), spi, ec->m(),
                     lost.size());
      bool all_recovered = true;
      for (const EcStore::ChunkRef& ref : lost) {
        {
          std::lock_guard<RankedMutex> lk(mu_);
          if (stop_) return;
        }
        std::string payload;
        std::string err;
        if (!FetchFromReplica(spi, ref.digest_hex, ref.length, &payload)) {
          all_recovered = false;
          corrupt_unrepairable_.fetch_add(1, std::memory_order_relaxed);
          FDFS_LOG_ERROR("scrub ec: chunk %s unrecoverable — stripe lost "
                         "and no replica serves it",
                         ref.digest_hex.c_str());
          if (events_ != nullptr)
            events_->Record(EventSeverity::kError, "ec.chunk_lost",
                            ref.digest_hex,
                            "spi=" + std::to_string(spi) +
                                " stripe=" + std::to_string(id));
          continue;
        }
        *ec_paced += ref.length;
        PaceEc(*ec_paced, pass_start_us);
        if (cs->RepairChunk(ref.digest_hex, payload.data(), payload.size(),
                            &err)) {
          ec_repair_fallback_chunks_.fetch_add(1,
                                               std::memory_order_relaxed);
          if (events_ != nullptr)
            events_->Record(EventSeverity::kWarn, "ec.chunk_repromoted",
                            ref.digest_hex,
                            "spi=" + std::to_string(spi) +
                                " stripe=" + std::to_string(id));
        } else if (err == "no longer referenced") {
          // Deleted since the stripe was cut — nothing left to save.
        } else {
          all_recovered = false;
          corrupt_unrepairable_.fetch_add(1, std::memory_order_relaxed);
          FDFS_LOG_ERROR("scrub ec: chunk %s re-promotion write failed: %s",
                         ref.digest_hex.c_str(), err.c_str());
        }
      }
      if (all_recovered) {
        int64_t reclaimed = 0;
        ec->DropStripe(id, &reclaimed);
        FDFS_LOG_INFO("scrub ec: dropped lost stripe %lld (%lld bytes) — "
                      "all chunks re-promoted",
                      static_cast<long long>(id),
                      static_cast<long long>(reclaimed));
      }
    }
  }
}

void ScrubManager::RunEcDemote(int spi, int64_t age_s, int64_t pass_start_us,
                               int64_t* ec_paced) {
  ChunkStore* cs = stores_[spi];
  EcStore* ec = cs->ec();
  if (ec == nullptr) return;

  // Replay the release debt from an earlier pass (or a crash between
  // demote and handover) BEFORE taking on more: release.map is cleared
  // only once every peer answered, and the release RPC is idempotent.
  auto pending = ec->PendingReleases();
  if (!pending.empty()) {
    if (!SendReleaseToPeers(spi, pending)) {
      FDFS_LOG_WARN("scrub ec: %zu pending releases not delivered to all "
                    "peers; retrying next pass",
                    pending.size());
      return;  // peers down — do not grow the debt
    }
    ec->ClearReleaseMap();
  }
  if (ec->k() <= 0) return;  // drained geometry: repairs only

  auto cands = cs->SnapshotDemotable(time(nullptr), age_s);
  if (cands.empty()) return;

  // Exactly one group member demotes a given digest: jump hash over the
  // SORTED member list (everyone computes the same list from the same
  // peer set, so ownership is consistent without coordination).
  std::vector<std::string> members =
      peers_ != nullptr ? peers_() : std::vector<std::string>();
  members.push_back(opts_.self_id);
  std::sort(members.begin(), members.end());
  members.erase(std::unique(members.begin(), members.end()), members.end());
  int32_t self_idx = static_cast<int32_t>(
      std::find(members.begin(), members.end(), opts_.self_id) -
      members.begin());
  int32_t n = static_cast<int32_t>(members.size());

  bool peers_ok = true;
  std::vector<ChunkStore::ChunkInfo> batch;
  int64_t batch_bytes = 0;
  auto flush = [&]() {
    if (batch.empty()) return;
    *ec_paced += batch_bytes;
    PaceEc(*ec_paced, pass_start_us);
    int64_t nchunks = 0, nbytes = 0;
    std::string err;
    int64_t id = cs->DemoteToEc(batch, &nchunks, &nbytes, &err);
    if (id < 0) {
      FDFS_LOG_WARN("scrub ec: demote batch (%zu chunks) failed on store "
                    "path %d: %s",
                    batch.size(), spi, err.c_str());
      batch.clear();
      batch_bytes = 0;
      return;
    }
    ec_demoted_chunks_.fetch_add(nchunks, std::memory_order_relaxed);
    ec_demoted_bytes_.fetch_add(nbytes, std::memory_order_relaxed);
    ec_last_demote_unix_ = time(nullptr);
    FDFS_LOG_INFO("scrub ec: demoted %lld chunks (%lld bytes) into stripe "
                  "%lld on store path %d",
                  static_cast<long long>(nchunks),
                  static_cast<long long>(nbytes),
                  static_cast<long long>(id), spi);
    if (events_ != nullptr)
      events_->Record(EventSeverity::kInfo, "ec.demoted",
                      "M" + std::to_string(spi),
                      "stripe=" + std::to_string(id) +
                          " chunks=" + std::to_string(nchunks) +
                          " bytes=" + std::to_string(nbytes));
    // Verify-then-release: only chunks the EC tier actually holds may
    // lose their replicas (DemoteToEc skips vanished/corrupt entries —
    // releasing those would orphan the only good copies).
    std::vector<std::pair<std::string, int64_t>> rel;
    for (const ChunkStore::ChunkInfo& info : batch)
      if (ec->Has(info.digest_hex))
        rel.emplace_back(info.digest_hex, info.length);
    if (!rel.empty()) {
      std::string jerr;
      if (!ec->AppendReleaseMap(rel, &jerr)) {
        // No journal, no release: peers keep their replicas (pure
        // over-replication — safe, reclaimed once the map writes again).
        FDFS_LOG_ERROR("scrub ec: release.map append failed: %s — "
                       "replicas kept",
                       jerr.c_str());
      } else if (!peers_ok) {
        // A peer already failed this pass: journal the debt and let the
        // next pass's replay deliver it.
      } else if (SendReleaseToPeers(spi, rel)) {
        ec->ClearReleaseMap();
      } else {
        peers_ok = false;
      }
    }
    batch.clear();
    batch_bytes = 0;
  };

  for (const ChunkStore::ChunkInfo& info : cands) {
    {
      std::lock_guard<RankedMutex> lk(mu_);
      if (stop_) return;
    }
    if (n > 1 && JumpHash(DigestOwnerKey(info.digest_hex), n) != self_idx)
      continue;
    batch.push_back(info);
    batch_bytes += info.length;
    if (batch.size() >= kEcBatchChunks || batch_bytes >= kEcBatchBytes)
      flush();
  }
  flush();
}

bool ScrubManager::SendReleaseToPeers(
    int spi, const std::vector<std::pair<std::string, int64_t>>& batch) {
  (void)spi;  // releases are digest-addressed; the peer finds the store
  if (batch.empty()) return true;
  std::vector<std::string> addrs =
      peers_ != nullptr ? peers_() : std::vector<std::string>();
  if (addrs.empty()) return true;  // single-node group: nothing to drop
  std::string body;
  PutFixedField(&body, group_name_, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(batch.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  for (const auto& chunk : batch) {
    if (!HexToBytes(chunk.first, &body)) return false;
    PutInt64BE(chunk.second, num);
    body.append(reinterpret_cast<char*>(num), 8);
  }
  bool all = true;
  for (const std::string& addr : addrs) {
    size_t colon = addr.rfind(':');
    if (colon == std::string::npos) {
      all = false;
      continue;
    }
    std::string err;
    int fd = TcpConnect(addr.substr(0, colon),
                        atoi(addr.c_str() + colon + 1), 3000, &err);
    if (fd < 0) {
      all = false;
      FDFS_LOG_WARN("scrub ec: release peer %s unreachable: %s",
                    addr.c_str(), err.c_str());
      continue;
    }
    std::string resp;
    uint8_t status = 0;
    bool ok = NetRpc(fd, static_cast<uint8_t>(StorageCmd::kEcRelease), body,
                     &resp, &status,
                     static_cast<int64_t>(batch.size()) + 1024,
                     kRpcTimeoutMs);
    close(fd);
    if (!ok || status != 0 || resp.size() != batch.size()) {
      all = false;
      FDFS_LOG_WARN("scrub ec: release to %s failed (status=%d)",
                    addr.c_str(), static_cast<int>(status));
      continue;
    }
    int64_t kept = 0;
    for (char c : resp) kept += (c != 0) ? 1 : 0;
    if (kept > 0)
      // Pinned/quarantined chunks the peer retained keep full-replica
      // coverage there; the owner's stripe is redundant for them, which
      // is safe (over-replication, not exposure).
      FDFS_LOG_INFO("scrub ec: peer %s kept %lld of %zu released chunks",
                    addr.c_str(), static_cast<long long>(kept),
                    batch.size());
  }
  return all;
}

}  // namespace fdfs
