// fdfs_storaged — storage daemon launcher.
//
// Reference: storage/fdfs_storaged.c:main() — conf load, storage_func_init,
// service init, accept loop; SIGUSR1 state dump (storage_dump.c), SIGINT/
// SIGTERM graceful stop.  Usage: fdfs_storaged <storage.conf> [foreground]
#include <signal.h>

#include <cstdio>
#include <cstring>

#include "common/ini.h"
#include "common/fsutil.h"
#include "common/log.h"
#include "storage/config.h"
#include "storage/server.h"

static fdfs::StorageServer* g_server = nullptr;
// Handlers only set flags (async-signal-safe); the event loop polls them.
static volatile sig_atomic_t g_stop_flag = 0;
static volatile sig_atomic_t g_dump_flag = 0;

static void OnSignal(int sig) {
  if (sig == SIGUSR1) {
    g_dump_flag = 1;
  } else {
    g_stop_flag = 1;
  }
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <storage.conf>\n", argv[0]);
    return 2;
  }
  fdfs::IniConfig ini;
  std::string err;
  if (!ini.LoadFile(argv[1], &err)) {
    std::fprintf(stderr, "config error: %s\n", err.c_str());
    return 1;
  }
  fdfs::StorageConfig cfg;
  if (!cfg.Load(ini, &err)) {
    std::fprintf(stderr, "config error: %s\n", err.c_str());
    return 1;
  }
  if (cfg.log_level == "debug") fdfs::LogSetLevel(fdfs::LogLevel::kDebug);
  else if (cfg.log_level == "warn") fdfs::LogSetLevel(fdfs::LogLevel::kWarn);
  else if (cfg.log_level == "error") fdfs::LogSetLevel(fdfs::LogLevel::kError);
  fdfs::LogSetupFileSink(cfg.base_path, cfg.log_file, cfg.log_rotate_size);

  fdfs::StorageServer server(cfg);
  if (!server.Init(&err)) {
    std::fprintf(stderr, "init error: %s\n", err.c_str());
    return 1;
  }
  g_server = &server;
  signal(SIGINT, OnSignal);
  signal(SIGTERM, OnSignal);
  signal(SIGUSR1, OnSignal);
  signal(SIGPIPE, SIG_IGN);
  server.loop().AddTimer(200, [&server]() {
    if (g_dump_flag) {
      g_dump_flag = 0;
      server.DumpState();
    }
    if (g_stop_flag) server.Stop();
  });
  server.Run();
  FDFS_LOG_INFO("storage daemon shut down");
  return 0;
}
