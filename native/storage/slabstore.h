// Slab-packed record store: small chunks and recipe sidecars appended
// into large slab files, with an in-memory slot index and online
// compaction (ROADMAP item 1 — the billion-file scenario).
//
// Motivation: the content-addressed chunk store burns one inode + one
// open/rename per chunk digest and a second sidecar inode per recipe,
// so a corpus of millions of 4 KB files dies on filesystem metadata
// long before it dies on bytes (SURVEY §2.3 packs small LEGACY files
// into 64 MB trunk slabs for exactly this reason; storage/trunk.{h,cc}
// reproduces that for whole files — this store brings the same idea to
// the chunk/recipe layer every modern path uses).
//
// Disk layout: <store_path>/data/slabs/<10-digit-id>.slab — a pure
// sequence of CRC-framed records, appended to the highest-id ("active")
// slab until it reaches slab_bytes, then rolled to id+1.  Each record:
//
//   off  size  field
//   0    4     magic "FSLB"
//   4    1     version (1)
//   5    1     kind (1 = chunk payload, 2 = recipe sidecar)
//   6    1     flags (bit0 = dead)
//   7    1     key length
//   8    8     alloc length BE (payload bytes reserved; == payload today)
//   16   8     payload length BE
//   24   4     payload crc32 BE
//   28   8     mtime BE (unix seconds)
//   36   4     header crc32 BE (over bytes [0,36) with flags forced 0,
//              so MarkDead's one-byte flag flip never invalidates it)
//   40   ...   key bytes, then the payload
//
// Chunks are keyed by their 40-hex digest (content address); recipes by
// their sidecar path relative to the store root.  The slot index
// (key -> {slab id, offsets, length}) is RAM-only and sharded into 16
// stripes; it is rebuilt at boot by scanning every slab's headers —
// the same no-binlog-to-diverge philosophy as ChunkStore's
// RebuildFromRecipes and the trunk allocator's ScanRebuild.  A torn
// tail (crash mid-append) fails its magic/CRC and is truncated away; a
// duplicate key (crash between a compaction/replace append and the old
// record's dead mark) resolves newest-wins, the older record re-marked
// dead.
//
// Deletes mark slots dead: one flag byte flipped in place plus RAM
// byte-accounting — slab space is never reused in place.  The paced
// background compactor (driven from the scrub pass) copies the live
// records of the deadest slab into the active slab and unlinks it;
// crash-safe because every copy is re-appended (and indexed) before
// the source record dies.  Records that fail re-verify during the copy
// are left in place and reported upward, where ChunkStore routes them
// through the existing quarantine/heal machinery.
//
// Locking: SlabStore is self-locked and calls nothing that locks.  Its
// ranks sit BETWEEN the chunk-store stripes and the read cache
// (lockrank.h): ChunkStore calls in while holding a digest stripe lock
// (rank 90), and nothing here calls back out.  mu_ (kSlabStore, 92)
// guards the active-slab fd, rollover, and per-slab accounting; the 16
// index stripes (kSlabIndex, 94) guard the key map.  Reads are
// lock-free pread against a looked-up location, with one retry when a
// compaction unlinks the source slab between lookup and open (the
// record was re-appended before the source died, so the second lookup
// always lands on live bytes).
#pragma once

#include <array>
#include <atomic>
#include <cstdint>
#include <functional>
#include <map>
#include <string>
#include <unordered_map>
#include <vector>

#include "common/lockrank.h"

namespace fdfs {

constexpr uint8_t kSlabKindChunk = 1;
constexpr uint8_t kSlabKindRecipe = 2;
constexpr size_t kSlabRecordHeaderSize = 40;
constexpr size_t kSlabKeyMaxLen = 255;

// Fixed-size header + key, as parsed off disk (codec golden surface:
// fdfs_codec slab-layout pins the byte layout cross-language).
struct SlabRecordView {
  uint8_t kind = 0;
  uint8_t flags = 0;
  std::string key;
  int64_t alloc_len = 0;
  int64_t payload_len = 0;
  uint32_t payload_crc32 = 0;
  int64_t mtime = 0;
  int64_t record_len = 0;  // header + key + alloc
};

// Encode one record (header + key + payload).  mtime is stamped by the
// caller so tests and the codec golden are deterministic.
std::string SlabEncodeRecord(uint8_t kind, const std::string& key,
                             const char* data, size_t len, int64_t mtime);
// Parse the record starting at p (avail bytes readable).  False when
// the bytes do not form a valid record (bad magic/version/CRC, short
// buffer) — the boot scan treats that as the torn tail.
bool SlabDecodeRecord(const char* p, size_t avail, SlabRecordView* out);

class SlabStore {
 public:
  // dir: <store_path>/data/slabs (created on first append).
  // slab_bytes: roll the active slab past this size (>= 1 MB enforced
  // by config).  min_dead_pct: a slab becomes a compaction victim once
  // its dead bytes reach this share of its size.
  SlabStore(std::string dir, int64_t slab_bytes, int min_dead_pct);
  ~SlabStore();

  // One slot-index entry.  payload_off points at the payload bytes;
  // record_off at the record header (where the dead flag lives).
  // mtime mirrors the record header so orphan parking can age by it
  // without a disk read (crash-safe GC grace, like flat file mtime).
  struct Slot {
    int64_t slab_id = 0;
    int64_t record_off = 0;
    int64_t payload_off = 0;
    int64_t payload_len = 0;
    int64_t mtime = 0;
  };

  // Boot: scan every slab's record headers into the slot index,
  // truncating torn tails and resolving duplicate keys newest-wins.
  // Call once before serving (ChunkStore::RebuildFromRecipes drives it).
  void ScanRebuild();

  // Append one record and publish it in the slot index.  Replace
  // semantics: an existing record under the same key is marked dead
  // (never reused in place).  durable forces an fsync before the index
  // publish — recipe appends use it to keep WriteRecipeFile's
  // durability; chunk appends do not (flat chunk writes never synced).
  bool Append(uint8_t kind, const std::string& key, const char* data,
              size_t len, bool durable, std::string* err);

  bool Has(uint8_t kind, const std::string& key) const;
  bool Lookup(uint8_t kind, const std::string& key, Slot* slot) const;
  // Full / positional payload reads (pread; one retry through a fresh
  // lookup when a compaction unlinked the slab under us).
  bool Read(uint8_t kind, const std::string& key, std::string* out) const;
  bool ReadSlice(uint8_t kind, const std::string& key, int64_t offset,
                 int64_t len, char* dst) const;

  // One request of a vectored slice batch (ISSUE 18): [offset,
  // offset+len) of key's payload lands in dst.  The key pointer is
  // borrowed for the call.
  struct SliceRead {
    const std::string* key = nullptr;
    int64_t offset = 0;
    int64_t len = 0;
    char* dst = nullptr;
  };
  // Vectored positional reads for one response round: requests group by
  // slab file, sort by file offset, and offset-contiguous runs (small
  // inter-record gaps — header + key — bridged through a scrap buffer)
  // coalesce into ONE preadv each.  Per-request outcomes land in ok[n];
  // a request whose lookup or preadv raced a compaction simply reports
  // ok[i] = false here and retries through the per-request ReadSlice
  // path (same fresh-lookup semantics as Read).  *batches accumulates
  // preadv syscalls issued, *vec_spans the requests a successful preadv
  // served — the dio.preadv_* counter feed.
  void ReadSlices(uint8_t kind, const SliceRead* reqs, size_t n, bool* ok,
                  int64_t* batches, int64_t* vec_spans) const;

  // Delete: drop the index entry, flip the on-disk dead flag, account
  // the bytes.  False when the key is not indexed.  *payload_len_out
  // (optional) reports the payload size for reclaim accounting.
  bool MarkDead(uint8_t kind, const std::string& key,
                int64_t* payload_len_out = nullptr);

  // Iterate live records of one kind.  ForEachLive reads payloads
  // (recipe rebuild); ForEachLiveMeta is header-only (orphan scan).
  struct RecordMeta {
    std::string key;
    int64_t payload_len = 0;
    int64_t mtime = 0;
  };
  void ForEachLiveMeta(
      uint8_t kind, const std::function<void(const RecordMeta&)>& fn) const;
  void ForEachLive(uint8_t kind,
                   const std::function<void(const std::string& key,
                                            const std::string& payload)>& fn)
      const;

  // Online compaction: pick dead-enough slabs (never the active one),
  // re-append their verified-live records, and unlink them.  pace(n) is
  // called per record copied with the bytes read (the scrub manager's
  // token bucket slots in here); stop() is polled between records so
  // shutdown never waits on a long compaction.  Records whose payload
  // fails re-verify (chunk: SHA1 != key; recipe: crc32 mismatch) are
  // LEFT IN PLACE and returned in corrupt_chunk_keys /
  // corrupt_recipe_keys — the caller routes chunks through the
  // quarantine/heal machinery, which marks them dead and lets the next
  // pass finish the slab.
  struct CompactResult {
    int64_t slabs_compacted = 0;
    int64_t reclaimed_bytes = 0;  // slab file bytes unlinked
    int64_t copied_records = 0;
    std::vector<std::string> corrupt_chunk_keys;
    std::vector<std::string> corrupt_recipe_keys;
  };
  CompactResult Compact(const std::function<void(int64_t)>& pace,
                        const std::function<bool()>& stop);

  // Stats (slab.* registry gauges).  Byte counters account full record
  // extents (header + key + payload), i.e. what compaction can reclaim.
  // All atomics: gauge-fns run under the stats-registry mutex and must
  // never block on mu_ (held across pwrite/fsync — a stalled mount
  // would freeze every STAT/snapshot/SLO tick otherwise).
  int64_t files() const { return files_.load(); }
  int64_t slots_live() const { return slots_live_.load(); }
  int64_t slots_dead() const { return slots_dead_.load(); }
  int64_t bytes_live() const { return bytes_live_.load(); }
  int64_t bytes_dead() const { return bytes_dead_.load(); }
  int64_t compactions() const { return compactions_.load(); }
  int64_t compacted_bytes() const { return compacted_bytes_.load(); }

  const std::string& dir() const { return dir_; }

 private:
  static constexpr int kIndexStripes = 16;
  struct IndexStripe {
    mutable RankedMutex mu{LockRank::kSlabIndex};
    std::unordered_map<std::string, Slot> map;  // key: kind byte + key
  };
  struct SlabInfo {
    int64_t size_bytes = 0;
    int64_t live_slots = 0;
    int64_t dead_slots = 0;
    int64_t live_bytes = 0;  // record extents still indexed
    int64_t dead_bytes = 0;  // record extents marked dead
  };

  static std::string IndexKey(uint8_t kind, const std::string& key) {
    std::string k(1, static_cast<char>(kind));
    k += key;
    return k;
  }
  int StripeFor(const std::string& ikey) const;
  std::string SlabPath(int64_t slab_id) const;

  // mu_ held: ensure the active slab fd is open (rolling past
  // slab_bytes_), ready for an append of `need` bytes.
  bool EnsureActiveLocked(int64_t need, std::string* err);
  // Flip the on-disk dead flag for a record (best-effort: the RAM
  // accounting is authoritative until the next boot scan).
  void FlagDeadOnDisk(int64_t slab_id, int64_t record_off) const;
  // mu_ held: move one record's extent from live to dead accounting.
  void AccountDeadLocked(int64_t slab_id, int64_t record_extent);
  // Scan one slab file into the index (boot path).
  void ScanOneSlab(int64_t slab_id, const std::string& path,
                   std::vector<std::pair<std::string, Slot>>* dups);
  // Append while holding no locks on entry; used by both the public
  // Append and the compactor.  When `expect_old` is non-null the index
  // publish only replaces an entry still equal to *expect_old — if it
  // moved (concurrent delete / re-put), the freshly appended copy is
  // marked dead instead (compaction vs mutation race).
  bool AppendInternal(uint8_t kind, const std::string& key,
                      const char* data, size_t len, bool durable,
                      const Slot* expect_old, std::string* err);

  std::string dir_;
  int64_t slab_bytes_;
  int min_dead_pct_;

  // kSlabStore: active fd + rollover + per-slab accounting.  Appends
  // hold it across the file write, so all small writes serialize here
  // (a single buffered write — the price of one-active-slab append
  // layout, noted in OPERATIONS.md).
  mutable RankedMutex mu_{LockRank::kSlabStore};
  int active_fd_ = -1;
  int64_t active_id_ = 0;
  int64_t active_size_ = 0;
  std::map<int64_t, SlabInfo> slabs_;  // ordered: compaction picks low ids
  // Dead-flag write fd, cached per slab (mu_ held at every call site):
  // a mass delete or a compaction round flags thousands of records in
  // one slab — reopening the file per record would cost three syscalls
  // each.  Closed when the flagged slab changes, at unlink, and on
  // rescan.
  mutable int flag_fd_ = -1;
  mutable int64_t flag_fd_slab_ = 0;

  std::array<IndexStripe, kIndexStripes> index_;

  std::atomic<int64_t> files_{0};  // mirrors slabs_.size() (gauge-fn read)
  std::atomic<int64_t> slots_live_{0}, slots_dead_{0};
  std::atomic<int64_t> bytes_live_{0}, bytes_dead_{0};
  std::atomic<int64_t> compactions_{0}, compacted_bytes_{0};
};

}  // namespace fdfs
