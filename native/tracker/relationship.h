// Multi-tracker relationship: leader election among tracker peers.
//
// Reference: tracker/tracker_relationship.c —
// relationship_thread_entrance(): trackers exchange status
// (TRACKER_PROTO_CMD_TRACKER_GET_STATUS), the lowest ip:port among
// responsive candidates becomes leader via NOTIFY_NEXT_LEADER +
// COMMIT_NEXT_LEADER, followers ping the leader and re-elect on loss.
//
// What leadership buys in this rebuild: a designated coordinator that
// monitor tooling can find (GET_STATUS), matching upstream's protocol.
// Cluster decisions that upstream routes through the leader (per-group
// trunk server) are made deterministically from shared state here
// (lowest ACTIVE member address), so every tracker reaches the same
// answer without coordination — a tpu-rebuild simplification that keeps
// the election protocol-visible but removes it from the correctness path.
#pragma once

#include <atomic>
#include <mutex>

#include "common/lockrank.h"
#include <string>
#include <thread>
#include <vector>

namespace fdfs {

class RelationshipManager {
 public:
  // peers: every tracker in the cluster, "ip:port", including self.
  RelationshipManager(std::string my_addr, std::vector<std::string> peers);
  ~RelationshipManager();

  void Start();
  void Stop();

  bool am_leader() const;
  std::string leader_addr() const;

  // -- handler backends (TrackerServer dispatch calls these) -------------
  // GET_STATUS (70): 1B am_leader + 16B leader_ip + 8B leader_port.
  std::string PackStatus() const;
  // PING_LEADER (71): true iff this tracker currently claims leadership.
  bool OnPingLeader() const { return am_leader(); }
  // NOTIFY_NEXT_LEADER (72) / COMMIT_NEXT_LEADER (73).
  void OnNotifyNextLeader(const std::string& addr);
  // false when the commit names an addr that was never notified (upstream
  // rejects a mismatched commit).
  bool OnCommitNextLeader(const std::string& addr);
  // One RPC to the current leader (false when leaderless or self-led);
  // used by followers to fetch leader-only decisions (trunk server).
  // Callers on an event loop must pass a short timeout: this blocks.
  bool RpcLeader(uint8_t cmd, const std::string& body, std::string* resp,
                 uint8_t* status, int timeout_ms = 2000) const;

 private:
  void ThreadMain();
  void RunElection();
  bool QueryPeerStatus(const std::string& addr, bool* is_leader,
                       std::string* their_leader) const;
  bool SendLeaderCmd(const std::string& addr, uint8_t cmd,
                     const std::string& leader) const;
  bool PingLeaderOnce(const std::string& addr) const;

  const std::string my_addr_;
  const std::vector<std::string> peers_;  // excluding self after ctor
  std::atomic<bool> stop_{false};
  std::thread thread_;
  mutable RankedMutex mu_{LockRank::kRelationship};
  std::string leader_addr_;
  std::string pending_leader_;
  int ping_failures_ = 0;
};

}  // namespace fdfs
