#include "tracker/placement.h"

#include <cstdio>
#include <cstring>

#include "common/bytes.h"
#include "common/jumphash.h"
#include "common/log.h"
#include "common/protocol_gen.h"

namespace fdfs {

const char* GroupStateName(GroupState s) {
  switch (s) {
    case GroupState::kActive: return "active";
    case GroupState::kDraining: return "draining";
    case GroupState::kRetired: return "retired";
  }
  return "?";
}

PlacementTable::Entry* PlacementTable::FindMutable(const std::string& group) {
  for (Entry& e : entries_)
    if (e.group == group) return &e;
  return nullptr;
}

const PlacementTable::Entry* PlacementTable::Find(
    const std::string& group) const {
  for (const Entry& e : entries_)
    if (e.group == group) return &e;
  return nullptr;
}

bool PlacementTable::EnsureGroup(const std::string& group) {
  if (Find(group) != nullptr) return false;
  entries_.push_back({group, GroupState::kActive});
  ++version_;
  FDFS_LOG_INFO("placement: group %s joined epoch at slot %zu (version %lld)",
                group.c_str(), entries_.size() - 1,
                static_cast<long long>(version_));
  return true;
}

int PlacementTable::Drain(const std::string& group) {
  Entry* e = FindMutable(group);
  if (e == nullptr) return 2;
  if (e->state == GroupState::kDraining) return 0;  // idempotent
  if (e->state == GroupState::kRetired) return 22;
  e->state = GroupState::kDraining;
  ++version_;
  FDFS_LOG_INFO("placement: group %s draining (version %lld)", group.c_str(),
                static_cast<long long>(version_));
  return 0;
}

int PlacementTable::Reactivate(const std::string& group) {
  Entry* e = FindMutable(group);
  if (e == nullptr) return 2;
  if (e->state == GroupState::kActive) return 0;  // idempotent
  // Retired groups left the hash domain with their data already moved
  // elsewhere; silently re-activating one would shift every key's
  // bucket without anything re-homing files into it.
  if (e->state == GroupState::kRetired) return 22;
  e->state = GroupState::kActive;
  ++version_;
  FDFS_LOG_INFO("placement: group %s reactivated (version %lld)",
                group.c_str(), static_cast<long long>(version_));
  return 0;
}

int PlacementTable::Retire(const std::string& group) {
  Entry* e = FindMutable(group);
  if (e == nullptr) return 2;
  if (e->state == GroupState::kRetired) return 0;  // idempotent
  if (e->state != GroupState::kDraining) return 22;
  e->state = GroupState::kRetired;
  ++version_;
  FDFS_LOG_INFO("placement: group %s retired (version %lld)", group.c_str(),
                static_cast<long long>(version_));
  return 0;
}

std::vector<std::string> PlacementTable::ActiveGroups() const {
  std::vector<std::string> out;
  for (const Entry& e : entries_)
    if (e.state == GroupState::kActive) out.push_back(e.group);
  return out;
}

std::string PlacementTable::PickGroup(std::string_view key) const {
  std::vector<std::string> active = ActiveGroups();
  if (active.empty()) return "";
  return active[JumpHash(PlacementKey(key),
                         static_cast<int32_t>(active.size()))];
}

// -- wire -----------------------------------------------------------------

std::string PlacementTable::PackWire(
    const std::vector<std::vector<WireMember>>& members_per_entry) const {
  std::string out;
  char buf[8];
  PutInt64BE(version_, reinterpret_cast<uint8_t*>(buf));
  out.append(buf, 8);
  PutInt64BE(static_cast<int64_t>(entries_.size()),
             reinterpret_cast<uint8_t*>(buf));
  out.append(buf, 8);
  for (size_t i = 0; i < entries_.size(); ++i) {
    PutFixedField(&out, entries_[i].group, kGroupNameMaxLen);
    out.push_back(static_cast<char>(entries_[i].state));
    const std::vector<WireMember>* members =
        i < members_per_entry.size() ? &members_per_entry[i] : nullptr;
    int64_t n = members == nullptr ? 0 : static_cast<int64_t>(members->size());
    PutInt64BE(n, reinterpret_cast<uint8_t*>(buf));
    out.append(buf, 8);
    for (int64_t m = 0; m < n; ++m) {
      PutFixedField(&out, (*members)[m].ip, kIpAddressSize);
      PutInt64BE((*members)[m].port, reinterpret_cast<uint8_t*>(buf));
      out.append(buf, 8);
    }
  }
  return out;
}

bool PlacementTable::AdoptWire(const std::string& body) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(body.data());
  size_t len = body.size();
  if (len < 16) return false;
  int64_t version = GetInt64BE(p);
  int64_t count = GetInt64BE(p + 8);
  size_t off = 16;
  // Divide-don't-multiply bounds sanity: a minimal entry is 25 bytes.
  if (count < 0 ||
      static_cast<uint64_t>(count) > (len - off) / (kGroupNameMaxLen + 9))
    return false;
  std::vector<Entry> entries;
  for (int64_t i = 0; i < count; ++i) {
    if (off + kGroupNameMaxLen + 9 > len) return false;
    Entry e;
    e.group = GetFixedField(p + off, kGroupNameMaxLen);
    uint8_t st = p[off + kGroupNameMaxLen];
    if (st > static_cast<uint8_t>(GroupState::kRetired)) return false;
    e.state = static_cast<GroupState>(st);
    off += kGroupNameMaxLen + 1;
    int64_t members = GetInt64BE(p + off);
    off += 8;
    const size_t rec = kIpAddressSize + 8;
    if (members < 0 || static_cast<uint64_t>(members) > (len - off) / rec)
      return false;
    off += static_cast<size_t>(members) * rec;  // followers keep only the epoch
    entries.push_back(std::move(e));
  }
  entries_ = std::move(entries);
  version_ = version;
  return true;
}

// -- persistence ----------------------------------------------------------

bool PlacementTable::Save(const std::string& path) const {
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr) return false;
  fprintf(f, "version %lld\n", static_cast<long long>(version_));
  for (const Entry& e : entries_)
    fprintf(f, "group %s %d\n", e.group.c_str(), static_cast<int>(e.state));
  fclose(f);
  return rename(tmp.c_str(), path.c_str()) == 0;
}

bool PlacementTable::Load(const std::string& path) {
  FILE* f = fopen(path.c_str(), "r");
  if (f == nullptr) return true;  // nothing saved yet
  char line[512];
  std::vector<Entry> entries;
  int64_t version = 0;
  while (fgets(line, sizeof(line), f) != nullptr) {
    char name[256];
    long long v = 0;
    int st = 0;
    if (sscanf(line, "version %lld", &v) == 1) {
      version = v;
      continue;
    }
    if (sscanf(line, "group %255s %d", name, &st) == 2 && st >= 0 &&
        st <= static_cast<int>(GroupState::kRetired))
      entries.push_back({name, static_cast<GroupState>(st)});
  }
  fclose(f);
  entries_ = std::move(entries);
  version_ = version;
  FDFS_LOG_INFO("placement epoch loaded: %zu groups, version %lld",
                entries_.size(), static_cast<long long>(version_));
  return true;
}

}  // namespace fdfs
