#include "tracker/hotmap.h"

#include <algorithm>
#include <cstdio>
#include <cstring>

#include "common/log.h"

namespace fdfs {

namespace {
// Changelog retention: enough history that a client polling at the map
// cadence never falls off the delta window under normal churn.
constexpr size_t kChangelogCap = 1024;
// Untracked ledger rows below this EWMA are evicted (reads/s).
constexpr double kLedgerFloor = 0.01;
constexpr size_t kLedgerCap = 4096;
}  // namespace

std::string HotMap::HomeGroup(const std::string& key) const {
  size_t slash = key.find('/');
  return slash == std::string::npos ? std::string() : key.substr(0, slash);
}

void HotMap::NoteHeat(const std::string& node,
                      const std::vector<HeatTrailerEntry>& entries) {
  auto& prev = last_seen_[node];
  for (const HeatTrailerEntry& e : entries) {
    if (e.key.empty() || e.key.size() > kHotKeyMaxLen) continue;
    // Credit reads served off an extra replica to the home key so a
    // routed read cannot cascade-promote its own copy.
    std::string key = e.key;
    auto alias = alias_.find(key);
    if (alias != alias_.end()) key = alias->second;

    int64_t dh = e.hits;
    int64_t db = e.bytes;
    auto it = prev.find(e.key);
    if (it != prev.end()) {
      dh = e.hits - it->second.first;
      db = e.bytes - it->second.second;
      // Counter-reset clamp (the monitor.top_rates discipline): a
      // shrinking cumulative counter means the daemon restarted, so the
      // new absolute value IS the window contribution.
      if (dh < 0 || db < 0) {
        dh = e.hits;
        db = e.bytes;
      }
    }
    prev[e.key] = {e.hits, e.bytes};
    LedgerRow& row = ledger_[key];
    row.window_hits += dh;
    row.window_bytes += db;
  }
}

void HotMap::Tick(double dt_s,
                  const std::function<std::vector<std::string>(
                      const std::string& home_group, int want)>& pick_targets,
                  bool run_policy) {
  ++tick_;
  if (dt_s <= 0) dt_s = 1;
  const double alpha = cfg_.ewma_alpha;

  // Fold the window into EWMAs; decay idle keys toward zero.
  for (auto it = ledger_.begin(); it != ledger_.end();) {
    LedgerRow& row = it->second;
    double rate = static_cast<double>(row.window_hits) / dt_s;
    row.ewma = alpha * rate + (1 - alpha) * row.ewma;
    row.window_hits = 0;
    row.window_bytes = 0;
    auto entry = entries_.find(it->first);
    if (entry != entries_.end()) {
      entry->second.ewma = row.ewma;
      ++it;
    } else if (row.ewma < kLedgerFloor) {
      it = ledger_.erase(it);  // cold and untracked: forget it
    } else {
      ++it;
    }
  }

  if (!run_policy) return;

  // Demote first so a freed slot can host a new promotion this tick.
  if (cfg_.demote_threshold > 0) {
    for (auto& [key, e] : entries_) {
      if (e.state != State::kPublished) continue;
      if (e.ewma >= cfg_.demote_threshold) continue;
      e.state = State::kRetiring;
      e.retired_version = ++version_;
      e.retire_tick = tick_;
      ++demotions_total_;
      RecordChange(key, {});
      FDFS_LOG_INFO("hotmap: demote %s (ewma %.1f/s, version %lld)",
                    key.c_str(), e.ewma, static_cast<long long>(version_));
    }
  }

  if (cfg_.promote_threshold <= 0) return;
  for (const auto& [key, row] : ledger_) {
    if (row.ewma < cfg_.promote_threshold) continue;
    if (entries_.count(key) != 0) continue;
    if (static_cast<int>(entries_.size()) >= cfg_.capacity) {
      FDFS_LOG_WARN("hotmap: at capacity (%d), not promoting %s",
                    cfg_.capacity, key.c_str());
      break;
    }
    std::string home = HomeGroup(key);
    if (home.empty()) continue;
    std::vector<std::string> targets =
        pick_targets(home, cfg_.max_extra_replicas);
    if (targets.empty()) continue;  // no spare capacity: defer
    Entry e;
    e.key = key;
    e.groups = std::move(targets);
    e.state = State::kPending;
    e.ewma = row.ewma;
    std::string remote = key.substr(home.size() + 1);
    for (const std::string& g : e.groups) alias_[g + "/" + remote] = key;
    ++promotions_total_;
    FDFS_LOG_INFO("hotmap: promote %s (ewma %.1f/s) -> %zu extra group(s)",
                  key.c_str(), row.ewma, e.groups.size());
    entries_.emplace(key, std::move(e));
  }
}

std::vector<HotTask> HotMap::TasksForGroup(const std::string& group) const {
  std::vector<HotTask> out;
  for (const auto& [key, e] : entries_) {
    if (HomeGroup(key) != group) continue;
    if (e.state == State::kPending) {
      out.push_back({kHotTaskReplicate, key, e.groups});
    } else if (e.state == State::kRetiring && tick_ > e.retire_tick) {
      // One-epoch gap: the tombstone must age a full policy tick before
      // any byte is deleted, so no poller holds a dead route.
      out.push_back({kHotTaskDrop, key, e.groups});
    }
    if (out.size() >= kHotTaskMaxTasks) break;
  }
  return out;
}

bool HotMap::AckReplicate(const std::string& key,
                          const std::vector<std::string>& groups) {
  auto it = entries_.find(key);
  if (it == entries_.end() || it->second.state != State::kPending)
    return false;
  Entry& e = it->second;
  for (const std::string& g : e.groups)
    if (std::find(groups.begin(), groups.end(), g) == groups.end())
      return false;  // verified set short: keep the tasks flowing
  e.state = State::kPublished;
  e.published_version = ++version_;
  RecordChange(key, e.groups);
  FDFS_LOG_INFO("hotmap: published %s -> %zu extra group(s) (version %lld)",
                key.c_str(), e.groups.size(),
                static_cast<long long>(version_));
  return true;
}

bool HotMap::AckDrop(const std::string& key) {
  auto it = entries_.find(key);
  if (it == entries_.end() || it->second.state != State::kRetiring)
    return false;
  std::string home = HomeGroup(key);
  std::string remote = key.substr(home.size() + 1);
  for (const std::string& g : it->second.groups)
    alias_.erase(g + "/" + remote);
  entries_.erase(it);
  FDFS_LOG_INFO("hotmap: dropped %s (extra copies deleted)", key.c_str());
  return true;
}

void HotMap::RecordChange(const std::string& key,
                          const std::vector<std::string>& groups) {
  changelog_.push_back({version_, key, groups});
  if (changelog_.size() > kChangelogCap) {
    size_t drop = changelog_.size() - kChangelogCap;
    trimmed_below_ = changelog_[drop - 1].version;
    changelog_.erase(changelog_.begin(),
                     changelog_.begin() + static_cast<ptrdiff_t>(drop));
  }
}

std::string HotMap::PackWire(int64_t since_version) const {
  if (since_version >= trimmed_below_ && since_version >= 0) {
    // Delta: latest changelog record per key newer than since_version.
    std::map<std::string, const ChangeRec*> latest;
    for (const ChangeRec& c : changelog_)
      if (c.version > since_version) latest[c.key] = &c;
    std::vector<HotMapEntry> out;
    out.reserve(latest.size());
    for (const auto& [key, c] : latest) out.push_back({key, c->groups});
    return PackHotMap(version_, /*full=*/false, out);
  }
  std::vector<HotMapEntry> out;
  for (const auto& [key, e] : entries_)
    if (e.state == State::kPublished) out.push_back({key, e.groups});
  return PackHotMap(version_, /*full=*/true, out);
}

bool HotMap::AdoptFull(const std::string& body) {
  int64_t version = 0;
  bool full = false;
  std::vector<HotMapEntry> wire;
  if (!ParseHotMap(reinterpret_cast<const uint8_t*>(body.data()), body.size(),
                   &version, &full, &wire) ||
      !full)
    return false;
  entries_.clear();
  alias_.clear();
  for (HotMapEntry& w : wire) {
    std::string home = HomeGroup(w.key);
    if (home.empty()) continue;
    Entry e;
    e.key = w.key;
    e.groups = std::move(w.groups);
    e.state = State::kPublished;
    e.published_version = version;
    std::string remote = e.key.substr(home.size() + 1);
    for (const std::string& g : e.groups) alias_[g + "/" + remote] = e.key;
    entries_.emplace(e.key, std::move(e));
  }
  version_ = version;
  changelog_.clear();
  trimmed_below_ = version_;
  return true;
}

std::map<std::string, int64_t> HotMap::GroupLoad() const {
  std::map<std::string, int64_t> out;
  for (const auto& [key, e] : entries_)
    for (const std::string& g : e.groups) ++out[g];
  return out;
}

const HotMap::Entry* HotMap::Find(const std::string& key) const {
  auto it = entries_.find(key);
  return it == entries_.end() ? nullptr : &it->second;
}

int64_t HotMap::CountState(State s) const {
  int64_t n = 0;
  for (const auto& [key, e] : entries_)
    if (e.state == s) ++n;
  return n;
}

bool HotMap::Save(const std::string& path) const {
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr) return false;
  fprintf(f, "version %lld\n", static_cast<long long>(version_));
  for (const auto& [key, e] : entries_) {
    fprintf(f, "entry %s %d %.3f %lld %lld", key.c_str(),
            static_cast<int>(e.state), e.ewma,
            static_cast<long long>(e.published_version),
            static_cast<long long>(e.retired_version));
    for (const std::string& g : e.groups) fprintf(f, " %s", g.c_str());
    fprintf(f, "\n");
  }
  fclose(f);
  return rename(tmp.c_str(), path.c_str()) == 0;
}

bool HotMap::Load(const std::string& path) {
  FILE* f = fopen(path.c_str(), "r");
  if (f == nullptr) return true;  // nothing saved yet
  char line[2048];
  while (fgets(line, sizeof(line), f) != nullptr) {
    long long v = 0;
    if (sscanf(line, "version %lld", &v) == 1) {
      version_ = v;
      continue;
    }
    char key[768];
    int st = 0;
    double ewma = 0;
    long long pub = 0, ret = 0;
    int consumed = 0;
    if (sscanf(line, "entry %767s %d %lf %lld %lld%n", key, &st, &ewma, &pub,
               &ret, &consumed) != 5)
      continue;
    if (st < 0 || st > static_cast<int>(State::kRetiring)) continue;
    Entry e;
    e.key = key;
    e.state = static_cast<State>(st);
    e.ewma = ewma;
    e.published_version = pub;
    e.retired_version = ret;
    e.retire_tick = 0;  // retiring entries become droppable next tick
    const char* rest = line + consumed;
    char grp[64];
    int adv = 0;
    while (sscanf(rest, " %63s%n", grp, &adv) == 1) {
      e.groups.push_back(grp);
      rest += adv;
    }
    std::string home = HomeGroup(e.key);
    if (home.empty()) continue;
    std::string remote = e.key.substr(home.size() + 1);
    for (const std::string& g : e.groups) alias_[g + "/" + remote] = e.key;
    ledger_[e.key].ewma = e.ewma;
    entries_.emplace(e.key, std::move(e));
  }
  fclose(f);
  // No changelog survives a restart: deltas start from here, older
  // pollers get a full snapshot.
  trimmed_below_ = version_;
  FDFS_LOG_INFO("hotmap loaded: %zu entries, version %lld", entries_.size(),
                static_cast<long long>(version_));
  return true;
}

}  // namespace fdfs
