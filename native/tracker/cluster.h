// Tracker in-memory cluster state — THE tracker brain.
//
// Reference: tracker/tracker_mem.c (tracker_mem_init, tracker_mem_add_
// storage, tracker_get_writable_storage, tracker_mem_get_storage_by_
// filename) + tracker/tracker_types.h (FDFSGroupInfo, FDFSStorageDetail).
// Groups hold storages; uploads are spread across groups by policy; reads
// are routed only to replicas whose sync timestamp from the file's source
// server has passed the file's create time (sync-timestamp vectors).
//
// Status lifecycle (tracker_mem.c join/offline state machine): a brand-new
// server joining a group that already has members enters WAIT_SYNC; its
// SYNC_DEST_REQ picks a source peer + until-timestamp (WAIT_SYNC→SYNCING);
// it is promoted to ACTIVE once the source's sync reports pass the
// until-timestamp (upstream: sync_old_done in the source's mark file) or on
// an explicit SYNC_NOTIFY.  Read safety is additionally carried by the
// sync-timestamp routing rule (a replica serves only files whose source
// has reported sync progress past the file's create time).
#pragma once

#include <cstdint>
#include <map>
#include <optional>
#include <string>
#include <utility>
#include <vector>

#include "common/protocol_gen.h"  // kBeatStatCount / kBeatStatNames
#include "tracker/placement.h"

namespace fdfs {

// sync_until_ts value marking a disk-recovery hold: promotion waits for the
// node's explicit done-notify (or a healthy re-JOIN), never sync reports.
constexpr int64_t kRecoveryHoldSentinel = INT64_MAX / 2;

struct StorageNode {
  std::string ip;
  int port = 0;
  int status = 7;  // StorageStatus::kActive
  int store_path_count = 1;
  int64_t join_time = 0;
  int64_t last_beat = 0;
  int64_t total_mb = 0;
  int64_t free_mb = 0;
  int64_t stats[kBeatStatCount] = {0};
  // "ip:port" of a source peer -> timestamp this node has synced up to.
  std::map<std::string, int64_t> synced_from;
  // Full-sync negotiation (SYNC_DEST_REQ): assigned source + the timestamp
  // this node must sync past before promotion to ACTIVE.
  std::string sync_src_addr;
  int64_t sync_until_ts = 0;
  // Gray-failure health (ISSUE 17): the node's self-reported gray score
  // from its latest beat trailer (-1 = never carried one — an older
  // storage, or health had nothing to say yet), when it arrived, and
  // this node's view of its PEERS ("ip:port" -> score 0..100).  The
  // differential matrix reads: a node every peer scores low while its
  // own trailer says healthy is gray.
  int64_t health_self = -1;
  int64_t health_ts = 0;
  std::map<std::string, int64_t> health_peer_scores;

  std::string Addr() const { return ip + ":" + std::to_string(port); }
};

struct GroupInfo {
  std::string name;
  std::map<std::string, StorageNode> storages;  // key "ip:port"
  size_t rr_write = 0;
  size_t rr_read = 0;
  // Bumped every time trunk_addr changes: the allocation fencing token
  // (trunk RPCs carry it; a stale trunk server or stale client is
  // rejected instead of silently allocating against a moved role).
  int64_t trunk_epoch = 0;
  // Elected trunk server "ip:port" (empty when trunk is off or the group
  // has no ACTIVE member).  Reference: the tracker leader decides the
  // per-group trunk server (tracker_relationship.c / SetTrunkServer 94).
  std::string trunk_addr;

  int ActiveCount() const;
  int64_t FreeMb() const;
};

struct StoreTarget {
  std::string group, ip;
  int port = 0;
  int store_path_index = 0xFF;  // 0xFF = storage picks
};

class Cluster {
 public:
  // store_lookup: 0 round-robin, 1 specified group, 2 load balance.
  explicit Cluster(int store_lookup = 0, std::string store_group = "",
                   bool trunk_enabled = false)
      : store_lookup_(store_lookup), store_group_(std::move(store_group)),
        trunk_enabled_(trunk_enabled) {}

  // Flight recorder (may stay null): membership transitions — joins,
  // beat-timeout OFFLINE, back-online — become structured cluster
  // events behind TrackerCmd::kEventDump.  Set once before serving.
  void set_events(class EventLog* events) { events_ = events; }

  // Placement epoch (may stay null = every group active): Join() appends
  // new groups, QueryStore routes around draining/retired groups and —
  // store_lookup = 3 — jump-hashes the client key over its active list.
  // Owned by TrackerServer (persisted with the rest of its state).
  void set_placement(PlacementTable* p) { placement_ = p; }

  // store_lookup = 2 flapping fix: the previous pick is kept until a
  // rival group leads its free space by MORE than this delta (MB).
  void set_balance_hysteresis_mb(int64_t mb) { balance_hysteresis_mb_ = mb; }

  // Lifecycle state this cluster's routing honors for `group` (kActive
  // when no placement table is attached or the group is unknown to it).
  GroupState PlacementState(const std::string& group) const;

  // -- membership (tracker_mem_add_storage / beats) ----------------------
  // nullopt: rejected (another member already owns this IP on a different
  // port — file-ID source identity is IP-only, so one member per IP).
  // recovering: the server is rebuilding a wiped disk — hold it in
  // WAIT_SYNC (never ACTIVE) until its recovery declares done.
  std::optional<std::vector<StorageNode>> Join(const std::string& group,
                                               const std::string& ip, int port,
                                               int store_path_count,
                                               int64_t now,
                                               bool recovering = false);
  // `stats` carries `nstats` beat slots (<= kBeatStatCount); a shorter
  // blob from an older storage leaves the tail slots untouched.
  bool Beat(const std::string& group, const std::string& ip, int port,
            const int64_t* stats, int nstats, int64_t now);
  bool UpdateDiskUsage(const std::string& group, const std::string& ip,
                       int port, int64_t total_mb, int64_t free_mb);
  // Health trailer from a storage beat (common/healthmon.h
  // ParseBeatHealthTrailer): the reporter's own gray score + its scores
  // about its peers.  Peer addresses outside the reporter's group
  // (trackers it probes) are kept too — HealthMatrixJson simply shows
  // them; only group members participate in the gray verdict.
  bool UpdateHealth(const std::string& group, const std::string& ip, int port,
                    int64_t self_score,
                    const std::vector<std::pair<std::string, int64_t>>& peers,
                    int64_t now);
  // Source "src" reports dest has synced its binlog through ts.
  bool SyncReport(const std::string& group, const std::string& src_addr,
                  const std::string& dest_addr, int64_t ts);
  // Heartbeat-timeout state machine (tracker_mem_check_alive): ACTIVE
  // nodes silent past `timeout_s` go OFFLINE; returns # transitions.
  int CheckAlive(int64_t now, int64_t timeout_s);
  bool DeleteStorage(const std::string& group, const std::string& addr);
  // IP-changed dealer (storage_ip_changed_dealer.c): move a member to a
  // new IP preserving its state; every reference (peers' synced_from
  // keys, sync sources, trunk server) is rewritten.
  bool RenameStorage(const std::string& group, const std::string& old_addr,
                     const std::string& new_ip, int port);

  // -- full-sync negotiation (tracker_deal_storage_sync_* analogues) -----
  // New server asks who should full-sync it.  Returns: 0 = source assigned
  // (*src/*until filled, dest WAIT_SYNC→SYNCING); 1 = no source needed
  // (first server in group; dest promoted ACTIVE); -1 = unknown dest.
  int SyncDestReq(const std::string& group, const std::string& dest_addr,
                  int64_t now, StorageNode* src, int64_t* until_ts);
  // Source peer asks whether it is the assigned full-sync source for dest.
  std::optional<int64_t> SyncSrcReq(const std::string& group,
                                    const std::string& src_addr,
                                    const std::string& dest_addr) const;
  // Dest (or its source) declares old-data sync done: promote to ACTIVE.
  bool SyncNotify(const std::string& group, const std::string& dest_addr);
  // Disk recovery (storage_disk_recovery.c): a member whose data was wiped
  // re-enters full-sync — synced_from cleared (its replicas are gone), a
  // source assigned, and promotion held until its explicit SyncNotify
  // (sentinel until_ts; auto-promotion via sync reports must not fire
  // while it is still re-downloading).  Return codes as SyncDestReq.
  int ReenterSync(const std::string& group, const std::string& dest_addr,
                  int64_t now, StorageNode* src);

  // -- trunk server election (leader decides; SURVEY §2.1/§2.3) ----------
  // Current trunk server for the group ("" when none); elects/repairs on
  // demand so callers always see a live choice when one is possible.
  // ONLY the tracker leader may call this: ACTIVE sets can transiently
  // differ across trackers, and two trackers electing independently can
  // hand two storages the same slot space (double allocation).
  std::string TrunkServer(const std::string& group);
  // Operator override (SERVER_SET_TRUNK_SERVER 94); target must be ACTIVE.
  bool SetTrunkServer(const std::string& group, const std::string& addr);
  // Follower-side: adopt the leader's decision verbatim (no election).
  void AdoptTrunkServer(const std::string& group, const std::string& addr,
                        int64_t epoch);
  int64_t TrunkEpoch(const std::string& group) const;
  // Read the current value without electing (followers, introspection).
  std::string CurrentTrunkAddr(const std::string& group) const;

  // -- routing (tracker_get_writable_storage & co.) ----------------------
  // `key`: optional client placement key (store_lookup = 3 appends it to
  // the classic empty QUERY_STORE body); ignored by the other policies
  // and when a group hint pins the pick.
  std::optional<StoreTarget> QueryStore(const std::string& group_hint,
                                        const std::string& key = "");
  std::optional<StoreTarget> QueryFetch(const std::string& group,
                                        const std::string& remote);
  std::optional<StoreTarget> QueryUpdate(const std::string& group,
                                         const std::string& remote);
  // ALL-variant queries (cmds 105/106/107): every valid candidate at once.
  std::vector<StoreTarget> QueryFetchAll(const std::string& group,
                                         const std::string& remote);
  std::vector<StoreTarget> QueryStoreAll(const std::string& group_hint,
                                         const std::string& key = "");

  // Server-ID alias table (storage_ids.conf): ip -> stable id, shown by
  // the monitor feed.
  void SetStorageIds(std::map<std::string, std::string> ip_to_id) {
    storage_ids_ = std::move(ip_to_id);
  }
  std::string StorageIdForIp(const std::string& ip) const {
    auto it = storage_ids_.find(ip);
    return it == storage_ids_.end() ? "" : it->second;
  }

  // -- introspection (fdfs_monitor feed; JSON) ---------------------------
  std::string GroupsJson() const;
  std::string OneGroupJson(const std::string& group) const;
  std::string StoragesJson(const std::string& group) const;
  // Full observability dump (SERVER_CLUSTER_STAT): every group with its
  // capacity and every storage with liveness (status, beat age) and the
  // complete named last-beat stat payload (kBeatStatNames).  `group`
  // filters to one group when non-empty.
  std::string ClusterStatJson(int64_t now, const std::string& group = "") const;
  // The N x N differential health view — the "nodes" array of the
  // HEALTH_MATRIX body (the server wraps role/port/gray_threshold
  // around it; fdfs_codec health-matrix golden; cli.py health
  // renderer).  Per node: its
  // self-reported score, the average of what its GROUP PEERS score it
  // (peer_avg, -1 when nobody has reported about it), how many peers
  // reported, and the verdict against `gray_threshold`:
  //   "gray"    peers score it below threshold while it claims healthy
  //             (the signature gray failure — or a lying/blind node)
  //   "sick"    its own trailer admits a score below threshold
  //   "ok"      both views at/above threshold
  //   "unknown" no health data at all (old storage, or too early)
  std::string HealthMatrixJson(int64_t now, int64_t gray_threshold) const;

  // -- persistence (tracker_save_storages analogue) ----------------------
  bool Save(const std::string& path) const;
  bool Load(const std::string& path);

  std::vector<StorageNode> Peers(const std::string& group,
                                 const std::string& exclude_addr) const;
  GroupInfo* FindGroup(const std::string& name);
  size_t group_count() const { return groups_.size(); }
  std::vector<std::string> GroupNames() const {
    std::vector<std::string> out;
    for (const auto& [name, g] : groups_) out.push_back(name);
    return out;
  }

 private:
  StorageNode* FindNode(const std::string& group, const std::string& addr);
  void EnsureTrunkServer(GroupInfo* g);
  std::map<std::string, GroupInfo> groups_;
  std::map<std::string, std::string> storage_ids_;  // ip -> id
  int store_lookup_;
  std::string store_group_;
  bool trunk_enabled_;
  size_t rr_group_ = 0;
  class EventLog* events_ = nullptr;
  PlacementTable* placement_ = nullptr;
  // store_lookup = 2 hysteresis state: the group the last upload went to
  // and the free-space lead a rival needs before the pick moves.
  std::string balance_group_;
  int64_t balance_hysteresis_mb_ = 1024;
};

}  // namespace fdfs
