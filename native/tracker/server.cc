#include "tracker/server.h"

#include <time.h>

#include <algorithm>
#include <cstring>

#include "common/bytes.h"
#include "common/healthmon.h"
#include "common/heatwire.h"
#include "common/jumphash.h"
#include "common/log.h"
#include "common/profiler.h"
#include "common/threadreg.h"
#include "common/protocol_gen.h"
#include "common/fsutil.h"

namespace fdfs {

namespace {

std::string FixedGroup(const uint8_t* p) {
  return GetFixedField(p, kGroupNameMaxLen);
}

std::string FixedIp(const uint8_t* p) { return GetFixedField(p, kIpAddressSize); }

std::string PackPeers(const std::vector<StorageNode>& peers) {
  std::string out(8, '\0');
  PutInt64BE(static_cast<int64_t>(peers.size()),
             reinterpret_cast<uint8_t*>(out.data()));
  for (const StorageNode& p : peers) {
    PutFixedField(&out, p.ip, kIpAddressSize);
    char pbuf[8];
    PutInt64BE(p.port, reinterpret_cast<uint8_t*>(pbuf));
    out.append(pbuf, 8);
    out.push_back(static_cast<char>(p.status));
  }
  return out;
}

std::string PackStoreTarget(const StoreTarget& t) {
  std::string out;
  PutFixedField(&out, t.group, kGroupNameMaxLen);
  PutFixedField(&out, t.ip, kIpAddressSize);
  char pbuf[8];
  PutInt64BE(t.port, reinterpret_cast<uint8_t*>(pbuf));
  out.append(pbuf, 8);
  out.push_back(static_cast<char>(t.store_path_index));
  return out;
}

std::string PackFetchTarget(const StoreTarget& t) {
  std::string out;
  PutFixedField(&out, t.ip, kIpAddressSize);
  char pbuf[8];
  PutInt64BE(t.port, reinterpret_cast<uint8_t*>(pbuf));
  out.append(pbuf, 8);
  return out;
}

// ALL-variant replies: 16B group + 1B path idx + 8B count + count x
// (16B ip + 8B port).
std::string PackTargetList(const std::string& group, uint8_t path_idx,
                           const std::vector<StoreTarget>& ts) {
  std::string out;
  PutFixedField(&out, group, kGroupNameMaxLen);
  out.push_back(static_cast<char>(path_idx));
  char buf[8];
  PutInt64BE(static_cast<int64_t>(ts.size()), reinterpret_cast<uint8_t*>(buf));
  out.append(buf, 8);
  for (const StoreTarget& t : ts) {
    PutFixedField(&out, t.ip, kIpAddressSize);
    PutInt64BE(t.port, reinterpret_cast<uint8_t*>(buf));
    out.append(buf, 8);
  }
  return out;
}

// Monitor-facing span names for the tracker opcodes worth reading on a
// timeline; everything else renders as "tracker.cmd<N>".
const char* TrackerOpName(uint8_t cmd) {
  switch (static_cast<TrackerCmd>(cmd)) {
    case TrackerCmd::kStorageJoin: return "tracker.storage_join";
    case TrackerCmd::kStorageBeat: return "tracker.storage_beat";
    case TrackerCmd::kServiceQueryStoreWithoutGroupOne:
    case TrackerCmd::kServiceQueryStoreWithGroupOne:
      return "tracker.query_store";
    case TrackerCmd::kServiceQueryStoreWithoutGroupAll:
    case TrackerCmd::kServiceQueryStoreWithGroupAll:
      return "tracker.query_store_all";
    case TrackerCmd::kServiceQueryFetchOne: return "tracker.query_fetch";
    case TrackerCmd::kServiceQueryFetchAll: return "tracker.query_fetch_all";
    case TrackerCmd::kServiceQueryUpdate: return "tracker.query_update";
    case TrackerCmd::kServerClusterStat: return "tracker.cluster_stat";
    case TrackerCmd::kServerListAllGroups: return "tracker.list_groups";
    case TrackerCmd::kStorageSyncReport: return "tracker.sync_report";
    case TrackerCmd::kQueryPlacement: return "tracker.query_placement";
    case TrackerCmd::kGroupDrain: return "tracker.group_drain";
    case TrackerCmd::kGroupReactivate: return "tracker.group_reactivate";
    case TrackerCmd::kProfileCtl: return "tracker.profile_ctl";
    case TrackerCmd::kProfileDump: return "tracker.profile_dump";
    case TrackerCmd::kHealthMatrix: return "tracker.health_matrix";
    case TrackerCmd::kAdmissionStatus: return "tracker.admission_status";
    case TrackerCmd::kQueryHotMap: return "tracker.query_hot_map";
    case TrackerCmd::kHotFanoutDone: return "tracker.hot_fanout_done";
    default: return nullptr;
  }
}

}  // namespace

TrackerServer::TrackerServer(TrackerConfig cfg) : cfg_(std::move(cfg)) {}

bool TrackerServer::Init(std::string* error) {
  if (!MakeDirs(cfg_.base_path + "/data")) {
    *error = "cannot create " + cfg_.base_path + "/data";
    return false;
  }
  // Flight recorder before the cluster brain: membership transitions
  // record into it from the first JOIN on.
  events_ = std::make_unique<EventLog>(
      static_cast<size_t>(cfg_.event_buffer_size));
  cluster_ = std::make_unique<Cluster>(cfg_.store_lookup, cfg_.store_group,
                                       cfg_.use_trunk_file);
  cluster_->set_events(events_.get());
  cluster_->set_balance_hysteresis_mb(cfg_.placement_hysteresis_free_mb);
  placement_ = std::make_unique<PlacementTable>();
  placement_path_ = cfg_.base_path + "/data/placement.dat";
  placement_->Load(placement_path_);
  cluster_->set_placement(placement_.get());
  // Elastic hot replication (ISSUE 20): always constructed — with
  // promotion off (the default) it still folds beat heat and serves an
  // empty map, so QUERY_HOT_MAP and the hot.* gauges stay live.
  {
    HotMap::Config hcfg;
    hcfg.promote_threshold = cfg_.hot_promote_threshold;
    hcfg.demote_threshold = cfg_.hot_demote_threshold;
    hcfg.max_extra_replicas = cfg_.hot_max_extra_replicas;
    hcfg.capacity = cfg_.hot_map_capacity;
    hotmap_ = std::make_unique<HotMap>(hcfg);
    hotmap_path_ = cfg_.base_path + "/data/hotmap.dat";
    hotmap_->Load(hotmap_path_);
  }

  // Telemetry history + SLOs (ISSUE 8): the same journal/evaluator pair
  // the storage daemon runs, minus the storage-only rules (their
  // readings are simply absent from this registry, so they never fire).
  if (cfg_.metrics_journal_mb > 0 && cfg_.slo_eval_interval_s > 0) {
    metrics_ = std::make_unique<MetricsJournal>(
        cfg_.base_path + "/data/metrics",
        static_cast<int64_t>(cfg_.metrics_journal_mb) << 20);
    std::string merr;
    if (!metrics_->Open(&merr)) {
      FDFS_LOG_WARN("metrics journal disabled: %s", merr.c_str());
      events_->Record(EventSeverity::kWarn, "config.anomaly",
                      "metrics journal disabled", merr);
      metrics_.reset();
    }
  }
  if (cfg_.slo_eval_interval_s > 0) {
    std::vector<SloRule> rules;
    if (!cfg_.slo_rules_file.empty()) {
      IniConfig slo_ini;
      std::string serr;
      if (slo_ini.LoadFile(cfg_.slo_rules_file, &serr)) {
        rules = SloEvaluator::LoadRules(slo_ini);
      } else {
        FDFS_LOG_WARN("slo_rules_file %s: %s (using compiled-in defaults)",
                      cfg_.slo_rules_file.c_str(), serr.c_str());
        events_->Record(EventSeverity::kWarn, "config.anomaly",
                        "slo_rules_file unreadable", serr);
        rules = SloEvaluator::DefaultRules();
      }
    } else {
      rules = SloEvaluator::DefaultRules();
    }
    slo_ = std::make_unique<SloEvaluator>(std::move(rules), events_.get());
  }
  // Admission control (ISSUE 19): always constructed — when disabled it
  // still classifies and counts (ADMISSION_STATUS + gauges stay live)
  // but never sheds.
  {
    AdmissionConfig acfg;
    acfg.enabled = cfg_.admission_control;
    acfg.tighten_threshold = cfg_.admission_tighten_pct / 100.0;
    acfg.relax_threshold = cfg_.admission_relax_pct / 100.0;
    acfg.loop_lag_high_ms =
        static_cast<double>(cfg_.admission_loop_lag_high_ms);
    acfg.retry_after_ms = cfg_.admission_retry_after_ms;
    admission_ = std::make_unique<AdmissionController>(acfg);
  }
  registry_.GaugeFn("admission.level", [this] {
    return static_cast<int64_t>(admission_->level());
  });
  registry_.GaugeFn("admission.pressure_milli",
                    [this] { return admission_->pressure_milli(); });
  registry_.GaugeFn("admission.ewma_milli",
                    [this] { return admission_->ewma_milli(); });
  registry_.GaugeFn("admission.tightens",
                    [this] { return admission_->tightens(); });
  registry_.GaugeFn("admission.relaxes",
                    [this] { return admission_->relaxes(); });
  registry_.GaugeFn("admission.admitted",
                    [this] { return admission_->admitted(); });
  registry_.GaugeFn("admission.shed_total",
                    [this] { return admission_->shed_total(); });
  registry_.GaugeFn("admission.retry_after_ms",
                    [this] { return admission_->retry_after_ms(); });
  for (int i = 0; i < kPriorityClassCount; ++i) {
    registry_.GaugeFn(std::string("admission.shed.") +
                          PriorityClassName(static_cast<uint8_t>(i)),
                      [this, i] { return admission_->shed_by_class(i); });
  }
  registry_.GaugeFn("hot.map_version", [this] { return hotmap_->version(); });
  registry_.GaugeFn("hot.promoted", [this] {
    return hotmap_->CountState(HotMap::State::kPublished);
  });
  registry_.GaugeFn("hot.pending", [this] {
    return hotmap_->CountState(HotMap::State::kPending);
  });
  registry_.GaugeFn("hot.retiring", [this] {
    return hotmap_->CountState(HotMap::State::kRetiring);
  });
  registry_.GaugeFn("hot.promotions_total",
                    [this] { return hotmap_->promotions_total(); });
  registry_.GaugeFn("hot.demotions_total",
                    [this] { return hotmap_->demotions_total(); });
  registry_.GaugeFn("hot.tracked_keys",
                    [this] { return hotmap_->tracked_keys(); });
  registry_.GaugeFn("slo.breaches_active", [this] {
    return slo_ != nullptr ? slo_->breaches_active() : int64_t{0};
  });
  registry_.GaugeFn("metrics.journal_bytes", [this] {
    return metrics_ != nullptr ? metrics_->bytes_retained() : int64_t{0};
  });
  registry_.GaugeFn("metrics.journal_records", [this] {
    return metrics_ != nullptr ? metrics_->appended() : int64_t{0};
  });

  // Saturation telemetry (ISSUE 6): the tracker's single nio loop is
  // the whole daemon — a slow handler here stalls every beat and every
  // routing query in the cluster.  Same registry contract as the
  // storage STAT so fdfs_top renders one table for both roles.
  hist_nio_lag_ = registry_.Histogram("nio.loop_lag_us",
                                      StatsRegistry::LatencyBucketsUs());
  ctr_nio_dispatched_ = registry_.Counter("nio.dispatched_ops");
  registry_.GaugeFn("nio.conns_active", [this] {
    return server_ != nullptr ? server_->conn_count() : int64_t{0};
  });
  ctr_requests_ = registry_.Counter("server.requests");
  ctr_errors_ = registry_.Counter("server.errors");
  hist_request_us_ = registry_.Histogram("server.request_us",
                                         StatsRegistry::LatencyBucketsUs());
  registry_.GaugeFn("server.refused_connections", [this] {
    return server_ != nullptr ? server_->refused_count() : int64_t{0};
  });
  registry_.GaugeFn("events.recorded", [this] { return events_->recorded(); });
  registry_.GaugeFn("events.dropped", [this] { return events_->dropped(); });
  registry_.GaugeFn("trace.spans_recorded", [this] {
    return trace_ != nullptr ? trace_->recorded() : int64_t{0};
  });
  registry_.GaugeFn("trace.spans_dropped", [this] {
    return trace_ != nullptr ? trace_->dropped() : int64_t{0};
  });
  loop_.set_iteration_hook([this](int64_t busy_us, int n_events) {
    hist_nio_lag_->Observe(busy_us);
    loop_busy_us_.fetch_add(busy_us, std::memory_order_relaxed);
    if (n_events > 0)
      ctr_nio_dispatched_->fetch_add(n_events, std::memory_order_relaxed);
  });
  // Profiler ceiling (0 keeps the feature entirely off) + health gauges,
  // same names as the storage daemon so fdfs_top reads one contract.
  Profiler::Global().set_max_hz(cfg_.profile_max_hz);
  registry_.GaugeFn("profile.samples",
                    [] { return Profiler::Global().samples(); });
  registry_.GaugeFn("profile.dropped",
                    [] { return Profiler::Global().dropped(); });
  registry_.GaugeFn("profile.active", [] {
    return static_cast<int64_t>(Profiler::Global().active() ? 1 : 0);
  });
  if (cfg_.use_storage_id && !cfg_.storage_ids_file.empty()) {
    // storage_ids.conf: "<id> <group> <ip>" per line (fdfs_shared_func.c:
    // fdfs_get_storage_ids_from_tracker_group table format).
    std::map<std::string, std::string> ids;
    FILE* f = fopen(cfg_.storage_ids_file.c_str(), "r");
    if (f != nullptr) {
      char line[256], id[64], grp[64], ip[64];
      while (fgets(line, sizeof(line), f) != nullptr) {
        if (line[0] == '#') continue;
        if (sscanf(line, "%63s %63s %63s", id, grp, ip) == 3) ids[ip] = id;
      }
      fclose(f);
      FDFS_LOG_INFO("loaded %zu storage ids from %s", ids.size(),
                    cfg_.storage_ids_file.c_str());
    } else {
      *error = "cannot open storage_ids file " + cfg_.storage_ids_file;
      return false;
    }
    cluster_->SetStorageIds(std::move(ids));
  }
  state_path_ = cfg_.base_path + "/data/storage_servers.dat";
  changelog_path_ = cfg_.base_path + "/data/changelog.dat";
  cluster_->Load(state_path_);
  // A lost/older placement.dat must not orphan groups the cluster state
  // remembers: backfill them (in name order — the one arbitrary choice,
  // made identically by every tracker replaying the same state).
  for (const std::string& g : cluster_->GroupNames())
    placement_->EnsureGroup(g);

  server_ = std::make_unique<RequestServer>(
      &loop_, [this](uint8_t cmd, const std::string& body,
                     const std::string& peer) { return Handle(cmd, body, peer); });
  server_->set_max_connections(cfg_.max_connections);
  // Admission gate: resolve the class (PRIORITY-frame byte, else the
  // tracker opcode table) and consult the ladder.  Runs on the single
  // loop thread, but AdmitOrShed is thread-safe anyway.
  server_->set_gate([this](uint8_t cmd, uint8_t tagged, int64_t* retry_ms) {
    uint8_t cls = tagged != kPriorityUntagged ? tagged
                                              : DefaultTrackerPriorityClass(cmd);
    return admission_->AdmitOrShed(cls, retry_ms);
  });
  // Span recording: one span per traced request (TRACE_CTX prefix) or
  // per slow request (force-retained with kTraceFlagSlow + one
  // structured JSON log line), dumped via kTraceDump.
  trace_ = std::make_unique<TraceRing>(
      static_cast<size_t>(cfg_.trace_buffer_size));
  server_->set_trace_hook([this](uint8_t cmd, const TraceCtx& ctx,
                                 int64_t start_us, int64_t dur_us,
                                 uint8_t status, const std::string& peer) {
    // Request accounting rides the per-dispatch hook (the tracker has
    // no LogAccess choke point): aggregate count/errors/latency feeding
    // the kStat registry and fdfs_top's tracker row.
    ctr_requests_->fetch_add(1, std::memory_order_relaxed);
    if (status != 0) ctr_errors_->fetch_add(1, std::memory_order_relaxed);
    hist_request_us_->Observe(dur_us);
    int64_t slow_us = cfg_.slow_request_threshold_ms * 1000;
    bool slow = slow_us > 0 && dur_us >= slow_us;
    if (!ctx.valid() && !slow) return;
    TraceSpan s;
    s.trace_id = ctx.valid() ? ctx.trace_id : trace_->NewTraceId();
    s.span_id = trace_->NextSpanId();
    s.parent_id = ctx.parent_span;
    s.start_us = start_us;
    s.dur_us = dur_us;
    s.status = status;
    s.flags = ctx.flags | (slow ? kTraceFlagSlow : 0);
    const char* name = TrackerOpName(cmd);
    char fallback[24];
    if (name == nullptr) {
      std::snprintf(fallback, sizeof(fallback), "tracker.cmd%d", cmd);
      name = fallback;
    }
    s.SetName(name);
    trace_->Record(s);
    if (slow) {
      FDFS_LOG_WARN("%s",
                    SlowRequestJson("tracker", s.name, s, peer, 0).c_str());
      events_->Record(EventSeverity::kWarn, "request.slow", s.name,
                      "peer=" + peer + " dur_us=" + std::to_string(dur_us) +
                          " status=" + std::to_string(status));
    }
  });
  if (!server_->Listen(cfg_.bind_addr, cfg_.port, error)) return false;

  loop_.AddTimer(1000, [this]() {
    cluster_->CheckAlive(time(nullptr), cfg_.check_active_interval_s);
  });
  // Drain endgame: only the leader decides a drain is complete (it owns
  // every other epoch transition too).
  loop_.AddTimer(2000, [this]() { MaybeAutoRetire(); });
  if (cfg_.slo_eval_interval_s > 0 && (metrics_ != nullptr || slo_ != nullptr))
    loop_.AddTimer(cfg_.slo_eval_interval_s * 1000,
                   [this]() { MetricsTick(); });
  loop_.AddTimer(cfg_.save_interval_s * 1000, [this]() {
    cluster_->Save(state_path_);
    placement_->Save(placement_path_);
    hotmap_->Save(hotmap_path_);
    // Periodic status file (tracker_write_status_file analogue).
    std::string tmp = cfg_.base_path + "/data/tracker_status.dat.tmp";
    FILE* f = fopen(tmp.c_str(), "w");
    if (f != nullptr) {
      fprintf(f, "ts=%lld\nleader=%s\nam_leader=%d\ngroups=%zu\n",
              static_cast<long long>(time(nullptr)),
              relationship_ ? relationship_->leader_addr().c_str() : "",
              relationship_ && relationship_->am_leader() ? 1 : 0,
              cluster_->group_count());
      fclose(f);
      rename(tmp.c_str(),
             (cfg_.base_path + "/data/tracker_status.dat").c_str());
    }
  });

  // Multi-tracker relationship (tracker_relationship.c): leader election
  // among the configured tracker peers.  Identity resolution order: an
  // explicit bind address; else the UNIQUE tracker_server entry with our
  // port (multi-host configs where each host binds all interfaces); else
  // loopback.  A wrong self-identity would leave this tracker in its own
  // candidate list twice (or never), which is how split-brain starts —
  // refuse ambiguous configs instead.
  std::string my_ip;
  if (!cfg_.bind_addr.empty() && cfg_.bind_addr != "0.0.0.0") {
    my_ip = cfg_.bind_addr;
  } else {
    std::string suffix = ":" + std::to_string(cfg_.port);
    int matches = 0;
    for (const std::string& p : cfg_.tracker_peers) {
      if (p.size() > suffix.size() &&
          p.compare(p.size() - suffix.size(), suffix.size(), suffix) == 0) {
        ++matches;
        my_ip = p.substr(0, p.size() - suffix.size());
      }
    }
    if (matches != 1) {
      if (!cfg_.tracker_peers.empty())
        FDFS_LOG_ERROR(
            "cannot identify this tracker among %zu tracker_server entries "
            "(%d match port %d): set bind_addr explicitly",
            cfg_.tracker_peers.size(), matches, cfg_.port);
      if (matches > 1) {
        *error = "ambiguous tracker identity: set bind_addr";
        return false;
      }
      my_ip = "127.0.0.1";
    }
  }
  relationship_ = std::make_unique<RelationshipManager>(
      my_ip + ":" + std::to_string(cfg_.port), cfg_.tracker_peers);
  relationship_->Start();

  FDFS_LOG_INFO("tracker daemon up: port=%d store_lookup=%d", cfg_.port,
                cfg_.store_lookup);
  return true;
}

void TrackerServer::Run() {
  // The tracker is one event loop; its ledger row is the whole daemon.
  ScopedThreadName ledger("tracker.loop");
  loop_.Run();
}

void TrackerServer::MetricsTick() {
  // One snapshot feeds both consumers (journal + SLO engine), so a
  // post-mortem can re-derive every breach from the retained history.
  int64_t now_mono = MonoUs();
  // Per-thread CPU ledger (threadreg.h): published before the snapshot
  // below so the journal persists this tick's thread.* gauges.
  ThreadRegistry::Global().SampleInto(&registry_);
  int64_t busy = loop_busy_us_.load(std::memory_order_relaxed);
  if (last_tick_mono_us_ > 0 && now_mono > last_tick_mono_us_) {
    int64_t pct = (busy - loop_busy_last_) * 100 / (now_mono - last_tick_mono_us_);
    if (pct < 0) pct = 0;
    if (pct > 100) pct = 100;
    registry_.SetGauge("nio.loop_busy_pct.main", pct);
  }
  loop_busy_last_ = busy;
  StatsSnapshot snap;
  registry_.Snapshot(&snap);
  if (metrics_ != nullptr) metrics_->Append(TraceWallUs(), snap);
  double dt_s = static_cast<double>(now_mono - last_tick_mono_us_) / 1e6;
  if (dt_s <= 0) dt_s = 1.0;
  if (slo_ != nullptr && have_tick_snap_) {
    slo_->Tick(last_tick_snap_, snap, dt_s);
  }
  // Admission ladder tick AFTER the SLO tick (breaches_active reflects
  // this snapshot's verdicts).  The tracker's pressure inputs are its
  // breach count and single-loop lag p99 — it has no dio pools and no
  // streamed-body ledger.
  if (admission_ != nullptr) {
    AdmissionSignals sig;
    sig.breaches_active = slo_ != nullptr ? slo_->breaches_active() : 0;
    double lag_ms = 0;
    if (have_tick_snap_ &&
        SloEvaluator::ComputeReading("loop_lag_p99_ms", last_tick_snap_,
                                     snap, dt_s, &lag_ms))
      sig.loop_lag_p99_ms = lag_ms;
    int moved = admission_->Tick(sig);
    if (moved != 0 && events_ != nullptr) {
      char detail[128];
      snprintf(detail, sizeof(detail), "level=%d ewma=%.6g pressure=%.6g",
               admission_->level(), admission_->ewma_milli() / 1000.0,
               admission_->pressure_milli() / 1000.0);
      events_->Record(moved > 0 ? EventSeverity::kWarn : EventSeverity::kInfo,
                      moved > 0 ? "admission.tighten" : "admission.relax",
                      admission_->level_name(), detail);
    }
  }
  last_tick_snap_ = std::move(snap);
  have_tick_snap_ = true;
  last_tick_mono_us_ = now_mono;
  // HeatPolicy pass (ISSUE 20): fold the beat-trailer heat window into
  // EWMAs every tick; only the leader promotes/demotes (followers keep
  // their ledgers warm for failover without diverging the map).
  bool leader = relationship_ == nullptr || relationship_->am_leader();
  int64_t hot_version_before = hotmap_->version();
  hotmap_->Tick(
      dt_s,
      [this](const std::string& home, int want) {
        return PickHotTargets(home, want);
      },
      leader);
  if (hotmap_->version() != hot_version_before) {
    hotmap_->Save(hotmap_path_);
    if (events_ != nullptr)
      events_->Record(EventSeverity::kInfo, "hot.map_changed",
                      "version=" + std::to_string(hotmap_->version()),
                      "promoted=" + std::to_string(hotmap_->CountState(
                                        HotMap::State::kPublished)) +
                          " retiring=" +
                          std::to_string(hotmap_->CountState(
                              HotMap::State::kRetiring)));
  }
}

std::vector<std::string> TrackerServer::PickHotTargets(const std::string& home,
                                                       int want) {
  struct Cand {
    std::string group;
    int64_t assigned;
    int64_t free_mb;
  };
  std::map<std::string, int64_t> load = hotmap_->GroupLoad();
  std::vector<Cand> cands;
  for (const std::string& g : placement_->ActiveGroups()) {
    if (g == home) continue;
    const GroupInfo* gi = cluster_->FindGroup(g);
    if (gi == nullptr || gi->ActiveCount() == 0) continue;
    cands.push_back({g, load.count(g) != 0 ? load[g] : 0, gi->FreeMb()});
  }
  // Fewest existing hot assignments first (ops/s spread), then most
  // free space (capacity), then name for determinism.
  std::sort(cands.begin(), cands.end(), [](const Cand& a, const Cand& b) {
    if (a.assigned != b.assigned) return a.assigned < b.assigned;
    if (a.free_mb != b.free_mb) return a.free_mb > b.free_mb;
    return a.group < b.group;
  });
  std::vector<std::string> out;
  for (const Cand& c : cands) {
    if (static_cast<int>(out.size()) >= want) break;
    out.push_back(c.group);
  }
  return out;
}

void TrackerServer::MaybeAdoptHotMap() {
  if (relationship_ == nullptr || relationship_->am_leader()) return;
  // The MaybeAdoptPlacement discipline: at most one leader round-trip a
  // second, ~10s backoff when unreachable, last adopted map serves on.
  int64_t now_ms = NowMs();
  if (now_ms - hotmap_fetched_ms_ < 1000) return;
  hotmap_fetched_ms_ = now_ms;
  std::string resp;
  uint8_t status = 0;
  if (relationship_->RpcLeader(
          static_cast<uint8_t>(TrackerCmd::kQueryHotMap), "", &resp, &status,
          /*timeout_ms=*/300) &&
      status == 0) {
    if (!hotmap_->AdoptFull(resp))
      FDFS_LOG_WARN("hotmap: malformed snapshot from leader (%zu bytes)",
                    resp.size());
  } else {
    hotmap_fetched_ms_ = now_ms + 9000;
  }
}

std::string TrackerServer::ResolveTrunkServer(const std::string& group) {
  if (!cfg_.use_trunk_file) return "";  // never poll for a disabled feature
  if (relationship_ == nullptr || relationship_->am_leader())
    return cluster_->TrunkServer(group);
  // Follower: refresh the adopted value from the leader at most once a
  // second (beats are frequent); an unreachable leader keeps the last
  // adopted answer — stale-but-consistent beats fresh-but-divergent.
  // The throttle stamp advances on failure too: a down leader must not
  // turn every storage beat into a blocking connect on this loop.
  int64_t now_ms = NowMs();
  int64_t& fetched = trunk_fetched_ms_[group];
  if (now_ms - fetched >= 1000) {
    fetched = now_ms;
    std::string body;
    PutFixedField(&body, group, kGroupNameMaxLen);
    std::string resp;
    uint8_t status = 0;
    // Short timeout: this blocks the event loop.  On failure, back off
    // ~10s so a dead leader costs one brief stall per window, not one
    // per storage beat.
    if (relationship_->RpcLeader(
            static_cast<uint8_t>(TrackerCmd::kTrackerGetTrunkServer), body,
            &resp, &status, /*timeout_ms=*/300) &&
        status == 0) {
      size_t nl = resp.find('\n');
      int64_t epoch = nl == std::string::npos
                          ? cluster_->TrunkEpoch(group)
                          : atoll(resp.c_str() + nl + 1);
      cluster_->AdoptTrunkServer(
          group, nl == std::string::npos ? resp : resp.substr(0, nl), epoch);
    } else {
      fetched = now_ms + 9000;
    }
  }
  return cluster_->CurrentTrunkAddr(group);
}

void TrackerServer::Stop() {
  cluster_->Save(state_path_);
  placement_->Save(placement_path_);
  hotmap_->Save(hotmap_path_);
  if (relationship_ != nullptr) relationship_->Stop();
  loop_.Stop();
}

std::string TrackerServer::PackPlacement() const {
  std::vector<std::vector<PlacementTable::WireMember>> members;
  for (const PlacementTable::Entry& e : placement_->entries()) {
    std::vector<PlacementTable::WireMember> ms;
    for (const StorageNode& s : cluster_->Peers(e.group, "")) {
      if (s.status != static_cast<int>(StorageStatus::kActive)) continue;
      ms.push_back({s.ip, s.port});
    }
    members.push_back(std::move(ms));
  }
  return placement_->PackWire(members);
}

void TrackerServer::MaybeAdoptPlacement() {
  if (relationship_ == nullptr || relationship_->am_leader()) return;
  // The ResolveTrunkServer discipline: at most one leader round-trip a
  // second, ~10s backoff when the leader is unreachable, and the last
  // adopted epoch keeps serving meanwhile.
  int64_t now_ms = NowMs();
  if (now_ms - placement_fetched_ms_ < 1000) return;
  placement_fetched_ms_ = now_ms;
  std::string resp;
  uint8_t status = 0;
  if (relationship_->RpcLeader(
          static_cast<uint8_t>(TrackerCmd::kQueryPlacement), "", &resp,
          &status, /*timeout_ms=*/300) &&
      status == 0) {
    if (!placement_->AdoptWire(resp))
      FDFS_LOG_WARN("placement: malformed epoch body from leader (%zu bytes)",
                    resp.size());
  } else {
    placement_fetched_ms_ = now_ms + 9000;
  }
}

void TrackerServer::MaybeAutoRetire() {
  if (relationship_ != nullptr && !relationship_->am_leader()) return;
  // Index the rebalance beat slots once (the names are the contract;
  // the positions are generated).
  static const int pending_slot = [] {
    for (int i = 0; i < kBeatStatCount; ++i)
      if (strcmp(kBeatStatNames[i], "rebalance_files_pending") == 0) return i;
    return -1;
  }();
  static const int done_slot = [] {
    for (int i = 0; i < kBeatStatCount; ++i)
      if (strcmp(kBeatStatNames[i], "rebalance_done") == 0) return i;
    return -1;
  }();
  if (pending_slot < 0 || done_slot < 0) return;
  for (const PlacementTable::Entry& e : placement_->entries()) {
    if (e.state != GroupState::kDraining) continue;
    int actives = 0;
    bool all_done = true;
    for (const StorageNode& s : cluster_->Peers(e.group, "")) {
      if (s.status != static_cast<int>(StorageStatus::kActive)) continue;
      ++actives;
      if (s.stats[done_slot] != 1 || s.stats[pending_slot] != 0)
        all_done = false;
    }
    // No ACTIVE member means no evidence — a group of crashed storages
    // must not be declared empty.
    if (actives == 0 || !all_done) continue;
    if (placement_->Retire(e.group) == 0) {
      placement_->Save(placement_path_);
      if (events_ != nullptr)
        events_->Record(EventSeverity::kInfo, "group.retired", e.group,
                        "version=" + std::to_string(placement_->version()));
    }
  }
}

void TrackerServer::DumpState() {
  FDFS_LOG_INFO("tracker state: %s", cluster_->GroupsJson().c_str());
  // SIGUSR1 postmortem dump: the retained event ring as one JSON line
  // (the kEventDump contract), next to the cluster state.
  if (events_ != nullptr)
    FDFS_LOG_INFO("event dump: %s",
                  events_->Json("tracker", cfg_.port).c_str());
  // Thread ledger with heartbeat ages (threadreg.h): the SIGUSR1 face
  // of the watchdog — "never" marks threads that don't beat.
  std::string ledger;
  for (const ThreadRegistry::HeartbeatEntry& hb :
       ThreadRegistry::Global().Heartbeats()) {
    if (!ledger.empty()) ledger += " ";
    ledger += hb.name + "(" + std::to_string(hb.tid) + ")=";
    ledger += hb.age_us < 0 ? std::string("never")
                            : std::to_string(hb.age_us / 1000) + "ms";
  }
  FDFS_LOG_INFO("thread ledger: %s", ledger.c_str());
}

std::pair<uint8_t, std::string> TrackerServer::Handle(
    uint8_t cmd, const std::string& body, const std::string& peer_ip) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(body.data());
  int64_t now = time(nullptr);
  switch (static_cast<TrackerCmd>(cmd)) {
    case TrackerCmd::kActiveTest:
    case TrackerCmd::kQuit:
      return {0, ""};

    case TrackerCmd::kStorageJoin: {
      // 16B group + 16B ip + 8B port + 8B store_path_count [+ 8B flags:
      // bit0 = disk recovery in progress]
      if (body.size() < 48) return {22, ""};
      std::string group = FixedGroup(p);
      std::string ip = FixedIp(p + 16);
      if (ip.empty()) ip = peer_ip;
      int64_t port = GetInt64BE(p + 32);
      int64_t spc = GetInt64BE(p + 40);
      bool recovering = body.size() >= 56 && (GetInt64BE(p + 48) & 1) != 0;
      if (group.empty() || port <= 0 || port > 65535 || spc < 1 || spc > 256)
        return {22, ""};
      auto peers = cluster_->Join(group, ip, static_cast<int>(port),
                                  static_cast<int>(spc), now, recovering);
      if (!peers.has_value()) return {114 /*EALREADY*/, ""};
      return {0, PackPeers(*peers)};
    }

    case TrackerCmd::kStorageBeat: {
      // 16B group + 16B ip + 8B port [+ kBeatStatCount x 8B stats]
      if (body.size() < 40) return {22, ""};
      std::string group = FixedGroup(p);
      std::string ip = FixedIp(p + 16);
      int64_t port = GetInt64BE(p + 32);
      int64_t stats[kBeatStatCount] = {0};
      const int64_t* sp = nullptr;
      // Accept shorter blobs from older storages (append-only contract);
      // missing tail slots stay at their last value.
      int nstats = static_cast<int>(
          std::min<size_t>((body.size() - 40) / 8, kBeatStatCount));
      if (nstats > 0) {
        for (int i = 0; i < nstats; ++i)
          stats[i] = GetInt64BE(p + 40 + 8 * i);
        sp = stats;
      }
      if (!cluster_->Beat(group, ip, static_cast<int>(port), sp, nstats, now))
        return {2, ""};  // unknown: storage must re-JOIN
      // Health trailer (common/healthmon.h): the append-only region
      // past the pinned stat slots carries the reporter's own gray
      // score plus its scores about its peers — one beat feeds one row
      // of the N x N HEALTH_MATRIX.  Absent from older storages (and
      // from beats before health has anything to say); a malformed
      // trailer is ignored, never an error — health must not be able
      // to break heartbeats.
      size_t stats_end = 40 + 8 * static_cast<size_t>(kBeatStatCount);
      if (body.size() > stats_end) {
        BeatHealthTrailer ht;
        if (ParseBeatHealthTrailer(body.data() + stats_end,
                                   body.size() - stats_end, &ht))
          cluster_->UpdateHealth(group, ip, static_cast<int>(port),
                                 ht.self_score, ht.peers, now);
        // Heat trailer (common/heatwire.h): the reporter's HEAT_TOP
        // cumulative read counters, appended after the health trailer
        // (either may be absent).  Same tolerance contract: malformed
        // heat must never break heartbeats.
        int64_t hoff = FindHeatTrailer(p + stats_end, body.size() - stats_end);
        if (hoff >= 0) {
          std::vector<HeatTrailerEntry> heat;
          if (ParseHeatTrailer(p + stats_end + hoff,
                               body.size() - stats_end -
                                   static_cast<size_t>(hoff),
                               &heat))
            hotmap_->NoteHeat(ip + ":" + std::to_string(port), heat);
        }
      }
      auto peers = cluster_->Peers(group, ip + ":" + std::to_string(port));
      // Trailer: the group's elected trunk server (zeros when trunk is
      // off) — how every member learns where to RPC slot allocations.
      std::string out = PackPeers(peers);
      std::string taddr = ResolveTrunkServer(group);
      std::string tip;
      int64_t tport = 0;
      size_t colon = taddr.rfind(':');
      if (colon != std::string::npos) {
        tip = taddr.substr(0, colon);
        tport = atoll(taddr.c_str() + colon + 1);
      }
      PutFixedField(&out, tip, kIpAddressSize);
      char pbuf[8];
      PutInt64BE(tport, reinterpret_cast<uint8_t*>(pbuf));
      out.append(pbuf, 8);
      // +8B trunk epoch: the allocation fencing token (see cluster.h).
      PutInt64BE(cluster_->TrunkEpoch(group),
                 reinterpret_cast<uint8_t*>(pbuf));
      out.append(pbuf, 8);
      // +1B group lifecycle state + 8B placement version (append-only
      // trailer extension, prefix-tolerant at the storage): how a member
      // learns its group started draining and must refuse new writes /
      // run the rebalance migrator.
      MaybeAdoptPlacement();  // followers: keep the served state fresh
      out.push_back(
          static_cast<char>(cluster_->PlacementState(group)));
      PutInt64BE(placement_->version(), reinterpret_cast<uint8_t*>(pbuf));
      out.append(pbuf, 8);
      // Hot-task trailer (append-only, prefix-tolerant at the storage):
      // replicate/drop assignments for keys homed in this group, but
      // only to each key's ELECTED member — jump-hash over the sorted
      // ACTIVE member addrs, so exactly one node runs a fan-out and an
      // offline elect re-routes on the next beat.  Leader-only: a
      // follower's adopted map has no pending/retiring entries anyway.
      if (relationship_ == nullptr || relationship_->am_leader()) {
        std::vector<HotTask> tasks = hotmap_->TasksForGroup(group);
        if (!tasks.empty()) {
          std::vector<std::string> addrs;
          for (const StorageNode& s : cluster_->Peers(group, ""))
            if (s.status == static_cast<int>(StorageStatus::kActive))
              addrs.push_back(s.ip + ":" + std::to_string(s.port));
          std::sort(addrs.begin(), addrs.end());
          std::string me = ip + ":" + std::to_string(port);
          std::vector<HotTask> mine;
          for (HotTask& t : tasks)
            if (!addrs.empty() &&
                addrs[JumpHash(PlacementKey(t.key),
                               static_cast<int32_t>(addrs.size()))] == me)
              mine.push_back(std::move(t));
          out += PackHotTasks(mine);  // "" when none elected here
        }
      }
      return {0, out};
    }

    case TrackerCmd::kStorageReportDiskUsage: {
      if (body.size() < 56) return {22, ""};
      std::string group = FixedGroup(p);
      std::string ip = FixedIp(p + 16);
      int64_t port = GetInt64BE(p + 32);
      if (!cluster_->UpdateDiskUsage(group, ip, static_cast<int>(port),
                                     GetInt64BE(p + 40), GetInt64BE(p + 48)))
        return {2, ""};
      return {0, ""};
    }

    case TrackerCmd::kStorageSyncReport: {
      // 16B group + 16B src_ip + 8B src_port + 16B dest_ip + 8B dest_port + 8B ts
      if (body.size() < 72) return {22, ""};
      std::string group = FixedGroup(p);
      std::string src = FixedIp(p + 16) + ":" +
                        std::to_string(GetInt64BE(p + 32));
      std::string dest = FixedIp(p + 40) + ":" +
                         std::to_string(GetInt64BE(p + 56));
      if (!cluster_->SyncReport(group, src, dest, GetInt64BE(p + 64)))
        return {2, ""};
      return {0, ""};
    }

    case TrackerCmd::kServiceQueryStoreWithoutGroupOne: {
      // Optional body = the client's placement key (store_lookup = 3
      // jump-hashes it; other policies ignore it).  Legacy clients send
      // an empty body and round-robin.
      auto t = cluster_->QueryStore("", body);
      if (!t.has_value()) return {2, ""};
      return {0, PackStoreTarget(*t)};
    }

    case TrackerCmd::kServiceQueryStoreWithGroupOne: {
      if (body.size() < 16) return {22, ""};
      auto t = cluster_->QueryStore(FixedGroup(p));
      if (!t.has_value()) return {2, ""};
      return {0, PackStoreTarget(*t)};
    }

    case TrackerCmd::kServiceQueryStoreWithoutGroupAll:
    case TrackerCmd::kServiceQueryStoreWithGroupAll: {
      std::string hint;
      if (static_cast<TrackerCmd>(cmd) ==
          TrackerCmd::kServiceQueryStoreWithGroupAll) {
        if (body.size() < 16) return {22, ""};
        hint = FixedGroup(p);
      }
      auto ts = cluster_->QueryStoreAll(hint, hint.empty() ? body : "");
      if (ts.empty()) return {2, ""};
      return {0, PackTargetList(ts[0].group, 0xFF, ts)};
    }

    case TrackerCmd::kServiceQueryFetchAll: {
      if (body.size() < 16 + 10) return {22, ""};
      std::string group = FixedGroup(p);
      auto ts = cluster_->QueryFetchAll(group, body.substr(16));
      if (ts.empty()) return {2, ""};
      return {0, PackTargetList(group, 0, ts)};
    }

    case TrackerCmd::kStorageSyncDestReq: {
      // New server asks for a full-sync source: 16B group + 16B ip + 8B port.
      // Resp: empty (no source needed) or 16B src_ip + 8B src_port + 8B
      // until_ts.
      if (body.size() < 40) return {22, ""};
      std::string group = FixedGroup(p);
      std::string dest =
          FixedIp(p + 16) + ":" + std::to_string(GetInt64BE(p + 32));
      StorageNode src;
      int64_t until = 0;
      int rc = cluster_->SyncDestReq(group, dest, now, &src, &until);
      if (rc < 0) return {2, ""};
      if (rc == 1) return {0, ""};
      std::string out;
      PutFixedField(&out, src.ip, kIpAddressSize);
      char buf[8];
      PutInt64BE(src.port, reinterpret_cast<uint8_t*>(buf));
      out.append(buf, 8);
      PutInt64BE(until, reinterpret_cast<uint8_t*>(buf));
      out.append(buf, 8);
      return {0, out};
    }

    case TrackerCmd::kStorageSyncSrcReq: {
      // Source asks whether it owns dest's full-sync: 16B group + 16B
      // src_ip + 8B src_port + 16B dest_ip + 8B dest_port.  Resp: 8B
      // until_ts, or status ENOENT when not the assigned source.
      if (body.size() < 64) return {22, ""};
      std::string group = FixedGroup(p);
      std::string src =
          FixedIp(p + 16) + ":" + std::to_string(GetInt64BE(p + 32));
      std::string dest =
          FixedIp(p + 40) + ":" + std::to_string(GetInt64BE(p + 56));
      auto until = cluster_->SyncSrcReq(group, src, dest);
      if (!until.has_value()) return {2, ""};
      std::string out(8, '\0');
      PutInt64BE(*until, reinterpret_cast<uint8_t*>(out.data()));
      return {0, out};
    }

    case TrackerCmd::kStorageSyncDestQuery: {
      // Disk recovery re-entry: 16B group + 16B ip + 8B port.  Same reply
      // shape as SYNC_DEST_REQ.
      if (body.size() < 40) return {22, ""};
      std::string group = FixedGroup(p);
      std::string dest =
          FixedIp(p + 16) + ":" + std::to_string(GetInt64BE(p + 32));
      StorageNode src;
      int rc = cluster_->ReenterSync(group, dest, now, &src);
      if (rc < 0) return {2, ""};
      if (rc == 2) return {11 /*EAGAIN: no live source yet, retry*/, ""};
      if (rc == 1) return {0, ""};
      std::string out;
      PutFixedField(&out, src.ip, kIpAddressSize);
      char buf[8];
      PutInt64BE(src.port, reinterpret_cast<uint8_t*>(buf));
      out.append(buf, 8);
      PutInt64BE(0, reinterpret_cast<uint8_t*>(buf));
      out.append(buf, 8);
      return {0, out};
    }

    case TrackerCmd::kStorageSyncNotify: {
      // Full-sync done declaration: 16B group + 16B ip + 8B port.
      if (body.size() < 40) return {22, ""};
      std::string group = FixedGroup(p);
      std::string dest =
          FixedIp(p + 16) + ":" + std::to_string(GetInt64BE(p + 32));
      if (!cluster_->SyncNotify(group, dest)) return {2, ""};
      return {0, ""};
    }

    case TrackerCmd::kStorageParameterReq: {
      // Cluster-global params every group member must agree on
      // (storage_param_getter.c).  INI-style text body.
      char buf[512];
      std::snprintf(
          buf, sizeof(buf),
          "store_lookup=%d\ncheck_active_interval=%d\n"
          "use_trunk_file=%d\nslot_min_size=%d\nslot_max_size=%d\n"
          "trunk_file_size=%lld\nreserved_storage_space=%lld\n"
          "rebalance_bandwidth_mb_s=%d\n",
          cfg_.store_lookup, cfg_.check_active_interval_s,
          cfg_.use_trunk_file ? 1 : 0, cfg_.slot_min_size, cfg_.slot_max_size,
          static_cast<long long>(cfg_.trunk_file_size),
          static_cast<long long>(cfg_.reserved_storage_space_mb),
          cfg_.rebalance_bandwidth_mb_s);
      return {0, buf};
    }

    case TrackerCmd::kServerListOneGroup: {
      if (body.size() < 16) return {22, ""};
      return {0, cluster_->OneGroupJson(FixedGroup(p))};
    }

    case TrackerCmd::kStorageReportIpChanged: {
      // 16B group + 16B old_ip + 16B new_ip + 8B port — the storage's own
      // IP moved; rewrite its identity, log to the changelog so peers can
      // rename their sync cursors (storage_ip_changed_dealer.c /
      // storage_changelog_req).
      if (body.size() < 56) return {22, ""};
      std::string group = FixedGroup(p);
      std::string old_ip = FixedIp(p + 16);
      std::string new_ip = FixedIp(p + 32);
      int64_t sport = GetInt64BE(p + 48);
      if (old_ip.empty() || new_ip.empty() || sport <= 0 || sport > 65535)
        return {22, ""};
      std::string old_addr = old_ip + ":" + std::to_string(sport);
      if (!cluster_->RenameStorage(group, old_addr, new_ip,
                                   static_cast<int>(sport)))
        return {2, ""};
      FILE* f = fopen(changelog_path_.c_str(), "a");
      if (f != nullptr) {
        fprintf(f, "%lld %s %s %s:%lld\n", static_cast<long long>(now),
                group.c_str(), old_addr.c_str(), new_ip.c_str(),
                static_cast<long long>(sport));
        fclose(f);
      }
      cluster_->Save(state_path_);
      return {0, ""};
    }

    case TrackerCmd::kStorageChangelogReq: {
      // Identity changelog since byte `offset` (8B, optional; 0 = all).
      int64_t offset = body.size() >= 8 ? GetInt64BE(p) : 0;
      std::string text;
      FILE* f = fopen(changelog_path_.c_str(), "r");
      if (f != nullptr) {
        if (offset > 0) fseek(f, static_cast<long>(offset), SEEK_SET);
        char buf[4096];
        size_t n;
        while ((n = fread(buf, 1, sizeof(buf), f)) > 0 &&
               text.size() < (4U << 20))
          text.append(buf, n);
        fclose(f);
      }
      return {0, text};
    }

    case TrackerCmd::kTrackerGetStatus:
      return {0, relationship_->PackStatus()};

    case TrackerCmd::kTrackerPingLeader:
      // A follower pings whoever it believes leads; a non-leader answer
      // (EFAULT-ish status) tells it to re-elect.
      return {relationship_->OnPingLeader() ? uint8_t{0} : uint8_t{2}, ""};

    case TrackerCmd::kTrackerNotifyNextLeader:
    case TrackerCmd::kTrackerCommitNextLeader: {
      if (body.size() < kIpAddressSize + 8) return {22, ""};
      std::string ip = FixedIp(p);
      int64_t lport = GetInt64BE(p + kIpAddressSize);
      if (ip.empty() || lport <= 0) return {22, ""};
      std::string addr = ip + ":" + std::to_string(lport);
      if (static_cast<TrackerCmd>(cmd) ==
          TrackerCmd::kTrackerNotifyNextLeader) {
        relationship_->OnNotifyNextLeader(addr);
        return {0, ""};
      }
      return {relationship_->OnCommitNextLeader(addr) ? uint8_t{0}
                                                      : uint8_t{22},
              ""};
    }

    case TrackerCmd::kServerSetTrunkServer: {
      // 16B group + "ip:port" — operator override of the elected trunk
      // server (fdfs_monitor's set_trunk_server).  The override must land
      // on the leader (where elections are decided, or the next repair
      // would silently revert it); a follower refuses with EBUSY rather
      // than proxying — two trackers with crossed leader views would
      // proxy to each other and stall both event loops.
      if (body.size() < 17) return {22, ""};
      if (relationship_ != nullptr && !relationship_->am_leader())
        return {16 /*EBUSY: not the leader*/, ""};
      if (!cluster_->SetTrunkServer(FixedGroup(p), body.substr(16)))
        return {2, ""};
      return {0, ""};
    }

    case TrackerCmd::kTrackerGetTrunkServer: {
      // 16B group -> "ip:port" (leader-only: a follower answering from
      // its own view would reintroduce the divergence this cmd removes).
      if (body.size() < 16) return {22, ""};
      if (relationship_ != nullptr && !relationship_->am_leader())
        return {16 /*EBUSY*/, ""};
      std::string grp = FixedGroup(p);
      std::string taddr = cluster_->TrunkServer(grp);
      return {0, taddr + "\n" +
                     std::to_string(cluster_->TrunkEpoch(grp))};
    }

    case TrackerCmd::kServiceQueryFetchOne:
    case TrackerCmd::kServiceQueryUpdate: {
      if (body.size() < 16 + 10) return {22, ""};
      std::string group = FixedGroup(p);
      std::string remote = body.substr(16);
      auto t = static_cast<TrackerCmd>(cmd) == TrackerCmd::kServiceQueryFetchOne
                   ? cluster_->QueryFetch(group, remote)
                   : cluster_->QueryUpdate(group, remote);
      if (!t.has_value()) return {2, ""};
      return {0, PackFetchTarget(*t)};
    }

    case TrackerCmd::kServerListAllGroups:
      return {0, cluster_->GroupsJson()};

    case TrackerCmd::kTraceDump:
      // Span ring dump (empty body).  Shape is the cross-language
      // contract decoded by fastdfs_tpu.trace.decode_dump.
      return {0, trace_ != nullptr ? trace_->Json("tracker", cfg_.port)
                                   : "{\"role\":\"tracker\",\"spans\":[]}"};

    case TrackerCmd::kStat:
      // Stats-registry snapshot (empty body): same JSON contract as
      // StorageCmd::kStat — the tracker's loop-lag/request telemetry.
      return {0, registry_.Json()};

    case TrackerCmd::kAdmissionStatus:
      // Admission-controller state dump (empty body -> JSON): ladder
      // level, pressure/EWMA, per-class shed counts — the same contract
      // as the storage daemon's (monitor.decode_admission; fdfs_codec
      // admission-json golden).
      if (!body.empty()) return {22 /*EINVAL*/, ""};
      return {0, admission_->StatusJson("tracker", cfg_.port)};

    case TrackerCmd::kEventDump:
      // Flight-recorder dump (empty body): membership transitions and
      // slow requests, per fastdfs_tpu.monitor.decode_events.
      return {0, events_ != nullptr
                     ? events_->Json("tracker", cfg_.port)
                     : "{\"role\":\"tracker\",\"events\":[]}"};

    case TrackerCmd::kMetricsHistory: {
      // Metrics-journal window dump: empty body = everything retained,
      // 8B body = since-ts (epoch µs).  ENOTSUP with journaling off so
      // callers can tell "no journal" from "no history yet".  Any other
      // length is a malformed window, not "no window": the storage
      // daemon rejects it too, and silently dumping the WHOLE ring —
      // decoded inline on this single loop — for a client that asked
      // for a narrow one is the worst possible reading.
      if (body.size() != 0 && body.size() != 8) return {22 /*EINVAL*/, ""};
      if (metrics_ == nullptr) return {95 /*ENOTSUP*/, ""};
      int64_t since = body.size() == 8 ? GetInt64BE(p) : 0;
      return {0, metrics_->DumpJson("tracker", cfg_.port,
                                    since < 0 ? 0 : since)};
    }

    case TrackerCmd::kProfileCtl: {
      // Profiler control: 17B body = 1B action (1=start, 0=stop) + 8B BE
      // hz + 8B BE duration seconds (protocol.py PROFILE_CTL).
      if (body.size() != 17) return {22 /*EINVAL*/, ""};
      uint8_t action = p[0];
      int64_t hz = GetInt64BE(p + 1);
      int64_t secs = GetInt64BE(p + 9);
      int rc;
      if (action == 1) {
        if (hz <= 0 || hz > 100000 || secs <= 0 || secs > 86400)
          rc = 22;
        else
          rc = Profiler::Global().Start(static_cast<int>(hz),
                                        static_cast<int>(secs));
      } else if (action == 0) {
        rc = Profiler::Global().Stop();
      } else {
        rc = 22;
      }
      if (rc != 0) return {static_cast<uint8_t>(rc), ""};
      Profiler& prof = Profiler::Global();
      return {0, std::string("{\"active\":") +
                     (prof.active() ? "true" : "false") +
                     ",\"hz\":" + std::to_string(prof.armed_hz()) + "}"};
    }

    case TrackerCmd::kProfileDump: {
      // Folded-stack dump (empty body -> JSON, monitor.decode_profile).
      // Symbolization is bounded by unique pcs, so inline on this loop
      // is acceptable — the kMetricsHistory precedent.  ENOTSUP while a
      // capture was never started.
      if (!body.empty()) return {22 /*EINVAL*/, ""};
      std::string j;
      int rc = Profiler::Global().DumpJson("tracker", cfg_.port, &j);
      if (rc != 0) return {static_cast<uint8_t>(rc), ""};
      return {0, j};
    }

    case TrackerCmd::kHealthMatrix:
      // Gray-failure matrix (empty body -> JSON): every node's
      // self-reported score vs the average of what its group peers
      // score it, with the verdict against health_gray_threshold
      // (monitor.decode_health_matrix; fdfs_codec health-matrix golden;
      // cli.py health renderer).
      if (!body.empty()) return {22 /*EINVAL*/, ""};
      return {0,
              "{\"role\":\"tracker\",\"port\":" + std::to_string(cfg_.port) +
                  ",\"gray_threshold\":" +
                  std::to_string(cfg_.health_gray_threshold) + ",\"nodes\":" +
                  cluster_->HealthMatrixJson(now, cfg_.health_gray_threshold) +
                  "}"};

    case TrackerCmd::kQueryHotMap: {
      // Hot-map query: empty body = full snapshot, 8B since_version =
      // compact delta (empty-groups entry = tombstone).  Followers
      // refresh their adopted copy from the leader first (throttled).
      if (body.size() != 0 && body.size() != 8) return {22 /*EINVAL*/, ""};
      MaybeAdoptHotMap();
      int64_t since = body.size() == 8 ? GetInt64BE(p) : -1;
      return {0, hotmap_->PackWire(since)};
    }

    case TrackerCmd::kHotFanoutDone: {
      // Fan-out ack from the home group's elected member: 16B home
      // group + 1B task type + 8B key_len + key + 8B verified-group
      // count + count x 16B group names.  Replicate acks publish the
      // entry (verify-then-publish); drop acks purge it.  Re-acks after
      // a state change are idempotent successes, so a slow duplicate
      // never errors the storage.
      if (body.size() < 33) return {22 /*EINVAL*/, ""};
      uint8_t type = p[16];
      int64_t klen = GetInt64BE(p + 17);
      if (klen <= 0 || klen > static_cast<int64_t>(kHotKeyMaxLen) ||
          25 + static_cast<size_t>(klen) + 8 > body.size())
        return {22, ""};
      std::string key = body.substr(25, static_cast<size_t>(klen));
      size_t off = 25 + static_cast<size_t>(klen);
      int64_t ngroups = GetInt64BE(p + off);
      off += 8;
      if (ngroups < 0 || ngroups > 64 ||
          off + static_cast<size_t>(ngroups) * kGroupNameMaxLen > body.size())
        return {22, ""};
      std::vector<std::string> groups;
      for (int64_t i = 0; i < ngroups; ++i) {
        groups.push_back(GetFixedField(p + off, kGroupNameMaxLen));
        off += kGroupNameMaxLen;
      }
      bool changed = type == kHotTaskDrop
                         ? hotmap_->AckDrop(key)
                         : hotmap_->AckReplicate(key, groups);
      if (changed) {
        hotmap_->Save(hotmap_path_);
        if (events_ != nullptr)
          events_->Record(EventSeverity::kInfo,
                          type == kHotTaskDrop ? "hot.dropped"
                                               : "hot.published",
                          key,
                          "version=" + std::to_string(hotmap_->version()));
      }
      return {0, ""};
    }

    case TrackerCmd::kServerClusterStat: {
      // One-RPC observability dump: tracker role + every group/storage
      // with the full named last-beat stat payload.  Optional 16B group
      // filter in the body.
      std::string group = body.size() >= 16 ? FixedGroup(p) : "";
      std::string leader =
          relationship_ != nullptr ? relationship_->leader_addr() : "";
      char head[256];
      std::snprintf(head, sizeof(head),
                    "{\"now\":%lld,\"tracker\":{\"am_leader\":%s,"
                    "\"leader\":\"%s\",\"groups\":%zu},\"groups\":",
                    static_cast<long long>(now),
                    relationship_ != nullptr && relationship_->am_leader()
                        ? "true" : "false",
                    leader.c_str(), cluster_->group_count());
      return {0, std::string(head) + cluster_->ClusterStatJson(now, group) +
                     "}"};
    }

    case TrackerCmd::kServerListStorage: {
      if (body.size() < 16) return {22, ""};
      return {0, cluster_->StoragesJson(FixedGroup(p))};
    }

    case TrackerCmd::kQueryPlacement:
      // Placement epoch fetch (empty body): clients route uploads from
      // the returned table without a tracker round-trip; storages learn
      // the active list the rebalance migrator re-places against.
      MaybeAdoptPlacement();
      return {0, PackPlacement()};

    case TrackerCmd::kGroupDrain:
    case TrackerCmd::kGroupReactivate: {
      // 16B group.  Leader-only (the kServerSetTrunkServer rationale:
      // epoch transitions decided in two places would fork the hash
      // domain); a follower refuses with EBUSY and the client retries
      // against its other trackers.
      if (body.size() < 16) return {22, ""};
      if (relationship_ != nullptr && !relationship_->am_leader())
        return {16 /*EBUSY: not the leader*/, ""};
      std::string group = FixedGroup(p);
      bool drain = static_cast<TrackerCmd>(cmd) == TrackerCmd::kGroupDrain;
      int rc = drain ? placement_->Drain(group)
                     : placement_->Reactivate(group);
      if (rc != 0) return {static_cast<uint8_t>(rc), ""};
      placement_->Save(placement_path_);
      if (events_ != nullptr)
        events_->Record(EventSeverity::kInfo,
                        drain ? "group.drain" : "group.reactivate", group,
                        "version=" + std::to_string(placement_->version()));
      std::string out(8, '\0');
      PutInt64BE(placement_->version(),
                 reinterpret_cast<uint8_t*>(out.data()));
      return {0, out};
    }

    case TrackerCmd::kServerDeleteStorage: {
      if (body.size() < 17) return {22, ""};
      std::string group = FixedGroup(p);
      std::string addr = body.substr(16);
      if (!cluster_->DeleteStorage(group, addr)) return {16 /*EBUSY*/, ""};
      return {0, ""};
    }

    default:
      FDFS_LOG_WARN("tracker: unknown cmd %d from %s", cmd, peer_ip.c_str());
      return {22, ""};
  }
}

}  // namespace fdfs
