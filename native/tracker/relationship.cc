#include "tracker/relationship.h"

#include <stdlib.h>
#include <unistd.h>

#include <algorithm>

#include "common/bytes.h"
#include "common/log.h"
#include "common/threadreg.h"
#include "common/net.h"
#include "common/protocol_gen.h"

namespace fdfs {

namespace {

constexpr int kRpcTimeoutMs = 2000;
constexpr int kPingFailureLimit = 3;

bool SplitAddr(const std::string& addr, std::string* host, int* port) {
  size_t colon = addr.rfind(':');
  if (colon == std::string::npos || colon == 0) return false;
  *host = addr.substr(0, colon);
  *port = atoi(addr.c_str() + colon + 1);
  return *port > 0;
}

bool Rpc(const std::string& addr, uint8_t cmd, const std::string& body,
         std::string* resp, uint8_t* status,
         int timeout_ms = kRpcTimeoutMs) {
  std::string host;
  int port;
  if (!SplitAddr(addr, &host, &port)) return false;
  std::string err;
  int fd = TcpConnect(host, port, timeout_ms, &err);
  if (fd < 0) return false;
  uint8_t hdr[kHeaderSize];
  PutInt64BE(static_cast<int64_t>(body.size()), hdr);
  hdr[8] = cmd;
  hdr[9] = 0;
  bool ok = SendAll(fd, hdr, sizeof(hdr), timeout_ms) &&
            SendAll(fd, body.data(), body.size(), timeout_ms) &&
            RecvAll(fd, hdr, sizeof(hdr), timeout_ms);
  if (ok) {
    int64_t len = GetInt64BE(hdr);
    *status = hdr[9];
    if (len < 0 || len > 4096) {
      ok = false;
    } else {
      resp->resize(static_cast<size_t>(len));
      if (len > 0) ok = RecvAll(fd, resp->data(), resp->size(), timeout_ms);
    }
  }
  close(fd);
  return ok;
}

std::string PackAddr(const std::string& addr) {
  std::string host;
  int port = 0;
  SplitAddr(addr, &host, &port);
  std::string out;
  PutFixedField(&out, host, kIpAddressSize);
  char buf[8];
  PutInt64BE(port, reinterpret_cast<uint8_t*>(buf));
  out.append(buf, 8);
  return out;
}

std::string UnpackAddr(const uint8_t* p) {
  std::string ip = GetFixedField(p, kIpAddressSize);
  int64_t port = GetInt64BE(p + kIpAddressSize);
  if (ip.empty() || port <= 0) return "";
  return ip + ":" + std::to_string(port);
}

}  // namespace

RelationshipManager::RelationshipManager(std::string my_addr,
                                         std::vector<std::string> peers)
    : my_addr_(std::move(my_addr)), peers_([&] {
        std::vector<std::string> out;
        for (std::string& p : peers)
          if (p != my_addr_) out.push_back(std::move(p));
        return out;
      }()) {}

RelationshipManager::~RelationshipManager() { Stop(); }

void RelationshipManager::Start() {
  if (peers_.empty()) {
    // Single-tracker cluster: this tracker IS the leader, no thread.
    std::lock_guard<RankedMutex> lk(mu_);
    leader_addr_ = my_addr_;
    return;
  }
  thread_ = std::thread(&RelationshipManager::ThreadMain, this);
}

void RelationshipManager::Stop() {
  stop_ = true;
  if (thread_.joinable()) thread_.join();
}

bool RelationshipManager::am_leader() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return leader_addr_ == my_addr_;
}

std::string RelationshipManager::leader_addr() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return leader_addr_;
}

std::string RelationshipManager::PackStatus() const {
  std::string leader = leader_addr();
  std::string out(1, leader == my_addr_ ? '\x01' : '\x00');
  out += PackAddr(leader.empty() ? "0.0.0.0:0" : leader);
  return out;
}

void RelationshipManager::OnNotifyNextLeader(const std::string& addr) {
  std::lock_guard<RankedMutex> lk(mu_);
  pending_leader_ = addr;
}

bool RelationshipManager::OnCommitNextLeader(const std::string& addr) {
  std::lock_guard<RankedMutex> lk(mu_);
  if (pending_leader_ != addr) return false;
  if (leader_addr_ != addr) {
    FDFS_LOG_INFO("tracker leader committed: %s%s", addr.c_str(),
                  addr == my_addr_ ? " (this tracker)" : "");
  }
  leader_addr_ = addr;
  ping_failures_ = 0;
  return true;
}

bool RelationshipManager::RpcLeader(uint8_t cmd, const std::string& body,
                                    std::string* resp, uint8_t* status,
                                    int timeout_ms) const {
  std::string leader = leader_addr();
  if (leader.empty() || leader == my_addr_) return false;
  return Rpc(leader, cmd, body, resp, status, timeout_ms);
}

bool RelationshipManager::QueryPeerStatus(const std::string& addr,
                                          bool* is_leader,
                                          std::string* their_leader) const {
  std::string resp;
  uint8_t status = 0;
  if (!Rpc(addr, static_cast<uint8_t>(TrackerCmd::kTrackerGetStatus), "",
           &resp, &status) ||
      status != 0 || resp.size() < 1 + kIpAddressSize + 8)
    return false;
  *is_leader = resp[0] != '\x00';
  *their_leader =
      UnpackAddr(reinterpret_cast<const uint8_t*>(resp.data()) + 1);
  return true;
}

bool RelationshipManager::SendLeaderCmd(const std::string& addr, uint8_t cmd,
                                        const std::string& leader) const {
  std::string resp;
  uint8_t status = 0;
  return Rpc(addr, cmd, PackAddr(leader), &resp, &status) && status == 0;
}

bool RelationshipManager::PingLeaderOnce(const std::string& addr) const {
  std::string resp;
  uint8_t status = 0;
  return Rpc(addr, static_cast<uint8_t>(TrackerCmd::kTrackerPingLeader),
             PackAddr(my_addr_), &resp, &status) &&
         status == 0;
}

void RelationshipManager::RunElection() {
  // Candidates: self + every responsive peer.  If any candidate already
  // claims leadership, adopt it (don't fight a settled cluster);
  // otherwise the lowest ip:port wins (upstream's rule) and the winner —
  // when it is us — notifies + commits to everyone.
  std::vector<std::string> candidates = {my_addr_};
  std::string claimed;
  for (const std::string& p : peers_) {
    if (stop_) return;
    bool is_leader = false;
    std::string their_leader;
    if (!QueryPeerStatus(p, &is_leader, &their_leader)) continue;
    candidates.push_back(p);
    if (is_leader) claimed = p;
  }
  std::string winner =
      claimed.empty() ? *std::min_element(candidates.begin(), candidates.end())
                      : claimed;
  if (winner == my_addr_) {
    for (const std::string& p : peers_) {
      if (stop_) return;
      SendLeaderCmd(p, static_cast<uint8_t>(TrackerCmd::kTrackerNotifyNextLeader),
                    my_addr_);
      SendLeaderCmd(p, static_cast<uint8_t>(TrackerCmd::kTrackerCommitNextLeader),
                    my_addr_);
    }
  }
  std::lock_guard<RankedMutex> lk(mu_);
  if (leader_addr_ != winner)
    FDFS_LOG_INFO("tracker leader elected: %s%s", winner.c_str(),
                  winner == my_addr_ ? " (this tracker)" : "");
  leader_addr_ = winner;
  ping_failures_ = 0;
}

void RelationshipManager::ThreadMain() {
  ScopedThreadName ledger("relationship");
  while (!stop_) {
    BeatThreadHeartbeat();
    std::string leader = leader_addr();
    if (leader.empty()) {
      RunElection();
    } else if (leader != my_addr_) {
      if (PingLeaderOnce(leader)) {
        std::lock_guard<RankedMutex> lk(mu_);
        ping_failures_ = 0;
      } else {
        int fails;
        {
          std::lock_guard<RankedMutex> lk(mu_);
          fails = ++ping_failures_;
        }
        if (fails >= kPingFailureLimit) {
          FDFS_LOG_WARN("tracker leader %s unresponsive (%d pings): "
                        "re-electing", leader.c_str(), fails);
          {
            std::lock_guard<RankedMutex> lk(mu_);
            leader_addr_.clear();
          }
          RunElection();
        }
      }
    }
    for (int i = 0; i < 10 && !stop_; ++i) {
      BeatThreadHeartbeat();  // idle between leader pings, not stalled
      usleep(100 * 1000);
    }
  }
}

}  // namespace fdfs
