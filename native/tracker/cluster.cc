#include "tracker/cluster.h"

#include <cstdio>
#include <cstring>

#include "common/eventlog.h"
#include "common/fileid.h"
#include "common/log.h"
#include "common/protocol_gen.h"

namespace fdfs {

namespace {
constexpr int kActive = static_cast<int>(StorageStatus::kActive);
constexpr int kOffline = static_cast<int>(StorageStatus::kOffline);
constexpr int kDeleted = static_cast<int>(StorageStatus::kDeleted);
constexpr int kWaitSync = static_cast<int>(StorageStatus::kWaitSync);
constexpr int kSyncing = static_cast<int>(StorageStatus::kSyncing);
}  // namespace

int GroupInfo::ActiveCount() const {
  int n = 0;
  for (const auto& [addr, s] : storages)
    if (s.status == kActive) ++n;
  return n;
}

int64_t GroupInfo::FreeMb() const {
  // Group capacity == min over active members (full replication).
  int64_t mn = -1;
  for (const auto& [addr, s] : storages) {
    if (s.status != kActive) continue;
    if (mn < 0 || s.free_mb < mn) mn = s.free_mb;
  }
  return mn < 0 ? 0 : mn;
}

GroupInfo* Cluster::FindGroup(const std::string& name) {
  auto it = groups_.find(name);
  return it == groups_.end() ? nullptr : &it->second;
}

StorageNode* Cluster::FindNode(const std::string& group,
                               const std::string& addr) {
  GroupInfo* g = FindGroup(group);
  if (g == nullptr) return nullptr;
  auto it = g->storages.find(addr);
  return it == g->storages.end() ? nullptr : &it->second;
}

std::optional<std::vector<StorageNode>> Cluster::Join(
    const std::string& group, const std::string& ip, int port,
    int store_path_count, int64_t now, bool recovering) {
  GroupInfo& g = groups_[group];
  g.name = group;
  // First sighting of a group appends it to the placement epoch (order
  // is the consistency contract — see tracker/placement.h).
  if (placement_ != nullptr) placement_->EnsureGroup(group);
  std::string addr = ip + ":" + std::to_string(port);
  // One member per IP: the file-ID source field identifies servers by IP
  // alone, so a second port on the same IP would corrupt read routing.
  for (const auto& [a, s] : g.storages) {
    if (s.ip == ip && s.port != port) {
      FDFS_LOG_WARN("join rejected: %s already in group %s as %s",
                    addr.c_str(), group.c_str(), a.c_str());
      return std::nullopt;
    }
  }
  StorageNode& node = g.storages[addr];
  bool fresh = node.join_time == 0;
  node.ip = ip;
  node.port = port;
  node.store_path_count = store_path_count;
  // A brand-new server in a non-empty group must full-sync before serving
  // (WAIT_SYNC; promoted via SyncDestReq/SyncReport).  A disk-recovering
  // server is likewise held out of routing until its explicit done-notify.
  // A known server re-joining keeps an in-flight sync state; anything
  // else goes ACTIVE.
  if (recovering) {
    node.status = kWaitSync;
    node.sync_src_addr.clear();  // no auto-promotion path while rebuilding
    node.sync_until_ts = 0;
  } else if ((node.status == kWaitSync || node.status == kSyncing) &&
             node.sync_until_ts == kRecoveryHoldSentinel) {
    // Held for disk recovery, but the node rejoined WITHOUT the
    // recovering flag: its rebuild finished and only the done-notify
    // failed to reach this tracker.  Clear the hold — otherwise a
    // tracker that was down at notify time excludes the node from its
    // read routing forever.
    FDFS_LOG_INFO("storage %s rejoined healthy: clearing recovery hold",
                  addr.c_str());
    node.status = kActive;
    node.sync_until_ts = 0;
    node.sync_src_addr.clear();
  } else if (fresh && g.storages.size() > 1) {
    node.status = kWaitSync;
  } else if (node.status != kWaitSync && node.status != kSyncing) {
    node.status = kActive;
  }
  node.last_beat = now;
  if (fresh) node.join_time = now;
  FDFS_LOG_INFO("storage %s %s group %s (members=%zu)", addr.c_str(),
                fresh ? "joined" : "rejoined", group.c_str(),
                g.storages.size());
  if (events_ != nullptr)
    events_->Record(EventSeverity::kInfo,
                    fresh ? "storage.joined" : "storage.rejoined", addr,
                    "group=" + group +
                        " members=" + std::to_string(g.storages.size()));
  return Peers(group, addr);
}

std::vector<StorageNode> Cluster::Peers(const std::string& group,
                                        const std::string& exclude) const {
  std::vector<StorageNode> out;
  auto it = groups_.find(group);
  if (it == groups_.end()) return out;
  for (const auto& [addr, s] : it->second.storages)
    if (addr != exclude && s.status != kDeleted) out.push_back(s);
  return out;
}

bool Cluster::Beat(const std::string& group, const std::string& ip, int port,
                   const int64_t* stats, int nstats, int64_t now) {
  StorageNode* n = FindNode(group, ip + ":" + std::to_string(port));
  if (n == nullptr) return false;  // must JOIN first
  n->last_beat = now;
  if (n->status == kOffline) {
    FDFS_LOG_INFO("storage %s back ONLINE in group %s", n->Addr().c_str(),
                  group.c_str());
    if (events_ != nullptr)
      events_->Record(EventSeverity::kInfo, "storage.online", n->Addr(),
                      "group=" + group);
  }
  // A beat never promotes a full-syncing server — only sync progress does.
  if (n->status != kWaitSync && n->status != kSyncing) n->status = kActive;
  if (stats != nullptr && nstats > 0) {
    if (nstats > kBeatStatCount) nstats = kBeatStatCount;
    memcpy(n->stats, stats, sizeof(int64_t) * nstats);
  }
  return true;
}

bool Cluster::UpdateDiskUsage(const std::string& group, const std::string& ip,
                              int port, int64_t total_mb, int64_t free_mb) {
  StorageNode* n = FindNode(group, ip + ":" + std::to_string(port));
  if (n == nullptr) return false;
  n->total_mb = total_mb;
  n->free_mb = free_mb;
  return true;
}

bool Cluster::UpdateHealth(
    const std::string& group, const std::string& ip, int port,
    int64_t self_score,
    const std::vector<std::pair<std::string, int64_t>>& peers, int64_t now) {
  StorageNode* n = FindNode(group, ip + ":" + std::to_string(port));
  if (n == nullptr) return false;
  n->health_self = self_score;
  n->health_ts = now;
  // Replace, don't merge: the trailer carries the reporter's WHOLE
  // current table, so a peer it stopped talking to ages out here too.
  n->health_peer_scores.clear();
  for (const auto& [addr, score] : peers) n->health_peer_scores[addr] = score;
  return true;
}

bool Cluster::SyncReport(const std::string& group, const std::string& src,
                         const std::string& dest, int64_t ts) {
  StorageNode* n = FindNode(group, dest);
  if (n == nullptr) return false;
  int64_t& cur = n->synced_from[src];
  if (ts > cur) cur = ts;
  // Full-sync completion: once the assigned source has replayed history
  // past the negotiated until-timestamp, the dest starts serving
  // (upstream: sync_old_done flips in the source's mark, dest→ACTIVE).
  if ((n->status == kSyncing || n->status == kWaitSync) &&
      n->sync_src_addr == src && ts >= n->sync_until_ts) {
    n->status = kActive;
    FDFS_LOG_INFO("storage %s full-sync complete (src=%s ts=%lld): ACTIVE",
                  dest.c_str(), src.c_str(), static_cast<long long>(ts));
  }
  return true;
}

int Cluster::SyncDestReq(const std::string& group,
                         const std::string& dest_addr, int64_t now,
                         StorageNode* src, int64_t* until_ts) {
  StorageNode* n = FindNode(group, dest_addr);
  if (n == nullptr) return -1;
  if (n->status != kWaitSync && n->status != kSyncing) return 1;  // settled
  // Source pick: the longest-standing ACTIVE peer (upstream prefers the
  // server with the greatest sync authority; join order is our proxy).
  GroupInfo* g = FindGroup(group);
  const StorageNode* pick = nullptr;
  for (const auto& [addr, s] : g->storages) {
    if (addr == dest_addr || s.status != kActive) continue;
    if (pick == nullptr || s.join_time < pick->join_time) pick = &s;
  }
  if (pick == nullptr) {
    // No ACTIVE peer to copy from — this is effectively the first usable
    // server in the group; there is nothing to wait for.
    n->status = kActive;
    n->sync_src_addr.clear();
    n->sync_until_ts = 0;
    return 1;
  }
  // Idempotent re-ask keeps the original until_ts (a crashed dest must not
  // move its own goalpost forward and miss files created in between).
  if (n->sync_src_addr != pick->Addr() || n->sync_until_ts == 0) {
    n->sync_src_addr = pick->Addr();
    n->sync_until_ts = now;
  }
  n->status = kSyncing;
  *src = *pick;
  *until_ts = n->sync_until_ts;
  return 0;
}

std::optional<int64_t> Cluster::SyncSrcReq(const std::string& group,
                                           const std::string& src_addr,
                                           const std::string& dest_addr) const {
  auto git = groups_.find(group);
  if (git == groups_.end()) return std::nullopt;
  auto it = git->second.storages.find(dest_addr);
  if (it == git->second.storages.end()) return std::nullopt;
  if (it->second.sync_src_addr != src_addr) return std::nullopt;
  return it->second.sync_until_ts;
}

void Cluster::EnsureTrunkServer(GroupInfo* g) {
  if (!trunk_enabled_) return;
  if (!g->trunk_addr.empty()) {
    auto it = g->storages.find(g->trunk_addr);
    if (it != g->storages.end() && it->second.status == kActive) return;
  }
  // Lowest ACTIVE member address wins: a pure function of shared state,
  // so every tracker elects the SAME trunk server without coordination
  // (join timestamps would diverge across trackers' local clocks).
  const StorageNode* pick = nullptr;
  for (const auto& [addr, s] : g->storages) {
    if (s.status != kActive) continue;
    if (pick == nullptr || addr < pick->Addr()) pick = &s;
  }
  std::string chosen = pick == nullptr ? "" : pick->Addr();
  if (chosen != g->trunk_addr) {
    g->trunk_epoch++;
    FDFS_LOG_INFO("group %s trunk server: %s -> %s (epoch %lld)",
                  g->name.c_str(),
                  g->trunk_addr.empty() ? "(none)" : g->trunk_addr.c_str(),
                  chosen.empty() ? "(none)" : chosen.c_str(),
                  static_cast<long long>(g->trunk_epoch));
    g->trunk_addr = chosen;
  }
}

std::string Cluster::TrunkServer(const std::string& group) {
  GroupInfo* g = FindGroup(group);
  if (g == nullptr) return "";
  EnsureTrunkServer(g);
  return g->trunk_addr;
}

void Cluster::AdoptTrunkServer(const std::string& group,
                               const std::string& addr, int64_t epoch) {
  GroupInfo* g = FindGroup(group);
  if (g == nullptr) return;
  if (g->trunk_addr != addr || g->trunk_epoch != epoch) {
    FDFS_LOG_INFO("group %s trunk server adopted from leader: %s -> %s "
                  "(epoch %lld)", g->name.c_str(),
                  g->trunk_addr.empty() ? "(none)" : g->trunk_addr.c_str(),
                  addr.empty() ? "(none)" : addr.c_str(),
                  static_cast<long long>(epoch));
    g->trunk_addr = addr;
    // Followers mirror the LEADER's epoch (bumping locally would
    // diverge the fencing token across trackers).
    g->trunk_epoch = epoch;
  }
}

int64_t Cluster::TrunkEpoch(const std::string& group) const {
  auto it = groups_.find(group);
  return it == groups_.end() ? 0 : it->second.trunk_epoch;
}

std::string Cluster::CurrentTrunkAddr(const std::string& group) const {
  auto it = groups_.find(group);
  return it == groups_.end() ? "" : it->second.trunk_addr;
}

bool Cluster::SetTrunkServer(const std::string& group,
                             const std::string& addr) {
  GroupInfo* g = FindGroup(group);
  if (g == nullptr) return false;
  auto it = g->storages.find(addr);
  if (it == g->storages.end() || it->second.status != kActive) return false;
  if (g->trunk_addr != addr) g->trunk_epoch++;
  g->trunk_addr = addr;
  FDFS_LOG_INFO("group %s trunk server set to %s by operator (epoch %lld)",
                group.c_str(), addr.c_str(),
                static_cast<long long>(g->trunk_epoch));
  return true;
}

int Cluster::ReenterSync(const std::string& group,
                         const std::string& dest_addr, int64_t now,
                         StorageNode* src) {
  StorageNode* n = FindNode(group, dest_addr);
  if (n == nullptr) return -1;
  n->synced_from.clear();  // wiped disk: nothing previously synced survives
  n->status = kWaitSync;
  n->sync_src_addr.clear();
  n->sync_until_ts = 0;
  int64_t until = 0;
  int rc = SyncDestReq(group, dest_addr, now, src, &until);
  if (rc == 0) {
    // Hold promotion for the explicit done-notify: the source's caught-up
    // reports only cover NEW writes, not the re-download of history.
    n->sync_until_ts = kRecoveryHoldSentinel;
  } else if (rc == 1 && FindGroup(group)->storages.size() > 1) {
    // No ACTIVE source YET, but peers exist (whole-group restart): the
    // wiped node must NOT go ACTIVE — an empty disk would take reads and
    // even win trunk-server election.  Hold WAIT_SYNC; the recovery
    // thread retries until a peer comes up.
    n->status = kWaitSync;
    return 2;
  }
  return rc;
}

bool Cluster::SyncNotify(const std::string& group,
                         const std::string& dest_addr) {
  StorageNode* n = FindNode(group, dest_addr);
  if (n == nullptr) return false;
  if (n->status == kWaitSync || n->status == kSyncing) {
    n->status = kActive;
    FDFS_LOG_INFO("storage %s promoted ACTIVE by sync notify", dest_addr.c_str());
  }
  return true;
}

int Cluster::CheckAlive(int64_t now, int64_t timeout_s) {
  int transitions = 0;
  for (auto& [gname, g] : groups_) {
    for (auto& [addr, s] : g.storages) {
      if (s.status == kActive && now - s.last_beat > timeout_s) {
        s.status = kOffline;
        ++transitions;
        FDFS_LOG_WARN("storage %s in group %s OFFLINE (silent %llds)",
                      addr.c_str(), gname.c_str(),
                      static_cast<long long>(now - s.last_beat));
        if (events_ != nullptr)
          events_->Record(
              EventSeverity::kWarn, "storage.offline", addr,
              "group=" + gname +
                  " silent_s=" + std::to_string(now - s.last_beat));
      }
    }
    // A syncing dest whose assigned source died would otherwise wait
    // forever (promotion requires a report FROM that source).  Re-point it
    // at a live peer; if it has become the group's only member (operator
    // deleted the dead source), there is nothing left to copy — promote.
    for (auto& [addr, s] : g.storages) {
      if (s.status != kSyncing && s.status != kWaitSync) continue;
      if (g.storages.size() == 1) {
        s.status = kActive;
        s.sync_src_addr.clear();
        FDFS_LOG_WARN("storage %s promoted ACTIVE: sole group member",
                      addr.c_str());
        continue;
      }
      if (s.sync_src_addr.empty()) continue;  // negotiation not started yet
      auto src_it = g.storages.find(s.sync_src_addr);
      if (src_it != g.storages.end() && src_it->second.status == kActive)
        continue;
      const StorageNode* pick = nullptr;
      for (const auto& [a2, s2] : g.storages) {
        if (a2 == addr || s2.status != kActive) continue;
        if (pick == nullptr || s2.join_time < pick->join_time) pick = &s2;
      }
      if (pick != nullptr) {
        FDFS_LOG_WARN("full-sync source %s for %s is gone: reassigned to %s",
                      s.sync_src_addr.c_str(), addr.c_str(),
                      pick->Addr().c_str());
        s.sync_src_addr = pick->Addr();  // original until_ts stays
      }
    }
  }
  return transitions;
}

bool Cluster::RenameStorage(const std::string& group,
                            const std::string& old_addr,
                            const std::string& new_ip, int port) {
  GroupInfo* g = FindGroup(group);
  if (g == nullptr) return false;
  auto it = g->storages.find(old_addr);
  if (it == g->storages.end()) return false;
  std::string new_addr = new_ip + ":" + std::to_string(port);
  if (new_addr == old_addr) return true;
  if (g->storages.count(new_addr)) return false;  // identity collision
  StorageNode node = std::move(it->second);
  g->storages.erase(it);
  node.ip = new_ip;
  node.port = port;
  g->storages[new_addr] = std::move(node);
  // Rewrite every reference to the old identity.
  for (auto& [addr2, s] : g->storages) {
    auto sf = s.synced_from.find(old_addr);
    if (sf != s.synced_from.end()) {
      int64_t ts = sf->second;
      s.synced_from.erase(sf);
      int64_t& cur = s.synced_from[new_addr];
      if (ts > cur) cur = ts;
    }
    if (s.sync_src_addr == old_addr) s.sync_src_addr = new_addr;
  }
  if (g->trunk_addr == old_addr) g->trunk_addr = new_addr;
  FDFS_LOG_INFO("storage %s renamed to %s in group %s", old_addr.c_str(),
                new_addr.c_str(), group.c_str());
  return true;
}

bool Cluster::DeleteStorage(const std::string& group, const std::string& addr) {
  GroupInfo* g = FindGroup(group);
  if (g == nullptr) return false;
  auto it = g->storages.find(addr);
  if (it == g->storages.end()) return false;
  if (it->second.status == kActive) return false;  // only non-active removable
  g->storages.erase(it);
  return true;
}

// -- routing --------------------------------------------------------------

GroupState Cluster::PlacementState(const std::string& group) const {
  if (placement_ == nullptr) return GroupState::kActive;
  const PlacementTable::Entry* e = placement_->Find(group);
  return e == nullptr ? GroupState::kActive : e->state;
}

std::optional<StoreTarget> Cluster::QueryStore(const std::string& group_hint,
                                               const std::string& key) {
  // Pick a group by policy over groups with >=1 ACTIVE member.  Groups a
  // placement epoch marks draining/retired take NO new writes (they keep
  // serving reads — QueryFetch/QueryUpdate do not filter).
  std::vector<GroupInfo*> candidates;
  for (auto& [name, g] : groups_)
    if (g.ActiveCount() > 0 && PlacementState(name) == GroupState::kActive)
      candidates.push_back(&g);
  if (candidates.empty()) return std::nullopt;

  GroupInfo* g = nullptr;
  if (!group_hint.empty()) {
    g = FindGroup(group_hint);
    if (g == nullptr || g->ActiveCount() == 0) return std::nullopt;
    if (PlacementState(group_hint) != GroupState::kActive)
      return std::nullopt;  // pinned uploads cannot dodge a drain
  } else if (store_lookup_ == 1 && !store_group_.empty()) {
    g = FindGroup(store_group_);
    if (g == nullptr || g->ActiveCount() == 0) return std::nullopt;
  } else if (store_lookup_ == 2) {
    // load balance: most free space (reference: store_lookup=2), with
    // hysteresis — the previous pick is kept until a rival leads by more
    // than balance_hysteresis_mb_, so two near-equal groups stop
    // flapping the target every upload.
    GroupInfo* best = nullptr;
    GroupInfo* prev = nullptr;
    for (GroupInfo* c : candidates) {
      if (best == nullptr || c->FreeMb() > best->FreeMb()) best = c;
      if (c->name == balance_group_) prev = c;
    }
    g = (prev != nullptr && best->FreeMb() <= prev->FreeMb() +
                                                  balance_hysteresis_mb_)
            ? prev
            : best;
    balance_group_ = g->name;
  } else if (store_lookup_ == 3 && placement_ != nullptr && !key.empty()) {
    // Consistent placement: jump-hash the client key over the epoch's
    // ACTIVE list.  The hashed group not being servable right now (no
    // ACTIVE member) is an honest routing failure — falling back to a
    // different group would scatter the key's replicas across homes.
    g = FindGroup(placement_->PickGroup(key));
    if (g == nullptr || g->ActiveCount() == 0) return std::nullopt;
  } else {
    // round-robin — also the keyless fallback under store_lookup = 3
    // (legacy clients that ship no placement key still upload).
    g = candidates[rr_group_++ % candidates.size()];
  }

  // Round-robin over ACTIVE members of the group.
  std::vector<const StorageNode*> active;
  for (const auto& [addr, s] : g->storages)
    if (s.status == kActive) active.push_back(&s);
  if (active.empty()) return std::nullopt;
  const StorageNode* pick = active[g->rr_write++ % active.size()];
  StoreTarget t;
  t.group = g->name;
  t.ip = pick->ip;
  t.port = pick->port;
  t.store_path_index = 0xFF;
  return t;
}

// Candidates for a read: the source server itself, or any replica whose
// synced_from the source has passed the file's create time (SURVEY §3.2
// routing).  Shared by the ONE (round-robin pick) and ALL variants.
static std::vector<const StorageNode*> FetchCandidates(
    const GroupInfo& g, const std::string& source_ip, int64_t create_ts) {
  std::vector<const StorageNode*> ok;
  for (const auto& [addr, s] : g.storages) {
    if (s.status != kActive) continue;
    if (s.ip == source_ip) {
      ok.push_back(&s);
      continue;
    }
    for (const auto& [src, ts] : s.synced_from) {
      if (src.rfind(source_ip + ":", 0) == 0 && ts >= create_ts) {
        ok.push_back(&s);
        break;
      }
    }
  }
  return ok;
}

std::optional<StoreTarget> Cluster::QueryFetch(const std::string& group,
                                               const std::string& remote) {
  GroupInfo* g = FindGroup(group);
  if (g == nullptr) return std::nullopt;
  auto parts = DecodeFileId(group + "/" + remote);
  if (!parts.has_value()) return std::nullopt;
  auto ok = FetchCandidates(*g, UnpackIp(parts->source_ip),
                            parts->create_timestamp);
  if (ok.empty()) return std::nullopt;
  const StorageNode* pick = ok[g->rr_read++ % ok.size()];
  StoreTarget t;
  t.group = group;
  t.ip = pick->ip;
  t.port = pick->port;
  return t;
}

std::vector<StoreTarget> Cluster::QueryFetchAll(const std::string& group,
                                                const std::string& remote) {
  std::vector<StoreTarget> out;
  GroupInfo* g = FindGroup(group);
  if (g == nullptr) return out;
  auto parts = DecodeFileId(group + "/" + remote);
  if (!parts.has_value()) return out;
  for (const StorageNode* s :
       FetchCandidates(*g, UnpackIp(parts->source_ip),
                       parts->create_timestamp)) {
    StoreTarget t;
    t.group = group;
    t.ip = s->ip;
    t.port = s->port;
    out.push_back(std::move(t));
  }
  return out;
}

std::vector<StoreTarget> Cluster::QueryStoreAll(const std::string& group_hint,
                                                const std::string& key) {
  // Same group pick as QueryStore, but every ACTIVE member is returned
  // (upstream QUERY_STORE_*_ALL: client chooses / retries among them).
  std::vector<StoreTarget> out;
  auto one = QueryStore(group_hint, key);
  if (!one.has_value()) return out;
  GroupInfo* g = FindGroup(one->group);
  for (const auto& [addr, s] : g->storages) {
    if (s.status != kActive) continue;
    StoreTarget t;
    t.group = g->name;
    t.ip = s.ip;
    t.port = s.port;
    t.store_path_index = 0xFF;
    out.push_back(std::move(t));
  }
  return out;
}

std::optional<StoreTarget> Cluster::QueryUpdate(const std::string& group,
                                                const std::string& remote) {
  // Mutations go to the source server when alive (reference:
  // tracker_deal_service_query_fetch_update update path).
  GroupInfo* g = FindGroup(group);
  if (g == nullptr) return std::nullopt;
  auto parts = DecodeFileId(group + "/" + remote);
  if (!parts.has_value()) return std::nullopt;
  std::string source_ip = UnpackIp(parts->source_ip);
  for (const auto& [addr, s] : g->storages) {
    if (s.status == kActive && s.ip == source_ip) {
      StoreTarget t;
      t.group = group;
      t.ip = s.ip;
      t.port = s.port;
      return t;
    }
  }
  return QueryFetch(group, remote);  // source down: any synced replica
}

// -- introspection --------------------------------------------------------

static void AppendStorageJson(std::string* out, const StorageNode& s,
                              const std::string& id) {
  char buf[1100];
  std::snprintf(
      buf, sizeof(buf),
      "{\"id\":\"%s\",\"ip\":\"%s\",\"port\":%d,\"status\":%d,"
      "\"store_paths\":%d,"
      "\"join_time\":%lld,\"last_beat\":%lld,\"total_mb\":%lld,"
      "\"free_mb\":%lld,\"upload\":[%lld,%lld],\"download\":[%lld,%lld],"
      "\"delete\":[%lld,%lld],\"dedup_hits\":%lld,\"dedup_bytes_saved\":%lld}",
      id.c_str(), s.ip.c_str(), s.port, s.status, s.store_path_count,
      static_cast<long long>(s.join_time), static_cast<long long>(s.last_beat),
      static_cast<long long>(s.total_mb), static_cast<long long>(s.free_mb),
      static_cast<long long>(s.stats[0]), static_cast<long long>(s.stats[1]),
      static_cast<long long>(s.stats[2]), static_cast<long long>(s.stats[3]),
      static_cast<long long>(s.stats[4]), static_cast<long long>(s.stats[5]),
      static_cast<long long>(s.stats[16]),
      static_cast<long long>(s.stats[17]));
  *out += buf;
}

static std::string GroupJson(const GroupInfo& g, GroupState state) {
  char buf[352];
  std::snprintf(buf, sizeof(buf),
                "{\"name\":\"%s\",\"members\":%zu,\"active\":%d,"
                "\"free_mb\":%lld,\"trunk_server\":\"%s\",\"state\":\"%s\"}",
                g.name.c_str(), g.storages.size(), g.ActiveCount(),
                static_cast<long long>(g.FreeMb()), g.trunk_addr.c_str(),
                GroupStateName(state));
  return buf;
}

std::string Cluster::GroupsJson() const {
  std::string out = "[";
  bool first = true;
  for (const auto& [name, g] : groups_) {
    if (!first) out += ",";
    first = false;
    out += GroupJson(g, PlacementState(name));
  }
  return out + "]";
}

std::string Cluster::OneGroupJson(const std::string& group) const {
  auto it = groups_.find(group);
  return it == groups_.end() ? "{}"
                             : GroupJson(it->second, PlacementState(group));
}

std::string Cluster::StoragesJson(const std::string& group) const {
  auto it = groups_.find(group);
  std::string out = "[";
  if (it != groups_.end()) {
    bool first = true;
    for (const auto& [addr, s] : it->second.storages) {
      if (!first) out += ",";
      first = false;
      AppendStorageJson(&out, s, StorageIdForIp(s.ip));
    }
  }
  return out + "]";
}

// Group names, trunk addrs, and storage ids arrive off the wire as
// arbitrary bytes; interpolating them raw would let one hostile JOIN
// break cluster_stat's JSON for every monitor client.
static std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char ch : s) {
    if (ch == '"' || ch == '\\') {
      out.push_back('\\');
      out.push_back(ch);
    } else if (static_cast<unsigned char>(ch) < 0x20) {
      char buf[8];
      std::snprintf(buf, sizeof(buf), "\\u%04x", ch & 0xFF);
      out += buf;
    } else {
      out.push_back(ch);
    }
  }
  return out;
}

static const char* StatusName(int status) {
  switch (static_cast<StorageStatus>(status)) {
    case StorageStatus::kInit: return "INIT";
    case StorageStatus::kWaitSync: return "WAIT_SYNC";
    case StorageStatus::kSyncing: return "SYNCING";
    case StorageStatus::kIpChanged: return "IP_CHANGED";
    case StorageStatus::kDeleted: return "DELETED";
    case StorageStatus::kOffline: return "OFFLINE";
    case StorageStatus::kOnline: return "ONLINE";
    case StorageStatus::kActive: return "ACTIVE";
    case StorageStatus::kRecovery: return "RECOVERY";
    default: return "UNKNOWN";
  }
}

std::string Cluster::ClusterStatJson(int64_t now,
                                     const std::string& group) const {
  std::string out = "[";
  bool gfirst = true;
  char buf[512];
  for (const auto& [gname, g] : groups_) {
    if (!group.empty() && gname != group) continue;
    if (!gfirst) out += ",";
    gfirst = false;
    std::snprintf(buf, sizeof(buf),
                  "{\"name\":\"%s\",\"members\":%zu,\"active\":%d,"
                  "\"free_mb\":%lld,\"trunk_server\":\"%s\","
                  "\"trunk_epoch\":%lld,\"state\":\"%s\",\"storages\":[",
                  JsonEscape(g.name).c_str(), g.storages.size(),
                  g.ActiveCount(), static_cast<long long>(g.FreeMb()),
                  JsonEscape(g.trunk_addr).c_str(),
                  static_cast<long long>(g.trunk_epoch),
                  GroupStateName(PlacementState(gname)));
    out += buf;
    bool sfirst = true;
    for (const auto& [addr, s] : g.storages) {
      if (!sfirst) out += ",";
      sfirst = false;
      std::snprintf(
          buf, sizeof(buf),
          "{\"id\":\"%s\",\"ip\":\"%s\",\"port\":%d,\"status\":%d,"
          "\"status_name\":\"%s\",\"store_paths\":%d,\"join_time\":%lld,"
          "\"last_beat\":%lld,\"beat_age_s\":%lld,\"total_mb\":%lld,"
          "\"free_mb\":%lld,\"stats\":{",
          JsonEscape(StorageIdForIp(s.ip)).c_str(),
          JsonEscape(s.ip).c_str(), s.port, s.status,
          StatusName(s.status), s.store_path_count,
          static_cast<long long>(s.join_time),
          static_cast<long long>(s.last_beat),
          static_cast<long long>(s.last_beat > 0 ? now - s.last_beat : -1),
          static_cast<long long>(s.total_mb),
          static_cast<long long>(s.free_mb));
      out += buf;
      for (int i = 0; i < kBeatStatCount; ++i) {
        std::snprintf(buf, sizeof(buf), "%s\"%s\":%lld", i ? "," : "",
                      kBeatStatNames[i],
                      static_cast<long long>(s.stats[i]));
        out += buf;
      }
      out += "}}";
    }
    out += "]}";
  }
  return out + "]";
}

std::string Cluster::HealthMatrixJson(int64_t now,
                                      int64_t gray_threshold) const {
  // Differential verdict per node: what the node SAYS about itself
  // (health_self from its trailer) against what its group peers SAY
  // about it (average of their trailer scores naming its address).
  // Disagreement in one direction is the whole point — a gray node
  // keeps reporting itself healthy while every peer watches its RPCs
  // time out.
  std::string out = "[";
  bool first = true;
  char buf[256];
  for (const auto& [gname, g] : groups_) {
    for (const auto& [addr, s] : g.storages) {
      if (s.status == kDeleted) continue;
      int64_t sum = 0, reports = 0;
      for (const auto& [paddr, p] : g.storages) {
        if (paddr == addr || p.status == kDeleted) continue;
        auto it = p.health_peer_scores.find(addr);
        if (it == p.health_peer_scores.end()) continue;
        sum += it->second;
        ++reports;
      }
      int64_t peer_avg = reports > 0 ? sum / reports : -1;
      const char* verdict;
      if (s.health_self < 0 && peer_avg < 0)
        verdict = "unknown";
      else if (s.health_self >= 0 && s.health_self < gray_threshold)
        verdict = "sick";  // the node itself admits it
      else if (peer_avg >= 0 && peer_avg < gray_threshold)
        verdict = "gray";  // peers see what the node does not report
      else
        verdict = "ok";
      if (!first) out += ",";
      first = false;
      std::snprintf(buf, sizeof(buf),
                    "{\"group\":\"%s\",\"addr\":\"%s\",\"self\":%lld,"
                    "\"peer_avg\":%lld,\"reports\":%lld,\"verdict\":\"%s\","
                    "\"age_s\":%lld,\"peers\":{",
                    JsonEscape(gname).c_str(), JsonEscape(addr).c_str(),
                    static_cast<long long>(s.health_self),
                    static_cast<long long>(peer_avg),
                    static_cast<long long>(reports), verdict,
                    static_cast<long long>(
                        s.health_ts > 0 ? now - s.health_ts : -1));
      out += buf;
      bool pfirst = true;
      for (const auto& [paddr, score] : s.health_peer_scores) {
        std::snprintf(buf, sizeof(buf), "%s\"%s\":%lld", pfirst ? "" : ",",
                      JsonEscape(paddr).c_str(),
                      static_cast<long long>(score));
        pfirst = false;
        out += buf;
      }
      out += "}}";
    }
  }
  return out + "]";
}

// -- persistence ----------------------------------------------------------

bool Cluster::Save(const std::string& path) const {
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr) return false;
  for (const auto& [gname, g] : groups_) {
    fprintf(f, "group %s\n", gname.c_str());
    // "-" = no trunk server; the EPOCH is written regardless — fencing
    // tokens must stay monotonic across tracker restarts.
    fprintf(f, "trunk %s %lld\n",
            g.trunk_addr.empty() ? "-" : g.trunk_addr.c_str(),
            static_cast<long long>(g.trunk_epoch));
    for (const auto& [addr, s] : g.storages) {
      fprintf(f, "storage %s %d %d %d %lld %lld %lld %lld", s.ip.c_str(),
              s.port, s.status, s.store_path_count,
              static_cast<long long>(s.join_time),
              static_cast<long long>(s.last_beat),
              static_cast<long long>(s.total_mb),
              static_cast<long long>(s.free_mb));
      for (int i = 0; i < kBeatStatCount; ++i)
        fprintf(f, " %lld", static_cast<long long>(s.stats[i]));
      fprintf(f, "\n");
      for (const auto& [src, ts] : s.synced_from)
        fprintf(f, "sync %s %s %lld\n", addr.c_str(), src.c_str(),
                static_cast<long long>(ts));
      if (!s.sync_src_addr.empty())
        fprintf(f, "syncsrc %s %s %lld\n", addr.c_str(),
                s.sync_src_addr.c_str(),
                static_cast<long long>(s.sync_until_ts));
    }
  }
  fclose(f);
  return rename(tmp.c_str(), path.c_str()) == 0;
}

bool Cluster::Load(const std::string& path) {
  FILE* f = fopen(path.c_str(), "r");
  if (f == nullptr) return true;  // nothing saved yet
  char line[2048];
  std::string cur_group;
  while (fgets(line, sizeof(line), f) != nullptr) {
    char a[256], b[256];
    if (sscanf(line, "group %255s", a) == 1) {
      cur_group = a;
      groups_[cur_group].name = cur_group;
      continue;
    }
    long long ep = 0;
    if (sscanf(line, "trunk %255s %lld", a, &ep) >= 1 && !cur_group.empty() &&
        strncmp(line, "trunk ", 6) == 0) {
      groups_[cur_group].trunk_addr = strcmp(a, "-") == 0 ? "" : a;
      groups_[cur_group].trunk_epoch = ep;
      continue;
    }
    StorageNode s;
    long long jt, lb, tm, fm;
    int consumed = 0;
    if (sscanf(line, "storage %255s %d %d %d %lld %lld %lld %lld%n", a,
               &s.port, &s.status, &s.store_path_count, &jt, &lb, &tm, &fm,
               &consumed) == 8 &&
        !cur_group.empty()) {
      s.ip = a;
      s.join_time = jt;
      s.last_beat = lb;
      s.total_mb = tm;
      s.free_mb = fm;
      const char* p = line + consumed;
      for (int i = 0; i < kBeatStatCount; ++i) {
        long long v = 0;
        int adv = 0;
        if (sscanf(p, " %lld%n", &v, &adv) == 1) {
          s.stats[i] = v;
          p += adv;
        }
      }
      // Survivors of a tracker restart start OFFLINE until they beat again.
      if (s.status == kActive) s.status = kOffline;
      groups_[cur_group].storages[s.Addr()] = s;
      continue;
    }
    long long ts;
    // "syncsrc" MUST be tried before "sync": sscanf's literal 'sync'
    // matches the prefix of 'syncsrc' and would mis-parse those lines.
    if (sscanf(line, "syncsrc %255s %255s %lld", a, b, &ts) == 3 &&
        !cur_group.empty()) {
      auto it = groups_[cur_group].storages.find(a);
      if (it != groups_[cur_group].storages.end()) {
        it->second.sync_src_addr = b;
        it->second.sync_until_ts = ts;
      }
      continue;
    }
    if (sscanf(line, "sync %255s %255s %lld", a, b, &ts) == 3 &&
        !cur_group.empty()) {
      auto it = groups_[cur_group].storages.find(a);
      if (it != groups_[cur_group].storages.end())
        it->second.synced_from[b] = ts;
    }
  }
  fclose(f);
  FDFS_LOG_INFO("cluster state loaded: %zu groups", groups_.size());
  return true;
}

}  // namespace fdfs
