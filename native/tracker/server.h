// Tracker daemon service: dispatch + schedules.
//
// Reference: tracker/tracker_service.c (tracker_deal_task and the
// tracker_deal_* handler per opcode) + tracker/fdfs_trackerd.c (main).
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "common/eventlog.h"
#include "common/metrog.h"
#include "common/net.h"
#include "common/req_server.h"
#include "common/sloeval.h"
#include "common/stats.h"
#include "storage/admission.h"
#include "tracker/cluster.h"
#include "tracker/hotmap.h"
#include "tracker/relationship.h"

namespace fdfs {

struct TrackerConfig {
  std::string bind_addr;
  int port = 22122;
  std::string base_path;
  int store_lookup = 0;        // 0 rr, 1 specified, 2 load-balance, 3 jump
  std::string store_group;
  // store_lookup = 2 hysteresis: a rival group must lead the current
  // pick's free space by more than this before the target switches
  // (tracker.conf:placement_hysteresis_free_mb).
  int64_t placement_hysteresis_free_mb = 1024;
  // Rebalance migrator pacing, served to every storage via
  // kStorageParameterReq (tracker.conf:rebalance_bandwidth_mb_s;
  // 0 = unpaced).
  int rebalance_bandwidth_mb_s = 8;
  // Beat timeout => OFFLINE.  Must exceed the storage heartbeat default
  // (30s); upstream uses 100s.
  int check_active_interval_s = 100;
  int save_interval_s = 30;
  // Accept-time connection cap (tracker.conf:max_connections upstream);
  // past it the server answers one EBUSY header and closes.  0 = off.
  int max_connections = 256;
  std::string log_level = "info";
  std::string log_file;               // empty = stderr
  int64_t log_rotate_size = 256LL << 20;
  // Cluster-global storage parameters served via kStorageParameterReq
  // (storage_param_getter.c: every group member must agree on these).
  bool use_trunk_file = false;
  int slot_min_size = 256;             // bytes; files below never trunked
  int slot_max_size = 16 * 1024 * 1024;  // files above stored flat
  int64_t trunk_file_size = 64LL * 1024 * 1024;
  int64_t reserved_storage_space_mb = 0;
  // Every tracker in the cluster ("ip:port", including this one) for the
  // multi-tracker relationship (tracker_relationship.c).  Empty = single.
  std::vector<std::string> tracker_peers;
  // Server-ID aliasing (tracker.conf:use_storage_id + storage_ids.conf
  // "<id> <group> <ip>" lines): stable operator-facing names for storages
  // whose IPs may change.
  bool use_storage_id = false;
  std::string storage_ids_file;
  // Distributed tracing (common/trace.h): span ring capacity and the
  // slow-request threshold — any request slower than this is recorded
  // (even untraced) and logged as one structured JSON line.  0 = slow
  // gate off.
  int trace_buffer_size = 2048;
  int64_t slow_request_threshold_ms = 1000;
  // Flight recorder (common/eventlog.h): capacity of the bounded ring
  // of structured cluster events (membership transitions, slow
  // requests) dumped via TrackerCmd::kEventDump and on SIGUSR1.
  int event_buffer_size = 256;
  // Telemetry history + SLOs (OPERATIONS.md "Telemetry history, SLOs &
  // heat"): on-disk cap of the metrics journal behind kMetricsHistory
  // (0 = off), the journal/SLO tick cadence (0 = off), and an optional
  // conf/slo.conf-style rule override file.  The tracker has no heat
  // sketch — it routes by group, never by file-id payloads.
  int metrics_journal_mb = 4;
  int slo_eval_interval_s = 5;
  std::string slo_rules_file;
  // Sampling-profiler ceiling (common/profiler.h): maximum PROFILE_CTL
  // rate this daemon will arm.  0 (default) = profiler entirely off
  // (no signal handler, no slab; PROFILE_CTL answers ENOTSUP).
  int profile_max_hz = 0;
  // Gray-failure health (ISSUE 17; OPERATIONS.md "Health, probes & gray
  // failure"): the score below which HEALTH_MATRIX calls a node gray
  // (peers score it under this while its own trailer claims healthy)
  // or sick (its own score is under this).  Scores are 0..100.
  int health_gray_threshold = 60;
  // Admission control (ISSUE 19; OPERATIONS.md "Overload control &
  // request QoS"): the tracker runs the same ladder controller as the
  // storage daemon on its single loop, so expensive dumps (born bulk
  // per DefaultTrackerPriorityClass) shed before beats and routing
  // queries queue behind them.  No dio/in-flight signals here — loop
  // lag and SLO breaches drive the ladder.
  bool admission_control = true;
  int admission_tighten_pct = 90;
  int admission_relax_pct = 45;
  int64_t admission_loop_lag_high_ms = 100;
  int64_t admission_retry_after_ms = 500;
  // Elastic hot replication (ISSUE 20; OPERATIONS.md "Elastic hot
  // replication"): cluster-wide read EWMA thresholds (reads/s) for
  // promoting a file to extra replica groups and demoting it back —
  // demote must sit well under promote (hysteresis) so the map cannot
  // flap.  0 promote threshold = feature off (the default).
  int hot_promote_threshold = 0;
  int hot_demote_threshold = 0;
  int hot_max_extra_replicas = 2;
  int hot_map_capacity = 128;
};

class TrackerServer {
 public:
  explicit TrackerServer(TrackerConfig cfg);
  bool Init(std::string* error);
  void Run();
  void Stop();
  EventLoop& loop() { return loop_; }
  Cluster& cluster() { return *cluster_; }
  RelationshipManager* relationship() { return relationship_.get(); }
  void DumpState();  // SIGUSR1 (tracker_dump.c analogue)

 private:
  std::pair<uint8_t, std::string> Handle(uint8_t cmd, const std::string& body,
                                         const std::string& peer_ip);
  // Trunk-server resolution for the beat trailer: the leader elects, a
  // follower adopts the leader's answer (cached briefly) and NEVER elects
  // locally — independent elections from transiently-diverged ACTIVE sets
  // can double-allocate trunk slots.
  std::string ResolveTrunkServer(const std::string& group);
  // Placement epoch plumbing (store_lookup = 3 subsystem).  The leader
  // owns transitions (admin opcodes, join appends, auto-retire); a
  // follower refreshes its adopted copy from the leader at most once a
  // second (the ResolveTrunkServer discipline — stale-but-consistent).
  void MaybeAdoptPlacement();
  // QUERY_PLACEMENT response body: epoch entries + each group's ACTIVE
  // members as routing hints.
  std::string PackPlacement() const;
  // Leader timer: a draining group whose every ACTIVE member reports
  // rebalance done (and nothing pending) retires out of the epoch.
  void MaybeAutoRetire();

  TrackerConfig cfg_;
  std::map<std::string, int64_t> trunk_fetched_ms_;  // follower cache age
  std::unique_ptr<TraceRing> trace_;  // span buffer behind kTraceDump
  // Flight recorder behind kEventDump + the SIGUSR1 dump.
  std::unique_ptr<EventLog> events_;
  // Saturation telemetry behind the new kStat opcode (ISSUE 6): the
  // tracker's event-loop lag, dispatched ops, live connections, and
  // aggregate request accounting — same registry JSON contract as the
  // storage daemon's STAT.
  StatsRegistry registry_;
  // Telemetry history + SLO engine (ISSUE 8): the journal persists one
  // registry snapshot per tick (kMetricsHistory dumps a window of
  // them); the evaluator emits slo.breach/recovered into events_.
  std::unique_ptr<MetricsJournal> metrics_;
  std::unique_ptr<SloEvaluator> slo_;
  // Admission gate (ISSUE 19): consulted by the RequestServer before
  // every dispatch; ticked with the SLO engine from the same snapshots.
  std::unique_ptr<AdmissionController> admission_;
  StatsSnapshot last_tick_snap_;
  bool have_tick_snap_ = false;
  int64_t last_tick_mono_us_ = 0;
  void MetricsTick();
  // Loop duty cycle (nio.loop_busy_pct.main): the iteration hook
  // accumulates busy time, the tick publishes the per-tick delta.
  std::atomic<int64_t> loop_busy_us_{0};
  int64_t loop_busy_last_ = 0;
  StatHistogram* hist_nio_lag_ = nullptr;
  std::atomic<int64_t>* ctr_nio_dispatched_ = nullptr;
  std::atomic<int64_t>* ctr_requests_ = nullptr;
  std::atomic<int64_t>* ctr_errors_ = nullptr;
  StatHistogram* hist_request_us_ = nullptr;
  std::unique_ptr<Cluster> cluster_;
  std::unique_ptr<PlacementTable> placement_;
  std::string placement_path_;
  int64_t placement_fetched_ms_ = 0;  // follower adoption throttle
  // Elastic hot replication (ISSUE 20): the leader's promotion map plus
  // its heat ledger; followers adopt published entries from the leader
  // (MaybeAdoptHotMap) and fold beats locally for failover warmth.
  std::unique_ptr<HotMap> hotmap_;
  std::string hotmap_path_;
  int64_t hotmap_fetched_ms_ = 0;
  void MaybeAdoptHotMap();
  // Under-loaded active groups != home for a promotion: fewest existing
  // hot assignments first, most free space second.
  std::vector<std::string> PickHotTargets(const std::string& home, int want);
  std::unique_ptr<RelationshipManager> relationship_;
  EventLoop loop_;
  std::unique_ptr<RequestServer> server_;
  std::string state_path_;
  std::string changelog_path_;  // identity changes (storage_changelog_req)
};

}  // namespace fdfs
