// Placement epoch for store_lookup = 3 (consistent jump-hash placement;
// fastdfs_tpu extension, SURVEY §0 "scale by adding groups").
//
// The epoch is the ORDERED list of groups the cluster has ever seen plus
// each group's lifecycle state (active / draining / retired), stamped
// with a version that bumps on every change.  Order is the contract:
// groups append on first join and never reorder or compact, so
// jump_hash(sha1(key), n_active) over the active sublist moves only
// ~1/(N+1) of keys when group N+1 joins (arXiv:1406.2294), and a
// draining group's files have a deterministic re-placement target that
// the tracker, the storage-side rebalance migrator, and a
// placement-routing Python client all compute independently.
//
// Single-threaded by design: all mutation and reads happen on the
// tracker's event loop (like Cluster), so there is no mutex here.  The
// table persists under base_path/data/placement.dat and is served to
// clients/storages via TrackerCmd::kQueryPlacement; followers adopt the
// leader's table wholesale (Adopt) instead of mutating locally.
#pragma once

#include <cstdint>
#include <string>
#include <string_view>
#include <vector>

namespace fdfs {

// Wire values (QUERY_PLACEMENT entry state byte; protocol.py contract).
enum class GroupState : uint8_t {
  kActive = 0,    // placed by jump hash, serves reads + writes
  kDraining = 1,  // no new writes; reads + replication continue; migrating
  kRetired = 2,   // drain finished: out of the hash domain, no data left
};

const char* GroupStateName(GroupState s);

class PlacementTable {
 public:
  struct Entry {
    std::string group;
    GroupState state = GroupState::kActive;
  };

  // Append-on-first-join (Cluster::Join hook).  Returns true when the
  // group was new (version bumped) — order preserved forever after.
  bool EnsureGroup(const std::string& group);

  // Admin transitions (GROUP_DRAIN / GROUP_REACTIVATE / auto-retire).
  // Errno-style returns: 0 ok (idempotent repeats included), 2 unknown
  // group, 22 invalid transition (reactivating a retired group is the
  // one refused move — its data is gone, re-adding must re-join).
  int Drain(const std::string& group);
  int Reactivate(const std::string& group);
  int Retire(const std::string& group);

  const Entry* Find(const std::string& group) const;
  // Groups currently in the jump-hash domain, in epoch order.
  std::vector<std::string> ActiveGroups() const;
  // jump_hash(sha1(key)) over ActiveGroups(); "" when none are active.
  std::string PickGroup(std::string_view key) const;

  int64_t version() const { return version_; }
  const std::vector<Entry>& entries() const { return entries_; }

  // QUERY_PLACEMENT response body: 8B version + 8B entry count + per
  // entry (16B group + 1B state + 8B member count + per member (16B ip
  // + 8B port)).  Members (a group's ACTIVE storages) come from the
  // caller because membership lives in Cluster, not here.
  struct WireMember {
    std::string ip;
    int port = 0;
  };
  std::string PackWire(
      const std::vector<std::vector<WireMember>>& members_per_entry) const;
  // Follower adoption: parse a leader's PackWire body and replace the
  // whole table (members are routing hints for clients; the follower
  // keeps only the epoch).  False on a malformed body (table untouched).
  bool AdoptWire(const std::string& body);

  // Text persistence under the tracker's base_path (atomic tmp+rename,
  // the Cluster::Save discipline).  Load of a missing file is OK-empty.
  bool Save(const std::string& path) const;
  bool Load(const std::string& path);

 private:
  Entry* FindMutable(const std::string& group);
  std::vector<Entry> entries_;
  int64_t version_ = 0;
};

}  // namespace fdfs
