// Elastic hot-replication map (ROADMAP item 3: act on the heat the
// cluster already sees).  The leader folds per-node HEAT_TOP beat
// trailers into a windowed, counter-reset-clamped ledger, keeps a read
// EWMA per key, and promotes keys whose cluster-wide rate crosses
// hot_promote_threshold to extra replica groups — demoting with
// hysteresis when the EWMA decays below hot_demote_threshold, so the
// map cannot flap (the SLO/admission discipline).
//
// Entry lifecycle is the verify-then-publish contract the routed read
// path depends on:
//
//   pending   — targets chosen, replicate tasks flowing to the home
//               group's elected member; NOT visible to clients.
//   published — fan-out byte-verified and acked; version bumped; entry
//               served in full snapshots and deltas.
//   retiring  — tombstone published (version bump) but extra copies
//               still on disk; drop tasks are issued only on a LATER
//               policy tick, so every client polling at the map cadence
//               sees the route die one epoch before the bytes do.
//   (purged)  — drop acked; changelog keeps the tombstone for deltas.
//
// Single-threaded by design, like PlacementTable: all calls happen on
// the tracker's event loop.  Persists next to placement.dat
// (base_path/data/hotmap.dat, atomic tmp+rename); followers rebuild
// from their own beats after failover, so persistence is a warm-start
// hint rather than a correctness requirement.
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <string>
#include <vector>

#include "common/heatwire.h"

namespace fdfs {

class HotMap {
 public:
  struct Config {
    double promote_threshold = 0;  // reads/s; 0 disables promotion
    double demote_threshold = 0;   // reads/s; must stay < promote
    int max_extra_replicas = 2;
    int capacity = 128;        // max pending+published+retiring entries
    double ewma_alpha = 0.3;   // per-tick smoothing
  };

  enum class State : uint8_t { kPending = 0, kPublished = 1, kRetiring = 2 };

  struct Entry {
    std::string key;                  // "<home group>/<remote filename>"
    std::vector<std::string> groups;  // extra replica groups (assignment)
    State state = State::kPending;
    double ewma = 0;                // cluster-wide reads/s
    int64_t published_version = 0;  // map version that published the entry
    int64_t retired_version = 0;    // map version of the tombstone
    int64_t retire_tick = 0;        // policy tick that demoted it
  };

  explicit HotMap(const Config& cfg) : cfg_(cfg) {}

  // Fold one node's cumulative heat snapshot (beat trailer) into the
  // window.  node is "ip:port"; per-key deltas are clamped at zero and a
  // shrinking counter (daemon restart) is treated as starting over — the
  // monitor.top_rates reset discipline.  Keys naming a published extra
  // replica are credited to the home key, so a routed read cannot
  // cascade-promote its own copy.
  void NoteHeat(const std::string& node,
                const std::vector<HeatTrailerEntry>& entries);

  // One policy pass (each metrics tick): fold the window into EWMAs,
  // then — only when run_policy (leader) — promote and demote.
  // pick_targets(home_group, want) returns up to `want` under-loaded
  // active groups != home (empty means defer the promotion — no
  // capacity right now).  Followers fold with run_policy=false so their
  // ledgers stay warm for failover without diverging the map.
  void Tick(double dt_s,
            const std::function<std::vector<std::string>(
                const std::string& home_group, int want)>& pick_targets,
            bool run_policy = true);

  // Replicate tasks for pending entries plus drop tasks for retiring
  // entries whose tombstone is at least one tick old, restricted to keys
  // homed in `group`.  Re-issued every beat until acked (idempotent).
  std::vector<HotTask> TasksForGroup(const std::string& group) const;

  // HOT_FANOUT_DONE replicate ack: publishes the entry (version bump)
  // once every assigned group is byte-verified.  False = unknown key or
  // verified set short (entry stays pending; tasks keep flowing).
  bool AckReplicate(const std::string& key,
                    const std::vector<std::string>& groups);
  // Drop ack: purge the retiring entry.  False = unknown key.
  bool AckDrop(const std::string& key);

  // QUERY_HOT_MAP body.  since_version < 0 → full snapshot (published
  // entries only).  Otherwise a delta of changelog records newer than
  // since_version (latest per key wins; empty groups = tombstone) — or a
  // full snapshot when the changelog no longer reaches back that far.
  std::string PackWire(int64_t since_version) const;

  // Follower adoption (the MaybeAdoptPlacement discipline): replace the
  // whole published set with a leader full snapshot.  False on a
  // malformed or non-full body (map untouched).
  bool AdoptFull(const std::string& body);

  // Extra-replica assignments per target group (pending + published +
  // retiring), for the under-loaded-target spread heuristic.
  std::map<std::string, int64_t> GroupLoad() const;

  bool Save(const std::string& path) const;
  bool Load(const std::string& path);

  int64_t version() const { return version_; }
  const std::map<std::string, Entry>& entries() const { return entries_; }
  // Home-group published routes for a key, for gauges/tests.
  const Entry* Find(const std::string& key) const;
  int64_t promotions_total() const { return promotions_total_; }
  int64_t demotions_total() const { return demotions_total_; }
  int64_t tracked_keys() const { return static_cast<int64_t>(ledger_.size()); }
  int64_t CountState(State s) const;

 private:
  struct LedgerRow {
    double ewma = 0;
    int64_t window_hits = 0;
    int64_t window_bytes = 0;
  };
  struct ChangeRec {
    int64_t version = 0;
    std::string key;
    std::vector<std::string> groups;  // empty = tombstone
  };

  void RecordChange(const std::string& key,
                    const std::vector<std::string>& groups);
  std::string HomeGroup(const std::string& key) const;

  Config cfg_;
  int64_t version_ = 0;
  int64_t tick_ = 0;
  int64_t promotions_total_ = 0;
  int64_t demotions_total_ = 0;
  std::map<std::string, Entry> entries_;
  std::map<std::string, LedgerRow> ledger_;
  // node -> key -> last cumulative {hits, bytes} snapshot.
  std::map<std::string, std::map<std::string, std::pair<int64_t, int64_t>>>
      last_seen_;
  // "extra_group/remote" -> home key, for heat canonicalization.
  std::map<std::string, std::string> alias_;
  std::vector<ChangeRec> changelog_;
  // Deltas are answerable only for since_version >= trimmed_below_;
  // older pollers get a full snapshot.
  int64_t trimmed_below_ = 0;
};

}  // namespace fdfs
