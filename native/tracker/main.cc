// fdfs_trackerd — tracker daemon launcher.
// Reference: tracker/fdfs_trackerd.c:main().
#include <signal.h>

#include <cstdio>

#include "common/ini.h"
#include "common/fsutil.h"
#include "common/log.h"
#include "tracker/server.h"

static volatile sig_atomic_t g_stop_flag = 0;
static volatile sig_atomic_t g_dump_flag = 0;

static void OnSignal(int sig) {
  if (sig == SIGUSR1) {
    g_dump_flag = 1;
  } else {
    g_stop_flag = 1;
  }
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <tracker.conf>\n", argv[0]);
    return 2;
  }
  fdfs::IniConfig ini;
  std::string err;
  if (!ini.LoadFile(argv[1], &err)) {
    std::fprintf(stderr, "config error: %s\n", err.c_str());
    return 1;
  }
  fdfs::TrackerConfig cfg;
  cfg.bind_addr = ini.GetStr("bind_addr", "");
  cfg.port = static_cast<int>(ini.GetInt("port", 22122));
  cfg.base_path = ini.GetStr("base_path", "");
  cfg.store_lookup = static_cast<int>(ini.GetInt("store_lookup", 0));
  cfg.store_group = ini.GetStr("store_group", "");
  cfg.placement_hysteresis_free_mb = ini.GetInt(
      "placement_hysteresis_free_mb", cfg.placement_hysteresis_free_mb);
  if (cfg.placement_hysteresis_free_mb < 0)
    cfg.placement_hysteresis_free_mb = 0;
  cfg.rebalance_bandwidth_mb_s = static_cast<int>(ini.GetInt(
      "rebalance_bandwidth_mb_s", cfg.rebalance_bandwidth_mb_s));
  if (cfg.rebalance_bandwidth_mb_s < 0) cfg.rebalance_bandwidth_mb_s = 0;
  cfg.check_active_interval_s =
      static_cast<int>(ini.GetSeconds("check_active_interval", 100));
  cfg.save_interval_s = static_cast<int>(ini.GetSeconds("save_interval", 30));
  cfg.max_connections =
      static_cast<int>(ini.GetInt("max_connections", cfg.max_connections));
  cfg.log_level = ini.GetStr("log_level", "info");
  cfg.log_file = ini.GetStr("log_file", "");
  cfg.log_rotate_size = ini.GetBytes("log_rotate_size", cfg.log_rotate_size);
  cfg.use_trunk_file = ini.GetBool("use_trunk_file", false);
  cfg.slot_min_size = static_cast<int>(ini.GetInt("slot_min_size", 256));
  cfg.slot_max_size =
      static_cast<int>(ini.GetInt("slot_max_size", 16 * 1024 * 1024));
  cfg.trunk_file_size = ini.GetInt("trunk_file_size", 64LL * 1024 * 1024);
  cfg.reserved_storage_space_mb = ini.GetInt("reserved_storage_space", 0);
  cfg.tracker_peers = ini.GetAll("tracker_server");
  cfg.use_storage_id = ini.GetBool("use_storage_id", false);
  cfg.storage_ids_file = ini.GetStr("storage_ids_filename", "");
  cfg.trace_buffer_size = static_cast<int>(
      ini.GetInt("trace_buffer_size", cfg.trace_buffer_size));
  if (cfg.trace_buffer_size < 16) cfg.trace_buffer_size = 16;
  cfg.slow_request_threshold_ms =
      ini.GetInt("slow_request_threshold_ms", cfg.slow_request_threshold_ms);
  if (cfg.slow_request_threshold_ms < 0) cfg.slow_request_threshold_ms = 0;
  // Same clamps as the storage daemon's config loader: the ring is
  // RAM-resident (each slot ~a few hundred bytes), so an absurd value
  // must not turn into a startup-time bad_alloc.
  int64_t ebs = ini.GetInt("event_buffer_size", cfg.event_buffer_size);
  if (ebs < 16) ebs = 16;
  if (ebs > (1 << 20)) ebs = 1 << 20;
  cfg.event_buffer_size = static_cast<int>(ebs);
  // Telemetry history + SLO evaluation.  Much tighter cap than the
  // storage loader's: the tracker serves METRICS_HISTORY inline on its
  // single event loop (no dio pool to offload to), so one dump's
  // whole-ring read + CRC scan must never stall beats and routing
  // queries for more than a few tens of ms — and the tracker registry
  // is tiny, so 16 MB of delta records already holds weeks of history.
  cfg.metrics_journal_mb = static_cast<int>(
      ini.GetInt("metrics_journal_mb", cfg.metrics_journal_mb));
  if (cfg.metrics_journal_mb < 0) cfg.metrics_journal_mb = 0;
  if (cfg.metrics_journal_mb > 16) cfg.metrics_journal_mb = 16;
  cfg.slo_eval_interval_s = static_cast<int>(
      ini.GetSeconds("slo_eval_interval_s", cfg.slo_eval_interval_s));
  if (cfg.slo_eval_interval_s < 0) cfg.slo_eval_interval_s = 0;
  cfg.slo_rules_file = ini.GetStr("slo_rules_file", "");
  cfg.profile_max_hz = static_cast<int>(
      ini.GetInt("profile_max_hz", cfg.profile_max_hz));
  if (cfg.profile_max_hz < 0) cfg.profile_max_hz = 0;
  if (cfg.profile_max_hz > 1000) cfg.profile_max_hz = 1000;  // ~1ms timer floor
  // Gray-failure verdict threshold (HEALTH_MATRIX; scores are 0..100,
  // so clamp into that range — 0 means "never call anything gray").
  cfg.health_gray_threshold = static_cast<int>(
      ini.GetInt("health_gray_threshold", cfg.health_gray_threshold));
  if (cfg.health_gray_threshold < 0) cfg.health_gray_threshold = 0;
  if (cfg.health_gray_threshold > 100) cfg.health_gray_threshold = 100;
  // Admission control (ISSUE 19): relax must sit strictly below tighten
  // or the hysteresis band vanishes and the ladder can flap.
  cfg.admission_control = ini.GetBool("admission_control", true);
  cfg.admission_tighten_pct = static_cast<int>(
      ini.GetInt("admission_tighten_pct", cfg.admission_tighten_pct));
  if (cfg.admission_tighten_pct < 1) cfg.admission_tighten_pct = 1;
  cfg.admission_relax_pct = static_cast<int>(
      ini.GetInt("admission_relax_pct", cfg.admission_relax_pct));
  if (cfg.admission_relax_pct >= cfg.admission_tighten_pct)
    cfg.admission_relax_pct = cfg.admission_tighten_pct / 2;
  if (cfg.admission_relax_pct < 0) cfg.admission_relax_pct = 0;
  cfg.admission_loop_lag_high_ms = ini.GetInt(
      "admission_loop_lag_high_ms", cfg.admission_loop_lag_high_ms);
  if (cfg.admission_loop_lag_high_ms < 0) cfg.admission_loop_lag_high_ms = 0;
  cfg.admission_retry_after_ms = ini.GetInt(
      "admission_retry_after_ms", cfg.admission_retry_after_ms);
  if (cfg.admission_retry_after_ms < 1) cfg.admission_retry_after_ms = 1;
  // Elastic hot replication (ISSUE 20): promote threshold 0 keeps the
  // feature off; with it on, demote must sit strictly below promote or
  // the hysteresis band vanishes and the hot map can flap.
  cfg.hot_promote_threshold = static_cast<int>(
      ini.GetInt("hot_promote_threshold", cfg.hot_promote_threshold));
  if (cfg.hot_promote_threshold < 0) cfg.hot_promote_threshold = 0;
  cfg.hot_demote_threshold = static_cast<int>(
      ini.GetInt("hot_demote_threshold", cfg.hot_demote_threshold));
  if (cfg.hot_demote_threshold >= cfg.hot_promote_threshold)
    cfg.hot_demote_threshold = cfg.hot_promote_threshold / 2;
  if (cfg.hot_demote_threshold < 0) cfg.hot_demote_threshold = 0;
  cfg.hot_max_extra_replicas = static_cast<int>(
      ini.GetInt("hot_max_extra_replicas", cfg.hot_max_extra_replicas));
  if (cfg.hot_max_extra_replicas < 1) cfg.hot_max_extra_replicas = 1;
  if (cfg.hot_max_extra_replicas > 16) cfg.hot_max_extra_replicas = 16;
  cfg.hot_map_capacity = static_cast<int>(
      ini.GetInt("hot_map_capacity", cfg.hot_map_capacity));
  if (cfg.hot_map_capacity < 1) cfg.hot_map_capacity = 1;
  if (cfg.hot_map_capacity > 65536) cfg.hot_map_capacity = 65536;
  if (cfg.base_path.empty()) {
    std::fprintf(stderr, "config error: base_path is required\n");
    return 1;
  }
  // Trunk slot sizes travel as uint32 on disk: >= 4GiB would silently
  // truncate the whole-file free block.  Fail fast at load instead.
  if (cfg.use_trunk_file) {
    if (cfg.trunk_file_size >= (4LL << 30)) {
      std::fprintf(stderr, "config error: trunk_file_size must be < 4GiB\n");
      return 1;
    }
    if (cfg.slot_max_size >= cfg.trunk_file_size) {
      // A slot can never exceed its trunk file; clamp (the common case is
      // a small trunk_file_size with the default slot_max_size).
      cfg.slot_max_size = static_cast<int>(cfg.trunk_file_size / 2);
      std::fprintf(stderr,
                   "config warning: slot_max_size >= trunk_file_size, "
                   "clamped to %d\n", cfg.slot_max_size);
    }
  }
  if (cfg.log_level == "debug") fdfs::LogSetLevel(fdfs::LogLevel::kDebug);
  else if (cfg.log_level == "warn") fdfs::LogSetLevel(fdfs::LogLevel::kWarn);
  else if (cfg.log_level == "error") fdfs::LogSetLevel(fdfs::LogLevel::kError);
  fdfs::LogSetupFileSink(cfg.base_path, cfg.log_file, cfg.log_rotate_size);

  fdfs::TrackerServer server(cfg);
  if (!server.Init(&err)) {
    std::fprintf(stderr, "init error: %s\n", err.c_str());
    return 1;
  }
  signal(SIGINT, OnSignal);
  signal(SIGTERM, OnSignal);
  signal(SIGUSR1, OnSignal);
  signal(SIGPIPE, SIG_IGN);
  server.loop().AddTimer(200, [&server]() {
    if (g_dump_flag) {
      g_dump_flag = 0;
      server.DumpState();
    }
    if (g_stop_flag) server.Stop();
  });
  server.Run();
  FDFS_LOG_INFO("tracker daemon shut down");
  return 0;
}
