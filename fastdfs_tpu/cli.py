"""CLI tools over the client library.

Reference: the L4 tools in ``client/`` — fdfs_upload_file.c,
fdfs_download_file.c, fdfs_delete_file.c, fdfs_file_info.c,
fdfs_monitor.c (cluster status), fdfs_test.c (full-API smoke).

Usage:  python -m fastdfs_tpu.cli <tool> <client.conf|tracker host:port> [args]
"""

from __future__ import annotations

import json
import os
import sys

from fastdfs_tpu.client import FdfsClient
from fastdfs_tpu.client.conn import StatusError
from fastdfs_tpu.common.fileid import decode_file_id


def _client(conf_or_addr: str) -> FdfsClient:
    if os.path.exists(conf_or_addr):
        return FdfsClient.from_conf(conf_or_addr)
    return FdfsClient(conf_or_addr)


def _flag(args: list[str], name: str, default: str | None = None):
    """`--name value` lookup shared by the flag-taking subcommands; a
    following token that is itself a flag does not count as a value."""
    if name in args:
        i = args.index(name)
        if i + 1 < len(args) and not args[i + 1].startswith("--"):
            return args[i + 1]
    return default


def cmd_upload(c: FdfsClient, args: list[str]) -> int:
    if not args:
        print("usage: upload <tracker> [--dedup] <local_file> [ext]",
              file=sys.stderr)
        return 2
    dedup = args[0] == "--dedup"
    if dedup:
        args = args[1:]
        if not args:
            print("usage: upload <tracker> [--dedup] <local_file> [ext]",
                  file=sys.stderr)
            return 2
    path = args[0]
    ext = args[1] if len(args) > 1 else os.path.splitext(path)[1].lstrip(".")[:6]
    with open(path, "rb") as fh:
        data = fh.read()
    if dedup:
        # Negotiated upload: fingerprint locally, ship only chunks the
        # daemon lacks; report the wire savings alongside the file ID.
        stats: dict = {}
        fid = c.upload_buffer_dedup(data, ext=ext, min_dup_ratio=0,
                                    stats=stats)
        print(fid)
        sent = stats.get("bytes_sent", len(data))
        print(f"wire: {sent}/{len(data)} bytes shipped"
              + (f" (fallback: {stats['fallback']})"
                 if stats.get("fallback") else ""), file=sys.stderr)
    else:
        fid = c.upload_buffer(data, ext=ext)
        print(fid)
    return 0


def cmd_download(c: FdfsClient, args: list[str]) -> int:
    usage = ("usage: download <tracker> [--parallel N] <file_id> "
             "[local_path]")
    parallel = 1
    if args and args[0] == "--parallel":
        if len(args) < 2 or not args[1].isdigit():
            print(usage, file=sys.stderr)
            return 2
        parallel = int(args[1])
        args = args[2:]
    if not args:
        print(usage, file=sys.stderr)
        return 2
    fid = args[0]
    out = args[1] if len(args) > 1 else os.path.basename(fid)
    # Single-stream downloads go through download_stream (O(segment)
    # client memory); --parallel N splits into jump-hash-routed ranges
    # fetched concurrently across the group's replicas.
    n = c.download_to_file(fid, out, parallel=parallel)
    print(f"{out}: {n} bytes" + (f" (parallel={parallel})"
                                 if parallel > 1 else ""))
    return 0


def cmd_delete(c: FdfsClient, args: list[str]) -> int:
    if not args:
        print("usage: delete <tracker> <file_id>", file=sys.stderr)
        return 2
    c.delete_file(args[0])
    print("deleted")
    return 0


def cmd_file_info(c: FdfsClient, args: list[str]) -> int:
    """Client-side ID decode + server-side query (fdfs_file_info.c)."""
    if not args:
        print("usage: file_info <tracker> <file_id>", file=sys.stderr)
        return 2
    fid, info = decode_file_id(args[0])
    print(f"group: {fid.group}\nstore path: M{fid.store_path_index:02X}")
    print(f"source ip: {info.source_ip}\ncreate time: {info.create_timestamp}")
    print(f"file size: {info.file_size}\ncrc32: {info.crc32:08X}")
    print(f"appender: {info.appender}  trunk: {info.trunk}  slave: {info.slave}")
    remote = c.query_file_info(args[0])
    print(f"server-reported size: {remote.file_size}")
    return 0


def cmd_monitor(c: FdfsClient, args: list[str]) -> int:
    """Cluster health (fdfs_monitor.c analogue): tracker role, per-group
    capacity, per-storage liveness with named beat stats, and each
    daemon's per-opcode counters from its STAT registry.

    Flags: --prometheus  emit text exposition format for scraping
           --no-storage-stats  skip the per-daemon STAT round-trips
           --group <name>      limit to one group
    """
    from fastdfs_tpu import monitor as M
    group = None
    if "--group" in args:
        i = args.index("--group")
        if i + 1 >= len(args) or args[i + 1].startswith("--"):
            print("usage: monitor <tracker> [--group <name>] [--prometheus] "
                  "[--no-storage-stats]", file=sys.stderr)
            return 2
        group = args[i + 1]
    snap = M.gather(c, with_storage_stats="--no-storage-stats" not in args,
                    group=group)
    if "--prometheus" in args:
        print(M.to_prometheus(snap), end="")
    else:
        print(M.render_text(snap))
    return 0


def cmd_top(c: FdfsClient, args: list[str]) -> int:
    """Live cluster saturation dashboard (fdfs_top): polls STAT +
    SERVER_CLUSTER_STAT + EVENT_DUMP across every node on an interval,
    computes delta RATES (ops/s, MB/s, cache hit %, nio loop-lag p99,
    dio queue-wait p99 from histogram deltas), and renders a refreshing
    per-node table plus a scrolling recent-events pane — the operator
    console the load harness runs against.

    Flags: --interval s   poll cadence (default 2)
           --count N      render N frames then exit (0 = forever;
                          scripts and tests use this)
           --group <name> limit the storage rows to one group
           --events N     events-pane depth (default 10)
           --heat [N]     per-node hot-file pane (HEAT_TOP; top N rows,
                          default 5)
           --threads [N]  per-node THREADS pane: the thread ledger from
                          the thread.* gauges already in each STAT
                          snapshot (top N by cpu%, default 8; no extra
                          RPC)
           --json         one machine-readable JSON object per frame
                          instead of the table
           --no-clear     never emit the ANSI clear (append frames)

    An ALERTS line appears whenever a node has active SLO breaches
    (slo.breach events raise a rule, slo.recovered clears it; the
    slo.breaches_active gauge backs the count for nodes whose breach
    predates this fdfs_top's first frame).
    """
    import time as _time

    from fastdfs_tpu import monitor as M

    def flag(name, default=None):
        return _flag(args, name, default)

    interval = float(flag("--interval", "2"))
    count = int(flag("--count", "0"))
    group = flag("--group")
    max_events = int(flag("--events", "10"))
    with_heat = "--heat" in args
    heat_rows = int(flag("--heat", "5") or 5) if with_heat else 5
    with_threads = "--threads" in args
    thread_rows = int(flag("--threads", "8") or 8) if with_threads else 8
    as_json = "--json" in args
    clear = "--no-clear" not in args and not as_json and sys.stdout.isatty()

    seen_seq: dict[str, tuple[int, int]] = {}
    recent: list[M.ClusterEvent] = []
    active_alerts: dict[str, set] = {}
    prev = None
    frames = 0
    try:
        while True:
            cur = M.gather_top(c, group=group, seen_seq=seen_seq)
            rates = M.top_rates(prev, cur)
            recent.extend(sorted(cur.events, key=lambda e: e.ts_us))
            del recent[:-200]  # bounded scrollback
            # Alert tracking: breach raises a rule on its node, recovery
            # clears it (events are seq-deduped, so replays can't flap).
            # Reconcile against the authoritative gauge BEFORE applying
            # this frame's events: a daemon that restarted after a breach
            # never emits slo.recovered (its evaluator state died with
            # it), so a node whose live slo.breaches_active reads 0 has
            # nothing red by definition.  Gauge-clear first, then events
            # — a breach landing between the STAT and EVENT_DUMP calls
            # still sticks.
            for node, ns in cur.nodes.items():
                if (ns.registry is not None and not
                        ns.registry["gauges"].get("slo.breaches_active")):
                    active_alerts.pop(node, None)
            for e in sorted(cur.events, key=lambda ev: (ev.ts_us, ev.seq)):
                if e.type == "slo.breach":
                    active_alerts.setdefault(e.node, set()).add(e.key)
                elif e.type == "slo.recovered":
                    active_alerts.get(e.node, set()).discard(e.key)
            alerts = {n: sorted(rules)
                      for n, rules in active_alerts.items() if rules}
            heat = None
            if with_heat:
                heat = {}
                for node, ns in cur.nodes.items():
                    if ns.role != "storage" or ns.registry is None:
                        continue
                    ip, _, port = ns.addr.rpartition(":")
                    try:
                        heat[node] = M.decode_heat(
                            c.storage_heat_top(ip, int(port), heat_rows))
                    except Exception:  # noqa: BLE001 — heat off / old node
                        heat[node] = []
            threads = None
            if with_threads:
                threads = {node: M.thread_ledger(ns.registry)
                           for node, ns in cur.nodes.items()
                           if ns.registry is not None}
            # HOT line data: the tracker's published hot map (elastic
            # replication); best-effort — an old tracker has no opcode.
            try:
                hot_map = c.query_hot_map()
            except Exception:  # noqa: BLE001
                hot_map = None
            if as_json:
                print(json.dumps({
                    "ts": cur.ts,
                    "nodes": rates,
                    "events": [vars(e) for e in cur.events],
                    "alerts": alerts,
                    "heat": ({n: [vars(h) for h in hs]
                              for n, hs in heat.items()}
                             if heat is not None else None),
                    "threads": ({n: rows[:thread_rows]
                                 for n, rows in threads.items()}
                                if threads is not None else None),
                    "hot_map": hot_map,
                }, sort_keys=True), flush=True)
            else:
                frame = M.render_top(cur, rates, recent, max_events,
                                     alerts=alerts, heat=heat,
                                     heat_rows=heat_rows, threads=threads,
                                     thread_rows=thread_rows,
                                     hot_map=hot_map)
                if clear:
                    print("\x1b[2J\x1b[H" + frame, flush=True)
                else:
                    print(frame, flush=True)
            prev = cur
            frames += 1
            if count and frames >= count:
                return 0
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def cmd_report(c: FdfsClient, args: list[str]) -> int:
    """fdfs_report: retrospective observability from the metrics
    journals (METRICS_HISTORY) — per-node rate/latency time-series over
    a window, the SLO breach timeline from the flight recorders, and
    per-node hot-file tables (HEAT_TOP).  Works after a crash or
    restart: the journal is on disk, so `--since <pre-crash>` replays
    the telemetry that led into the failure.

    Flags: --since <t>    window start: seconds-ago when < 10^7 (e.g.
                          `--since 600` = the last 10 minutes), else an
                          absolute unix-seconds stamp (as printed by
                          `date +%s`).  Default: everything retained.
           --group <name> limit to one group's storages
           --rows N       intervals shown per node (default 12)
           --heat-k N     heat rows requested/rendered (default 5)
           --json         machine-readable dump instead of the tables
    """
    import time as _time

    from fastdfs_tpu import monitor as M

    def flag(name, default=None):
        return _flag(args, name, default)

    since_us = 0
    raw_since = flag("--since")
    if raw_since is not None:
        v = float(raw_since)
        if v <= 0:
            print("--since must be positive", file=sys.stderr)
            return 2
        epoch_s = _time.time() - v if v < 1e7 else v
        since_us = int(epoch_s * 1e6)
    group = flag("--group")
    rows = int(flag("--rows", "12"))
    heat_k = int(flag("--heat-k", "5"))

    data = M.gather_report(c, since_us=since_us, group=group, heat_k=heat_k)
    if not data.history and data.errors:
        # Nothing reachable carried a journal: that is a failure, not an
        # empty report.
        for node, err in sorted(data.errors.items()):
            print(f"{node}  error: {err}", file=sys.stderr)
        return 1
    if "--json" in args:
        print(json.dumps({
            "since_us": data.since_us,
            "series": {n: M.report_series(h)
                       for n, h in data.history.items()},
            "snapshots": {n: len(h) for n, h in data.history.items()},
            "breaches": [vars(e) for e in
                         M.breach_timeline(data.events, data.since_us,
                                           data.history)],
            "heat": {n: [vars(h) for h in hs]
                     for n, hs in data.heat.items()},
            "errors": data.errors,
        }, sort_keys=True))
    else:
        print(M.render_report(data, max_rows=rows, heat_rows=heat_k))
    return 0 if not data.errors else 1


def cmd_test(c: FdfsClient, args: list[str]) -> int:
    """Full-API smoke (fdfs_test.c): upload + metadata + query + download +
    delete."""
    data = os.urandom(10000)
    fid = c.upload_buffer(data, ext="bin")
    print(f"upload: {fid}")
    c.set_metadata(fid, {"from": "fdfs_test", "len": str(len(data))})
    print(f"metadata: {c.get_metadata(fid)}")
    info = c.query_file_info(fid)
    print(f"file info: size={info.file_size} ip={info.source_ip}")
    assert c.download_to_buffer(fid) == data
    print("download: OK")
    c.delete_file(fid)
    print("delete: OK")
    return 0


def cmd_groups_json(c: FdfsClient, args: list[str]) -> int:
    print(json.dumps(c.list_groups(), indent=2))
    return 0


def cmd_append(c: FdfsClient, args: list[str]) -> int:
    """fdfs_append_file: append a local file to an appender file."""
    if len(args) < 2:
        print("usage: append <tracker> <appender_file_id> <local_file>",
              file=sys.stderr)
        return 2
    with open(args[1], "rb") as fh:
        c.append_buffer(args[0], fh.read())
    print("appended")
    return 0


def cmd_upload_appender(c: FdfsClient, args: list[str]) -> int:
    """fdfs_upload_appender: create an appender file."""
    if not args:
        print("usage: upload_appender <tracker> <local_file> [ext]",
              file=sys.stderr)
        return 2
    ext = args[1] if len(args) > 1 else os.path.splitext(args[0])[1].lstrip(".")[:6]
    with open(args[0], "rb") as fh:
        print(c.upload_appender_buffer(fh.read(), ext=ext))
    return 0


def cmd_delete_server(c: FdfsClient, args: list[str]) -> int:
    """fdfs_monitor's delete-server action (non-active members only)."""
    if len(args) < 2:
        print("usage: delete_server <tracker> <group> <ip:port>",
              file=sys.stderr)
        return 2
    ip, _, port = args[1].partition(":")
    c.delete_storage(args[0], ip, int(port))
    print("deleted")
    return 0


def cmd_set_trunk_server(c: FdfsClient, args: list[str]) -> int:
    """fdfs_monitor's set-trunk-server action."""
    if len(args) < 2:
        print("usage: set_trunk_server <tracker> <group> <ip:port>",
              file=sys.stderr)
        return 2
    ip, _, port = args[1].partition(":")
    c.set_trunk_server(args[0], ip, int(port))
    print("trunk server set")
    return 0


def cmd_near_dups(c: FdfsClient, args: list[str]) -> int:
    """Ranked near-duplicates of a stored file from the dedup engine's
    MinHash/LSH index (fastdfs_tpu extension; no reference equivalent —
    the upstream tree has no similarity index at all)."""
    if not args:
        print("usage: near_dups <tracker> <file_id>", file=sys.stderr)
        return 2
    pairs = c.near_dups(args[0])
    if not pairs:
        print("no near-duplicates known")
        return 0
    for fid, score in pairs:
        print(f"{score:.4f}  {fid}")
    return 0


def cmd_tracker_status(c: FdfsClient, args: list[str]) -> int:
    """Multi-tracker relationship probe (leader + role)."""
    print(json.dumps(c.tracker_status()))
    return 0


def cmd_trace(c: FdfsClient, args: list[str]) -> int:
    """Distributed request tracing: run one traced upload through the
    cluster, collect every node's span ring (TRACE_DUMP), stitch by
    trace_id, and render the cross-node timeline.

    Flags: --file <path>     trace an upload of this file (default: a
                             random 256 KB payload, deleted afterwards)
           --size <bytes>    random payload size for the default mode
           --trace-id <hex>  skip the upload; render an existing trace
                             from the cluster's rings
           --wait <s>        settle time before collecting (default 1.5,
                             lets the replication hop record sync spans)
           --json            machine-readable span list instead of the
                             timeline
    """
    import time as _time

    from fastdfs_tpu import trace as T

    def flag(name, default=None):
        return _flag(args, name, default)

    trace_id = None
    cleanup_fid = None
    tracer = None
    if flag("--trace-id") is not None:
        trace_id = int(flag("--trace-id"), 16)
    else:
        if flag("--file") is not None:
            with open(flag("--file"), "rb") as fh:
                data = fh.read()
            ext = os.path.splitext(flag("--file"))[1].lstrip(".")[:6]
        else:
            data = os.urandom(int(flag("--size", "262144")))
            ext = "bin"
            cleanup_fid = True
        fid, tracer = T.traced_upload(c, data, ext=ext)
        trace_id = tracer.trace_id
        print(f"uploaded {fid}  trace_id={trace_id:016x}", file=sys.stderr)
        _time.sleep(float(flag("--wait", "1.5")))  # let replication ship
        if cleanup_fid:
            try:
                c.delete_file(fid)
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass
    spans, errors = T.collect_cluster_spans(c)
    if tracer is not None:  # merge the client-side spans recorded locally
        spans.extend(tracer.spans)
    matched = [s for s in spans if s.trace_id == trace_id]
    for node, err in errors.items():
        print(f"warning: {node}: {err}", file=sys.stderr)
    if "--json" in args:
        print(T.spans_to_json(matched))
    else:
        print(T.render_timeline(matched, trace_id))
    return 0 if matched else 1


def cmd_profile(c: FdfsClient, args: list[str]) -> int:
    """One-shot CPU profile of a daemon (fdfs_profile): arm the
    in-daemon SIGPROF sampler, wait out the capture window, pull the
    folded-stack dump, and print it — collapsed-stack text by default
    (pipe straight into flamegraph.pl or load into speedscope), raw
    dump JSON with --json.

    Usage: profile <tracker> <ip:port> [--tracker] [flags]

           <ip:port>      the daemon to profile (a storage node, or
                          with --tracker a tracker)
           --hz N         sample rate (default 97 — prime, so it can't
                          alias against 10ms timer wheels; clamped to
                          the daemon's profile_max_hz)
           --seconds N    capture window (default 5; the daemon
                          auto-disarms at the deadline either way)
           --folded       collapsed-stack output (the default)
           --json         raw PROFILE_DUMP JSON instead
           --no-wait      arm and exit (dump later with --dump-only)
           --dump-only    skip arming; dump whatever the last capture
                          holds
           --stop         disarm early and exit

    ENOTSUP (status 95) means profiling is off at the daemon: set
    profile_max_hz > 0 in its conf (see OPERATIONS.md "Profiling & the
    thread ledger" — the feature costs nothing until armed).
    """
    import time as _time

    from fastdfs_tpu import monitor as M
    from fastdfs_tpu.client.tracker_client import TrackerClient

    def flag(name, default=None):
        return _flag(args, name, default)

    node = next((a for a in args if not a.startswith("--")
                 and ":" in a), None)
    if node is None:
        print("usage: profile <tracker> <ip:port> [--tracker] [--hz N] "
              "[--seconds N] [--folded|--json] [--stop]", file=sys.stderr)
        return 2
    ip, _, port_s = node.rpartition(":")
    port = int(port_s)
    hz = int(flag("--hz", "97"))
    seconds = int(flag("--seconds", "5"))
    is_tracker = "--tracker" in args

    def ctl(what, *a):
        if is_tracker:
            with TrackerClient(ip, port, c.timeout) as t:
                return getattr(t, what)(*a)
        return getattr(c, f"storage_{what}")(ip, port, *a)

    if "--stop" in args:
        print(json.dumps(ctl("profile_stop"), sort_keys=True))
        return 0
    if "--dump-only" not in args:
        ack = ctl("profile_start", hz, seconds)
        print(f"armed {node} at {ack.get('hz', hz)} Hz for {seconds}s",
              file=sys.stderr)
        if "--no-wait" in args:
            return 0
        # The daemon disarms itself at the deadline; the slack covers
        # the last in-flight SIGPROF and tick jitter.
        _time.sleep(seconds + 0.5)
    raw = ctl("profile_dump")
    dump = M.decode_profile(raw)
    if dump.dropped:
        print(f"warning: {dump.dropped} samples dropped (slab full) — "
              "the busiest window is under-represented", file=sys.stderr)
    if "--json" in args:
        print(json.dumps(raw, sort_keys=True))
    else:
        print(M.render_folded(dump))
    return 0


def cmd_scrub(c: FdfsClient, args: list[str]) -> int:
    """Integrity engine (anti-entropy) console: per-storage scrub status
    from the SCRUB_STATUS blob, with optional kick and watch modes.

    Flags: --kick          force a verify+repair+GC pass on every
                           storage first (SCRUB_KICK)
           --watch [s]     re-render every s seconds (default 2) until
                           interrupted
           --group <name>  limit to one group
           --json          machine-readable {addr: {field: value}}
    """
    import time as _time

    group = None
    if "--group" in args:
        i = args.index("--group")
        if i + 1 >= len(args) or args[i + 1].startswith("--"):
            print("usage: scrub <tracker> [--kick] [--watch [s]] "
                  "[--group <name>] [--json]", file=sys.stderr)
            return 2
        group = args[i + 1]
    interval = 0.0
    if "--watch" in args:
        i = args.index("--watch")
        interval = 2.0
        if i + 1 < len(args) and not args[i + 1].startswith("--"):
            try:
                interval = float(args[i + 1])
            except ValueError:
                pass

    def storages():
        cs = c.cluster_stat(group)
        return [(s["ip"], s["port"])
                for g in cs.get("groups", [])
                for s in g.get("storages", [])]

    members = storages()
    if not members:
        print("no storages known to the tracker", file=sys.stderr)
        return 1
    if "--kick" in args:
        for ip, port in members:
            try:
                c.scrub_kick(ip, port)
                print(f"kicked {ip}:{port}", file=sys.stderr)
            except Exception as e:  # noqa: BLE001 — keep kicking the rest
                print(f"kick {ip}:{port} failed: {e}", file=sys.stderr)

    def render_once() -> int:
        rows: dict[str, dict] = {}
        errors: dict[str, str] = {}
        for ip, port in members:
            addr = f"{ip}:{port}"
            try:
                rows[addr] = c.scrub_status(ip, port)
            except Exception as e:  # noqa: BLE001 — a dead node is a row
                errors[addr] = str(e)
        if "--json" in args:
            # Unreachable nodes appear as {"error": ...} entries, and any
            # error makes the exit code nonzero — a monitoring consumer
            # must never mistake a partial answer for a healthy cluster.
            merged: dict[str, dict] = dict(rows)
            merged.update({a: {"error": e} for a, e in errors.items()})
            print(json.dumps(merged, indent=2, sort_keys=True))
        else:
            for addr, st in sorted(rows.items()):
                state = "RUNNING" if st["running"] else "idle"
                print(f"{addr}  {state}  passes={st['passes']} "
                      f"progress={st['pass_chunks_done']}"
                      f"/{st['pass_chunks_total']}")
                print(f"  verified: {st['chunks_verified']} chunks "
                      f"({st['bytes_verified']} bytes)   corrupt: "
                      f"{st['chunks_corrupt']}  repaired: "
                      f"{st['chunks_repaired']}  unrepairable: "
                      f"{st['corrupt_unrepairable']}  quarantined: "
                      f"{st['quarantined']}")
                print(f"  gc: pending {st['gc_pending_chunks']} chunks "
                      f"({st['gc_pending_bytes']} bytes)   reclaimed "
                      f"{st['chunks_reclaimed']} chunks + "
                      f"{st['recipes_reclaimed']} recipes "
                      f"({st['bytes_reclaimed']} bytes)")
            for addr, err in sorted(errors.items()):
                print(f"{addr}  error: {err}")
        return 0 if rows and not errors else 1

    if interval <= 0:
        return render_once()
    try:
        while True:
            if "--json" not in args:  # keep --watch --json parseable
                print(f"-- scrub @ {_time.strftime('%H:%M:%S')} --")
            render_once()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def cmd_ec(c: FdfsClient, args: list[str]) -> int:
    """Erasure-coding cold-tier console: per-storage EC status from the
    EC_STATUS blob — stripe inventory, demotion/release accounting, and
    reconstruction counters — with optional kick and watch modes.

    Flags: --kick          force an EC demotion pass on every storage
                           first (EC_KICK: age gate dropped to 0 for
                           one pass, then the scrubber is kicked)
           --watch [s]     re-render every s seconds (default 2) until
                           interrupted
           --group <name>  limit to one group
           --json          machine-readable {addr: {field: value}}

    Daemons with EC off (ec_k = 0, nothing striped on disk) answer
    StatusError(95) and render as "ec off" rows rather than errors.
    """
    import time as _time

    group = None
    if "--group" in args:
        i = args.index("--group")
        if i + 1 >= len(args) or args[i + 1].startswith("--"):
            print("usage: ec <tracker> [--kick] [--watch [s]] "
                  "[--group <name>] [--json]", file=sys.stderr)
            return 2
        group = args[i + 1]
    interval = 0.0
    if "--watch" in args:
        i = args.index("--watch")
        interval = 2.0
        if i + 1 < len(args) and not args[i + 1].startswith("--"):
            try:
                interval = float(args[i + 1])
            except ValueError:
                pass

    def storages():
        cs = c.cluster_stat(group)
        return [(s["ip"], s["port"])
                for g in cs.get("groups", [])
                for s in g.get("storages", [])]

    members = storages()
    if not members:
        print("no storages known to the tracker", file=sys.stderr)
        return 1
    if "--kick" in args:
        for ip, port in members:
            try:
                c.ec_kick(ip, port)
                print(f"kicked {ip}:{port}", file=sys.stderr)
            except StatusError as e:
                if e.status == 95:  # EC off here — not a failure
                    print(f"skip {ip}:{port}: ec off", file=sys.stderr)
                else:
                    print(f"kick {ip}:{port} failed: {e}", file=sys.stderr)
            except Exception as e:  # noqa: BLE001 — keep kicking the rest
                print(f"kick {ip}:{port} failed: {e}", file=sys.stderr)

    def render_once() -> int:
        rows: dict[str, dict] = {}
        off: list[str] = []
        errors: dict[str, str] = {}
        for ip, port in members:
            addr = f"{ip}:{port}"
            try:
                rows[addr] = c.ec_status(ip, port)
            except StatusError as e:
                if e.status == 95:
                    off.append(addr)
                else:
                    errors[addr] = str(e)
            except Exception as e:  # noqa: BLE001 — a dead node is a row
                errors[addr] = str(e)
        if "--json" in args:
            merged: dict[str, dict] = dict(rows)
            merged.update({a: {"enabled": 0} for a in off})
            merged.update({a: {"error": e} for a, e in errors.items()})
            print(json.dumps(merged, indent=2, sort_keys=True))
        else:
            for addr, st in sorted(rows.items()):
                scheme = (f"RS({st['k']}+{st['m']})" if st["enabled"]
                          else "draining")
                print(f"{addr}  {scheme}  stripes={st['stripes']} "
                      f"chunks={st['stripe_chunks']} "
                      f"data={st['data_bytes']}B "
                      f"parity={st['parity_bytes']}B")
                print(f"  demoted: {st['demoted_chunks']} chunks "
                      f"({st['demoted_bytes']} bytes)   released: "
                      f"{st['released_chunks']} chunks "
                      f"({st['released_bytes']} bytes)   remote reads: "
                      f"{st['remote_reads']}")
                print(f"  reconstructed: {st['reconstructed_shards']} "
                      f"shards ({st['reconstructed_bytes']} bytes)   "
                      f"repair fallbacks: {st['repair_fallback_chunks']}"
                      f"   last demote: {st['last_demote_unix']}")
            for addr in sorted(off):
                print(f"{addr}  ec off")
            for addr, err in sorted(errors.items()):
                print(f"{addr}  error: {err}")
        return 0 if not errors else 1

    if interval <= 0:
        return render_once()
    try:
        while True:
            if "--json" not in args:  # keep --watch --json parseable
                print(f"-- ec @ {_time.strftime('%H:%M:%S')} --")
            render_once()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def cmd_health(c: FdfsClient, args: list[str]) -> int:
    """Gray-failure health console: the tracker's N x N differential
    matrix (HEALTH_MATRIX — each node's self-reported score against what
    its group peers score it, with the tracker's verdict) and, with
    --detail, every storage's own HEALTH_STATUS table (per-peer, per-op
    EWMA latency / error% / timeout%, disk-probe latencies, stalled
    threads).

    Verdicts: ok      both views at/above the gray threshold
              gray    peers score it below threshold while its own
                      trailer claims healthy — the signature gray
                      failure (slow disk, flaky NIC, wedged thread)
              sick    its own trailer admits a score below threshold
              unknown no health data yet (old storage, or just booted)

    Flags: --detail        also query each storage's HEALTH_STATUS
           --watch [s]     re-render every s seconds (default 2) until
                           interrupted
           --json          machine-readable {matrix: ..., status: ...}
    """
    import time as _time

    from fastdfs_tpu import monitor as M

    interval = 0.0
    if "--watch" in args:
        i = args.index("--watch")
        interval = 2.0
        if i + 1 < len(args) and not args[i + 1].startswith("--"):
            try:
                interval = float(args[i + 1])
            except ValueError:
                pass

    def render_once() -> int:
        raw = c.health_matrix()
        matrix = M.decode_health_matrix(raw)
        detail: dict[str, dict] = {}
        errors: dict[str, str] = {}
        if "--detail" in args:
            for n in matrix.nodes:
                ip, _, port = n.addr.rpartition(":")
                try:
                    detail[n.addr] = c.storage_health_status(ip, int(port))
                except Exception as e:  # noqa: BLE001 — a dead node is a row
                    errors[n.addr] = str(e)
        if "--json" in args:
            print(json.dumps({"matrix": raw, "status": detail,
                              "errors": errors}, indent=2, sort_keys=True))
            return 0 if not errors else 1
        print(f"gray threshold: {matrix.gray_threshold}  "
              f"(score 0..100, 100 = healthy)")
        cols = (f"{'node':<28} {'verdict':<8} {'self':>5} {'peers':>6} "
                f"{'reports':>7} {'age':>5}")
        print(cols)
        print("-" * len(cols))
        order = {"gray": 0, "sick": 1, "unknown": 2, "ok": 3}
        flagged = 0
        for n in sorted(matrix.nodes,
                        key=lambda n: (order[n.verdict], n.addr)):
            if n.verdict in ("gray", "sick"):
                flagged += 1
            self_s = "-" if n.self_score < 0 else str(n.self_score)
            peer_s = "-" if n.peer_avg < 0 else str(n.peer_avg)
            age = "-" if n.age_s < 0 else f"{n.age_s}s"
            print(f"{n.group + '/' + n.addr:<28} {n.verdict:<8} "
                  f"{self_s:>5} {peer_s:>6} {n.reports:>7} {age:>5}")
        for addr, raw_st in sorted(detail.items()):
            st = M.decode_health_status(raw_st)
            print(f"\n{addr}  self={st.score}  stalled={st.stalled_threads}"
                  f"  probe read={st.probe_read_us}us "
                  f"write={st.probe_write_us}us "
                  f"(threshold {st.probe_threshold_ms}ms)")
            for p in st.peers:
                print(f"  {p.addr:<24} {p.op:<6} score={p.score:<4} "
                      f"ewma={p.rpc_ewma_us}us err={p.error_pct}% "
                      f"timeout={p.timeout_pct}% "
                      f"ops={p.ops}/{p.errors}e/{p.timeouts}t "
                      f"age={p.age_s}s")
        for addr, err in sorted(errors.items()):
            print(f"\n{addr}  error: {err}")
        return 0 if not errors else 1

    if interval <= 0:
        return render_once()
    try:
        while True:
            if "--json" not in args:  # keep --watch --json parseable
                print(f"-- health @ {_time.strftime('%H:%M:%S')} --")
            render_once()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def cmd_admission(c: FdfsClient, args: list[str]) -> int:
    """Overload-control console: every daemon's admission-ladder status
    (ADMISSION_STATUS) — the tracker's plus each storage's shed level,
    pressure EWMA against its tighten/relax thresholds, and lifetime
    per-class shed counts.  The status opcode is born control-class, so
    it answers even from a daemon at reads-only.

    Flags: --watch [s]     re-render every s seconds (default 2) until
                           interrupted
           --json          machine-readable {addr: {field: value}}
    """
    import time as _time

    from fastdfs_tpu import monitor as M

    interval = 0.0
    if "--watch" in args:
        i = args.index("--watch")
        interval = 2.0
        if i + 1 < len(args) and not args[i + 1].startswith("--"):
            try:
                interval = float(args[i + 1])
            except ValueError:
                pass

    def storages():
        cs = c.cluster_stat()
        return [(s["ip"], s["port"])
                for g in cs.get("groups", [])
                for s in g.get("storages", [])]

    members = storages()

    def render_once() -> int:
        rows: dict[str, dict] = {}
        errors: dict[str, str] = {}
        try:
            raw = c.tracker_admission_status()
            rows[f"tracker {raw['port']}"] = raw
        except Exception as e:  # noqa: BLE001 — a dead node is a row
            errors["tracker"] = str(e)
        for ip, port in members:
            addr = f"{ip}:{port}"
            try:
                rows[addr] = c.storage_admission_status(ip, port)
            except Exception as e:  # noqa: BLE001
                errors[addr] = str(e)
        if "--json" in args:
            merged: dict[str, dict] = dict(rows)
            merged.update({a: {"error": e} for a, e in errors.items()})
            print(json.dumps(merged, indent=2, sort_keys=True))
            return 0 if rows and not errors else 1
        cols = (f"{'node':<24} {'level':<16} {'ewma':>6} {'thresh':>11} "
                f"{'admitted':>9} {'shed':>7} {'retry':>7}")
        print(cols)
        print("-" * len(cols))
        for addr, raw_st in sorted(rows.items()):
            st = M.decode_admission(raw_st)
            off = "" if st.enabled else " (DISABLED)"
            thresh = f"{st.relax_threshold}/{st.tighten_threshold}"
            print(f"{addr:<24} {st.level_name:<16} {st.ewma:>6.2f} "
                  f"{thresh:>11} {st.admitted:>9} {st.shed:>7} "
                  f"{st.retry_after_ms:>5}ms{off}")
            shed = {k: v for k, v in sorted(st.shed_by_class.items())
                    if v}
            if shed:
                print("  shed by class: " +
                      "  ".join(f"{k}={v}" for k, v in shed.items()))
        for addr, err in sorted(errors.items()):
            print(f"{addr}  error: {err}")
        return 0 if rows and not errors else 1

    if interval <= 0:
        return render_once()
    try:
        while True:
            if "--json" not in args:  # keep --watch --json parseable
                print(f"-- admission @ {_time.strftime('%H:%M:%S')} --")
            render_once()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def cmd_hot(c: FdfsClient, args: list[str]) -> int:
    """Elastic hot-replication console (ISSUE 20): the tracker's
    published hot map (QUERY_HOT_MAP — every promoted file and the
    extra groups serving it), the tracker's promotion/demotion ledger
    gauges, each storage's fan-out progress gauges, and a per-node
    hot-file pane straight from the heat sketches (the same table
    fdfs_top --heat renders).

    Flags: --watch [s]     re-render every s seconds (default 2) until
                           interrupted
           --rows N        heat-pane rows per node (default 5)
           --json          machine-readable {map: ..., tracker: ...,
                           storages: ..., heat: ...}
    """
    import time as _time

    from fastdfs_tpu import monitor as M

    interval = 0.0
    if "--watch" in args:
        i = args.index("--watch")
        interval = 2.0
        if i + 1 < len(args) and not args[i + 1].startswith("--"):
            try:
                interval = float(args[i + 1])
            except ValueError:
                pass
    rows = int(_flag(args, "--rows", "5") or 5)

    _TRACKER_GAUGES = ("hot.map_version", "hot.promoted", "hot.pending",
                       "hot.retiring", "hot.promotions_total",
                       "hot.demotions_total", "hot.tracked_keys")
    _STORAGE_GAUGES = ("hot.fanout_replicated", "hot.fanout_dropped",
                       "hot.fanout_verify_failures", "hot.fanout_failures",
                       "hot.fanout_queue")

    def members():
        cs = c.cluster_stat()
        return [(s["ip"], s["port"])
                for g in cs.get("groups", [])
                for s in g.get("storages", [])]

    def render_once() -> int:
        hot_map = c.query_hot_map()
        tracker_gauges: dict[str, int] = {}
        try:
            reg = c._with_tracker(lambda t: t.stat())
            tracker_gauges = {k: v for k, v in reg.get("gauges", {}).items()
                              if k in _TRACKER_GAUGES}
        except Exception as e:  # noqa: BLE001 — gauges are best-effort
            print(f"warning: tracker stat: {e}", file=sys.stderr)
        storages: dict[str, dict] = {}
        heat: dict[str, list] = {}
        for ip, port in members():
            addr = f"{ip}:{port}"
            try:
                reg = c.storage_stat(ip, port)
                storages[addr] = {k: v
                                  for k, v in reg.get("gauges", {}).items()
                                  if k in _STORAGE_GAUGES}
            except Exception as e:  # noqa: BLE001 — a dead node is a row
                storages[addr] = {"error": str(e)}
            try:
                heat[addr] = M.decode_heat(c.storage_heat_top(ip, port,
                                                              rows))
            except Exception:  # noqa: BLE001 — heat off / old node
                heat[addr] = []
        if "--json" in args:
            print(json.dumps({
                "map": hot_map,
                "tracker": tracker_gauges,
                "storages": storages,
                "heat": {n: [vars(h) for h in hs]
                         for n, hs in heat.items()},
            }, indent=2, sort_keys=True))
            return 0
        print(f"hot map v{hot_map['version']} "
              f"({len(hot_map['entries'])} published):")
        if not hot_map["entries"]:
            print("  (none)")
        for e in hot_map["entries"]:
            print(f"  {e['key']} -> {','.join(e['groups'])}")
        if tracker_gauges:
            print("tracker: " +
                  "  ".join(f"{k.removeprefix('hot.')}={v}"
                            for k, v in sorted(tracker_gauges.items())))
        print("fan-out (per elected storage):")
        for addr, st in sorted(storages.items()):
            if "error" in st:
                print(f"  {addr}  error: {st['error']}")
                continue
            print(f"  {addr}  " +
                  "  ".join(f"{k.removeprefix('hot.fanout_')}={v}"
                            for k, v in sorted(st.items())))
        print(f"hot files (top {rows} per node, "
              "hits / err-bound / MB / ops):")
        for line in M._heat_table_lines(heat, rows):
            print(line)
        return 0

    if interval <= 0:
        return render_once()
    try:
        while True:
            if "--json" not in args:  # keep --watch --json parseable
                print(f"-- hot @ {_time.strftime('%H:%M:%S')} --")
            render_once()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def cmd_group(c: FdfsClient, args: list[str]) -> int:
    """Group lifecycle console (multi-group scale-out): the placement
    epoch with per-group state and, for draining groups, each member's
    rebalance progress from its last beat.

    Forms: group <tracker> status [--json] [--watch [s]]
           group <tracker> drain <name>
           group <tracker> reactivate <name>
    """
    import time as _time

    from fastdfs_tpu import monitor as M

    usage = ("usage: group <tracker> status [--json] [--watch [s]] | "
             "drain <name> | reactivate <name>")
    if not args:
        print(usage, file=sys.stderr)
        return 2
    verb = args[0]

    if verb in ("drain", "reactivate"):
        if len(args) < 2 or args[1].startswith("--"):
            print(usage, file=sys.stderr)
            return 2
        name = args[1]
        fn = c.group_drain if verb == "drain" else c.group_reactivate
        version = fn(name)
        print(f"group {name} {verb} accepted: placement version {version}")
        return 0
    if verb != "status":
        print(usage, file=sys.stderr)
        return 2

    interval = 0.0
    if "--watch" in args:
        i = args.index("--watch")
        interval = 2.0
        if i + 1 < len(args) and not args[i + 1].startswith("--"):
            try:
                interval = float(args[i + 1])
            except ValueError:
                pass

    _REB = ("rebalance_files_moved", "rebalance_bytes_moved",
            "rebalance_files_pending", "rebalance_errors", "rebalance_done")

    def render_once() -> int:
        table = c.query_placement()
        # Rebalance progress rides the beat: pull each member's last-beat
        # stat slots out of the tracker's cluster dump (one RPC).
        beats: dict[str, dict] = {}
        try:
            cs = c.cluster_stat()
            for g in cs.get("groups", []):
                for s in g.get("storages", []):
                    beats[f"{s['ip']}:{s['port']}"] = \
                        M.beat_stats_from_storage(s)
        except Exception as e:  # noqa: BLE001 — progress is best-effort
            print(f"warning: cluster_stat: {e}", file=sys.stderr)
        if "--json" in args:
            out = {"version": table["version"], "groups": []}
            for g in table["groups"]:
                row = dict(g)
                row["rebalance"] = {
                    f"{m['ip']}:{m['port']}": {
                        k: beats.get(f"{m['ip']}:{m['port']}", {}).get(k, 0)
                        for k in _REB}
                    for m in g["members"]}
                out["groups"].append(row)
            print(json.dumps(out, indent=2, sort_keys=True))
            return 0
        print(f"placement version {table['version']}  "
              f"({len(table['groups'])} groups)")
        for g in table["groups"]:
            print(f"{g['group']:<16} {g['state_name']:<9} "
                  f"members={len(g['members'])}")
            for m in g["members"]:
                addr = f"{m['ip']}:{m['port']}"
                b = beats.get(addr)
                if b is None or g["state_name"] == "active":
                    continue
                done = "yes" if b.get("rebalance_done", 0) else "no"
                print(f"  {addr}  moved={b.get('rebalance_files_moved', 0)} "
                      f"({b.get('rebalance_bytes_moved', 0)} bytes)  "
                      f"pending={b.get('rebalance_files_pending', 0)}  "
                      f"errors={b.get('rebalance_errors', 0)}  done={done}")
        return 0

    if interval <= 0:
        return render_once()
    try:
        while True:
            if "--json" not in args:  # keep --watch --json parseable
                print(f"-- groups @ {_time.strftime('%H:%M:%S')} --")
            render_once()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


TOOLS = {
    "upload": cmd_upload,
    "download": cmd_download,
    "delete": cmd_delete,
    "file_info": cmd_file_info,
    "monitor": cmd_monitor,
    "top": cmd_top,
    "report": cmd_report,
    "test": cmd_test,
    "groups_json": cmd_groups_json,
    "append": cmd_append,
    "upload_appender": cmd_upload_appender,
    "delete_server": cmd_delete_server,
    "set_trunk_server": cmd_set_trunk_server,
    "tracker_status": cmd_tracker_status,
    "near_dups": cmd_near_dups,
    "trace": cmd_trace,
    "profile": cmd_profile,
    "scrub": cmd_scrub,
    "ec": cmd_ec,
    "health": cmd_health,
    "admission": cmd_admission,
    "group": cmd_group,
    "hot": cmd_hot,
}


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2 or argv[0] not in TOOLS:
        print(f"usage: python -m fastdfs_tpu.cli <{'|'.join(TOOLS)}> "
              "<client.conf|tracker_host:port> [args...]", file=sys.stderr)
        return 2
    tool, conf = argv[0], argv[1]
    try:
        return TOOLS[tool](_client(conf), argv[2:])
    except Exception as e:  # CLI surface: print, nonzero exit
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
