"""Dedup engine: CDC chunking → fingerprints → exact/near-dup verdicts.

This is the storage-plugin payload (the rebuild's analogue of the hook
point in the reference's ``storage/storage_func.h``): the storage upload
path hands incoming bytes to :class:`DedupEngine` and gets back per-chunk
write/skip verdicts plus near-duplicate candidates for the tracker index.
"""

from fastdfs_tpu.dedup.index import ExactDigestIndex, MinHashLSHIndex  # noqa: F401
from fastdfs_tpu.dedup.engine import (  # noqa: F401
    DedupConfig,
    DedupEngine,
    IngestReport,
    ChunkRecord,
)
