"""Digest indexes: exact (SHA1) and near-dup (MinHash + LSH banding).

The exact index is the dedup verdict authority; the LSH index serves the
tracker-side near-duplicate queries (north star: "tracker's file-id index
backed by a jax.numpy cosine/MinHash similarity search").  Both snapshot to
disk — the new stateful component SURVEY.md §5 says checkpoint/resume must
cover (the reference's restart-safety is binlogs + ``.dat`` files; the
dedup index gets the same treatment).
"""

from __future__ import annotations

import json
import os
from typing import Any, Sequence

import numpy as np

from fastdfs_tpu.ops.minhash import EMPTY

# Bumped whenever the signature spec changes (v2 = the survivor sketch,
# round 3); snapshots carry it so a stale index fails loudly instead of
# silently scoring noise against incompatible signatures.
SIG_SPEC_VERSION = 2


# Sentinel offset meaning "the ref is the carrier object itself, not a
# [carrier, offset] pair" — kept for API generality; production refs are
# always [file_ref, offset].
_OFF_BARE = -(1 << 62)

# Snapshot format version for the exact index (v2 = columnar arrays;
# v1 = flat digest bytes + per-entry json refs).  load() reads both.
_EXACT_SPEC = 2


class ExactDigestIndex:
    """digest bytes → ``[carrier, offset]`` ref (chunk locator / file id),
    engineered for tens of millions of entries.

    A plain ``dict[bytes, list]`` costs ~200 B/entry — config 5's nominal
    scale (~62M chunks) would need >12 GB of pure bookkeeping.  Instead:
    an LSM-flavored layout with a sorted ``S20`` digest column plus
    parallel ``int32`` carrier-id / ``int64`` offset columns (the BASE),
    and a small dict DELTA for recent inserts, merged into the base when
    it grows past a quarter of it.  ~36 B/entry steady-state, batch
    lookups vectorize through ``np.searchsorted``, and snapshots are raw
    column dumps (SHA1 digests are incompressible — no zlib pass).

    Carrier objects (file ids) are interned in a side table, so the per
    entry cost is independent of file-id length.  Removals tombstone
    base rows (compacted at the next merge) and delete delta entries.
    """

    def __init__(self) -> None:
        self._base_dig = np.empty(0, dtype="S20")
        self._base_carrier = np.empty(0, dtype=np.int32)
        self._base_off = np.empty(0, dtype=np.int64)
        self._base_dead = np.empty(0, dtype=bool)
        self._dead = 0                                  # tombstoned rows
        self._delta: dict[bytes, tuple[int, int]] = {}  # dig -> (cid, off)
        self._carriers: list[Any] = []
        self._carrier_ids: dict[Any, int] = {}
        self._len = 0

    def __len__(self) -> int:
        return self._len

    # -- internals ---------------------------------------------------------

    def _cid(self, carrier: Any) -> int:
        i = self._carrier_ids.get(carrier)
        if i is None:
            i = len(self._carriers)
            self._carriers.append(carrier)
            self._carrier_ids[carrier] = i
        return i

    @staticmethod
    def _decompose(ref: Any) -> tuple[Any, int]:
        if (isinstance(ref, (list, tuple)) and len(ref) == 2
                and isinstance(ref[1], (int, np.integer))):
            return ref[0], int(ref[1])
        return ref, _OFF_BARE

    def _compose(self, cid: int, off: int) -> Any:
        c = self._carriers[cid]
        return c if off == _OFF_BARE else [c, off]

    def _base_row(self, digest: bytes) -> int:
        """Row index of a LIVE base entry, or -1."""
        n = len(self._base_dig)
        if n == 0:
            return -1
        # The probe must be an S20 ARRAY scalar, not np.bytes_: only
        # S20-to-S20 comparison gets NUL-padding semantics, so the ~1/256
        # SHA1 digests ending in 0x00 still match their stored row.
        q = np.array(digest, dtype="S20")
        i = int(np.searchsorted(self._base_dig, q))
        if i < n and self._base_dig[i] == q and not self._base_dead[i]:
            return i
        return -1

    def _merge(self) -> None:
        """Fold the delta into the base (and compact tombstones)."""
        alive = ~self._base_dead if self._dead else slice(None)
        parts_d = [self._base_dig[alive]]
        parts_c = [self._base_carrier[alive]]
        parts_o = [self._base_off[alive]]
        if self._delta:
            nd = len(self._delta)
            parts_d.append(np.fromiter(self._delta.keys(), dtype="S20",
                                       count=nd))
            vals = self._delta.values()
            parts_c.append(np.fromiter((v[0] for v in vals), dtype=np.int32,
                                       count=nd))
            parts_o.append(np.fromiter((v[1] for v in self._delta.values()),
                                       dtype=np.int64, count=nd))
        dig = np.concatenate(parts_d)
        order = np.argsort(dig, kind="stable")
        self._base_dig = dig[order]
        self._base_carrier = np.concatenate(parts_c)[order]
        self._base_off = np.concatenate(parts_o)[order]
        self._base_dead = np.zeros(len(dig), dtype=bool)
        self._dead = 0
        self._delta = {}
        self._compact_carriers()

    def _compact_carriers(self) -> None:
        """Drop forgotten (None-slotted) carriers and remap the base
        carrier column — without this, create/forget churn leaks every
        dead file-id string into RAM and every snapshot forever.  Only
        runs on merge, when the delta is empty (its cids would otherwise
        need remapping too)."""
        if not any(c is None for c in self._carriers):
            return
        used = np.unique(self._base_carrier) if len(self._base_carrier) \
            else np.empty(0, dtype=np.int32)
        remap = np.full(len(self._carriers), -1, dtype=np.int32)
        remap[used] = np.arange(len(used), dtype=np.int32)
        self._base_carrier = remap[self._base_carrier]
        self._carriers = [self._carriers[int(c)] for c in used]
        self._carrier_ids = {}
        for i, c in enumerate(self._carriers):
            try:
                self._carrier_ids[c] = i
            except TypeError:
                pass  # unhashable carrier (load() tolerates them too)

    def _maybe_merge(self) -> None:
        if len(self._delta) >= max(65536, len(self._base_dig) // 4):
            self._merge()

    # -- API ---------------------------------------------------------------

    def lookup(self, digest: bytes):
        v = self._delta.get(digest)
        if v is not None:
            return self._compose(v[0], v[1])
        i = self._base_row(digest)
        if i < 0:
            return None
        return self._compose(int(self._base_carrier[i]),
                             int(self._base_off[i]))

    def lookup_batch(self, digests: Sequence[bytes]) -> list[Any]:
        """One vectorized searchsorted over the base for the whole batch
        (the TPU engine judges chunks hundreds at a time)."""
        out: list[Any] = [None] * len(digests)
        if not digests:
            return out
        n = len(self._base_dig)
        if n:
            keys = np.array(list(digests), dtype="S20")
            idx = np.searchsorted(self._base_dig, keys)
            np.clip(idx, 0, n - 1, out=idx)
            hit = (self._base_dig[idx] == keys) & ~self._base_dead[idx]
            for j in np.nonzero(hit)[0]:
                i = int(idx[j])
                out[j] = self._compose(int(self._base_carrier[i]),
                                       int(self._base_off[i]))
        if self._delta:
            for j, d in enumerate(digests):
                v = self._delta.get(d)
                if v is not None:
                    out[j] = self._compose(v[0], v[1])
        return out

    def insert(self, digest: bytes, ref: Any) -> bool:
        """Insert if absent; returns True when this digest was new."""
        if digest in self._delta or self._base_row(digest) >= 0:
            return False
        carrier, off = self._decompose(ref)
        self._delta[digest] = (self._cid(carrier), off)
        self._len += 1
        self._maybe_merge()
        return True

    def remove(self, digest: bytes) -> bool:
        if self._delta.pop(digest, None) is not None:
            self._len -= 1
            return True
        i = self._base_row(digest)
        if i < 0:
            return False
        self._base_dead[i] = True
        self._dead += 1
        self._len -= 1
        return True

    def items(self):
        """Live (digest, ref) pairs — delta first, then base.  Base
        digests are re-padded to the full 20 bytes: numpy ``S20`` scalars
        strip trailing NULs on extraction, which would silently shorten
        ~1/256 SHA1 digests for byte-equality consumers."""
        for d, (cid, off) in self._delta.items():
            yield d, self._compose(cid, off)
        for i in range(len(self._base_dig)):
            if not self._base_dead[i]:
                yield bytes(self._base_dig[i]).ljust(20, b"\0"), self._compose(
                    int(self._base_carrier[i]), int(self._base_off[i]))

    def remove_by_carrier(self, carrier: Any) -> int:
        """Tombstone every live entry attributed to ``carrier`` (a deleted
        file id) — one vectorized mask over the base carrier column plus a
        delta scan, so `forget` needs no per-file side table of digest
        lists (which would reintroduce the per-entry object overhead this
        columnar layout exists to avoid).  Returns the number removed."""
        cid = self._carrier_ids.get(carrier)
        if cid is None:
            return 0
        dead_delta = [d for d, v in self._delta.items() if v[0] == cid]
        for d in dead_delta:
            del self._delta[d]
        n = len(dead_delta)
        if len(self._base_dig):
            hit = (self._base_carrier == cid) & ~self._base_dead
            k = int(hit.sum())
            if k:
                self._base_dead[hit] = True
                self._dead += k
                n += k
        self._len -= n
        # Release the interned id now (the string itself at the next
        # merge): churned file ids must not accumulate in the carrier
        # table or its snapshots.
        self._carriers[cid] = None
        del self._carrier_ids[carrier]
        return n

    # -- persistence (checkpoint/resume parity; SURVEY.md §5) -------------

    def save(self, path: str) -> None:
        self._merge()  # snapshot = one sorted columnar base
        _atomic_savez(
            path, compress=False,  # SHA1 columns are incompressible
            digests=self._base_dig.view(np.uint8),
            carrier_idx=self._base_carrier, offsets=self._base_off,
            carriers=np.array([json.dumps(c) for c in self._carriers],
                              dtype=object),
            exact_spec=_EXACT_SPEC)

    @classmethod
    def load(cls, path: str) -> "ExactDigestIndex":
        data = np.load(_npz_path(path), allow_pickle=True)
        idx = cls()
        if "exact_spec" not in data:  # v1: flat bytes + per-entry json refs
            raw = data["digests"].tobytes()
            refs = data["refs"]
            for i in range(len(refs)):
                idx.insert(raw[i * 20:(i + 1) * 20], json.loads(str(refs[i])))
            return idx
        idx._base_dig = np.ascontiguousarray(data["digests"]).view("S20")
        idx._base_carrier = np.asarray(data["carrier_idx"], dtype=np.int32)
        idx._base_off = np.asarray(data["offsets"], dtype=np.int64)
        idx._base_dead = np.zeros(len(idx._base_dig), dtype=bool)
        idx._carriers = [json.loads(str(c)) for c in data["carriers"]]
        idx._carrier_ids = {}
        for i, c in enumerate(idx._carriers):
            try:
                idx._carrier_ids[c] = i
            except TypeError:  # unhashable carrier (e.g. json list)
                pass
        idx._len = len(idx._base_dig)
        return idx


class MinHashLSHIndex:
    """Near-duplicate index: LSH band buckets over MinHash signatures.

    ``num_perms = bands * rows``.  A query hashes each signature band;
    items sharing any band bucket become candidates, then the true
    signature-agreement score is computed vectorized against the stored
    signature matrix (host numpy) and thresholded.
    """

    def __init__(self, num_perms: int = 64, bands: int = 16) -> None:
        if num_perms % bands:
            raise ValueError(f"bands {bands} must divide num_perms {num_perms}")
        self.num_perms = num_perms
        self.bands = bands
        self.rows = num_perms // bands
        self._buckets: list[dict[bytes, list[int]]] = [{} for _ in range(bands)]
        # Rows accumulate in a list (O(1) amortized add); the dense matrix is
        # materialized lazily and cached for queries.
        self._rows: list[np.ndarray] = []
        self._sigs_cache: np.ndarray | None = None
        self._refs: list[Any] = []
        # ref -> ALL item ids carrying it (hashable refs only): O(1)
        # signature_of (latest id) and O(items-of-ref) remove — a linear
        # _refs scan per delete would make churn quadratic at the scale
        # the exact index is engineered for.
        self._ids_by_ref: dict[Any, list[int]] = {}
        self._dead = 0  # tombstoned rows (compacted when they dominate)

    def __len__(self) -> int:
        return len(self._refs)

    def _band_keys(self, sig: np.ndarray) -> list[bytes]:
        return [sig[b * self.rows:(b + 1) * self.rows].tobytes()
                for b in range(self.bands)]

    def add(self, sig: np.ndarray, ref: Any) -> int:
        """Insert; returns the item id, or -1 for an all-``EMPTY``
        signature (a chunk/file with no sketch survivors carries no
        similarity information — indexing it would make every such item
        a spurious 1.0-score near-dup of every other)."""
        sig = np.asarray(sig, dtype=np.uint32)
        if sig.shape != (self.num_perms,):
            raise ValueError(f"signature shape {sig.shape} != ({self.num_perms},)")
        if (sig == EMPTY).all():
            return -1
        item = len(self._refs)
        self._refs.append(ref)
        self._rows.append(sig)
        self._sigs_cache = None
        try:
            self._ids_by_ref.setdefault(ref, []).append(item)
        except TypeError:
            pass  # unhashable ref: signature_of/remove unsupported for it
        for b, key in enumerate(self._band_keys(sig)):
            self._buckets[b].setdefault(key, []).append(item)
        return item

    def query(self, sig: np.ndarray, top_k: int = 5,
              min_similarity: float = 0.5) -> list[tuple[Any, float]]:
        """Top-k near-dup candidates with signature-agreement scores.

        Scoring is plain numpy: a per-query candidate set is at most a
        few thousand rows, where host vector ops win outright — eager
        accelerator dispatch costs ~ms per op (tens of ms on a remote
        backend), turning a retrieval sweep into dispatch overhead.  The
        mesh-sharded query path uses the :attr:`signatures` matrix with
        its own jitted collectives instead.
        """
        sig = np.asarray(sig, dtype=np.uint32)
        if (sig == EMPTY).all():
            return []
        cand: set[int] = set()
        for b, key in enumerate(self._band_keys(sig)):
            cand.update(self._buckets[b].get(key, ()))
        if not cand:
            return []
        ids = np.fromiter(cand, dtype=np.int64)
        sigs = self.signatures
        scores = (sigs[ids] == sig[None, :]).mean(axis=1, dtype=np.float32)
        order = np.argsort(-scores)[:top_k]
        return [(self._refs[int(ids[i])], float(scores[i]))
                for i in order
                if scores[i] >= min_similarity
                and self._refs[int(ids[i])] is not None]

    def remove(self, ref: Any) -> int:
        """Tombstone every item carrying ``ref`` (deleted file); queries
        skip tombstones.  When tombstones outnumber live rows the whole
        index compacts (ids, rows, buckets rebuilt) — without this,
        create/delete churn grows signature storage and band buckets
        without bound.  Returns the number of items removed."""
        try:
            ids = self._ids_by_ref.pop(ref, None)
        except TypeError:
            # Unhashable refs never enter the ref map — fall back to the
            # linear scan so they still tombstone.
            ids = [i for i, r in enumerate(self._refs) if r == ref]
            for i in ids:
                self._refs[i] = None
            self._dead += len(ids)
            self._maybe_compact()
            return len(ids)
        if not ids:
            return 0
        for i in ids:
            self._refs[i] = None
        self._dead += len(ids)
        self._maybe_compact()
        return len(ids)

    def _maybe_compact(self) -> None:
        if self._dead <= max(len(self._refs) - self._dead, 1024):
            return
        live = [i for i, r in enumerate(self._refs) if r is not None]
        self._refs = [self._refs[i] for i in live]
        self._rows = [self._rows[i] for i in live]
        self._sigs_cache = None
        self._dead = 0
        self._reindex()

    def _reindex(self) -> None:
        """Rebuild band buckets + the ref map from _refs/_rows (shared by
        snapshot load and tombstone compaction)."""
        self._buckets = [{} for _ in range(self.bands)]
        self._ids_by_ref = {}
        for item, (ref, sig) in enumerate(zip(self._refs, self._rows)):
            for b, key in enumerate(self._band_keys(sig)):
                self._buckets[b].setdefault(key, []).append(item)
            if ref is not None:
                try:
                    self._ids_by_ref.setdefault(ref, []).append(item)
                except TypeError:
                    pass

    def signature_of(self, ref: Any) -> np.ndarray | None:
        """Latest stored signature for ``ref`` (None when unindexed or
        removed) — the entry point for ref-keyed near-dup queries."""
        try:
            ids = self._ids_by_ref.get(ref)
        except TypeError:
            return None
        return self._rows[ids[-1]] if ids else None

    @property
    def signatures(self) -> np.ndarray:
        """The (N, P) stored signature matrix (for sharded/mesh queries)."""
        if self._sigs_cache is None:
            self._sigs_cache = (np.stack(self._rows) if self._rows
                                else np.zeros((0, self.num_perms), np.uint32))
        return self._sigs_cache

    # -- persistence ------------------------------------------------------

    def save(self, path: str) -> None:
        _atomic_savez(
            path, sigs=self.signatures,
            refs=np.array([json.dumps(r) for r in self._refs], dtype=object),
            num_perms=self.num_perms, bands=self.bands,
            sig_spec=SIG_SPEC_VERSION)

    @classmethod
    def load(cls, path: str) -> "MinHashLSHIndex":
        data = np.load(_npz_path(path), allow_pickle=True)
        spec = int(data["sig_spec"]) if "sig_spec" in data else 1
        if spec != SIG_SPEC_VERSION:
            raise ValueError(
                f"near-dup index snapshot {path!r} holds spec-v{spec} "
                f"signatures, this build computes spec-v{SIG_SPEC_VERSION}; "
                "the sets are not comparable — delete the snapshot and "
                "re-ingest (exact dedup state is unaffected)")
        idx = cls(int(data["num_perms"]), int(data["bands"]))
        sigs = np.asarray(data["sigs"], dtype=np.uint32)
        idx._rows = list(sigs)
        idx._sigs_cache = sigs if len(sigs) else None
        idx._refs = [json.loads(str(r)) for r in data["refs"]]
        idx._reindex()
        idx._dead = sum(1 for r in idx._refs if r is None)
        return idx


def _npz_path(path: str) -> str:
    return path if path.endswith(".npz") else path + ".npz"


def _atomic_savez(path: str, compress: bool = True, **arrays) -> None:
    """Write-then-rename snapshot (reference: tracker_save_storages() writes
    its ``.dat`` files the same way for crash consistency).  compress=False
    for columns that will not compress (e.g. SHA1 digests) — at tens of
    millions of entries the zlib pass dominates snapshot time."""
    final = _npz_path(path)
    tmp = final + ".tmp"
    (np.savez_compressed if compress else np.savez)(tmp, **arrays)
    # np.savez appends .npz to paths without it.
    os.replace(tmp + ".npz", final)
