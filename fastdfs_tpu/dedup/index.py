"""Digest indexes: exact (SHA1) and near-dup (MinHash + LSH banding).

The exact index is the dedup verdict authority; the LSH index serves the
tracker-side near-duplicate queries (north star: "tracker's file-id index
backed by a jax.numpy cosine/MinHash similarity search").  Both snapshot to
disk — the new stateful component SURVEY.md §5 says checkpoint/resume must
cover (the reference's restart-safety is binlogs + ``.dat`` files; the
dedup index gets the same treatment).
"""

from __future__ import annotations

import json
import os
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np

from fastdfs_tpu.ops.minhash import EMPTY

# Bumped whenever the signature spec changes (v2 = the survivor sketch,
# round 3); snapshots carry it so a stale index fails loudly instead of
# silently scoring noise against incompatible signatures.
SIG_SPEC_VERSION = 2


class ExactDigestIndex:
    """digest bytes → opaque ref (chunk locator / file id)."""

    def __init__(self) -> None:
        self._map: dict[bytes, Any] = {}

    def __len__(self) -> int:
        return len(self._map)

    def lookup(self, digest: bytes):
        return self._map.get(digest)

    def lookup_batch(self, digests: Sequence[bytes]) -> list[Any]:
        return [self._map.get(d) for d in digests]

    def insert(self, digest: bytes, ref: Any) -> bool:
        """Insert if absent; returns True when this digest was new."""
        if digest in self._map:
            return False
        self._map[digest] = ref
        return True

    def remove(self, digest: bytes) -> bool:
        return self._map.pop(digest, None) is not None

    def items(self):
        return self._map.items()

    # -- persistence (checkpoint/resume parity; SURVEY.md §5) -------------

    def save(self, path: str) -> None:
        digests = np.frombuffer(b"".join(self._map.keys()), dtype=np.uint8)
        refs = np.array([json.dumps(v) for v in self._map.values()], dtype=object)
        _atomic_savez(path, digests=digests, refs=refs)

    @classmethod
    def load(cls, path: str) -> "ExactDigestIndex":
        data = np.load(_npz_path(path), allow_pickle=True)
        idx = cls()
        raw = data["digests"].tobytes()
        refs = data["refs"]
        for i in range(len(refs)):
            idx._map[raw[i * 20:(i + 1) * 20]] = json.loads(str(refs[i]))
        return idx


class MinHashLSHIndex:
    """Near-duplicate index: LSH band buckets over MinHash signatures.

    ``num_perms = bands * rows``.  A query hashes each signature band;
    items sharing any band bucket become candidates, then the true
    signature-agreement score is computed vectorized against the stored
    signature matrix (TPU/CPU via jnp) and thresholded.
    """

    def __init__(self, num_perms: int = 64, bands: int = 16) -> None:
        if num_perms % bands:
            raise ValueError(f"bands {bands} must divide num_perms {num_perms}")
        self.num_perms = num_perms
        self.bands = bands
        self.rows = num_perms // bands
        self._buckets: list[dict[bytes, list[int]]] = [{} for _ in range(bands)]
        # Rows accumulate in a list (O(1) amortized add); the dense matrix is
        # materialized lazily and cached for queries.
        self._rows: list[np.ndarray] = []
        self._sigs_cache: np.ndarray | None = None
        self._refs: list[Any] = []
        # ref -> latest item id (hashable refs only), for O(1)
        # signature_of — the production query path "what is <file_id>
        # near?" enters by ref, not by signature.
        self._by_ref: dict[Any, int] = {}

    def __len__(self) -> int:
        return len(self._refs)

    def _band_keys(self, sig: np.ndarray) -> list[bytes]:
        return [sig[b * self.rows:(b + 1) * self.rows].tobytes()
                for b in range(self.bands)]

    def add(self, sig: np.ndarray, ref: Any) -> int:
        """Insert; returns the item id, or -1 for an all-``EMPTY``
        signature (a chunk/file with no sketch survivors carries no
        similarity information — indexing it would make every such item
        a spurious 1.0-score near-dup of every other)."""
        sig = np.asarray(sig, dtype=np.uint32)
        if sig.shape != (self.num_perms,):
            raise ValueError(f"signature shape {sig.shape} != ({self.num_perms},)")
        if (sig == EMPTY).all():
            return -1
        item = len(self._refs)
        self._refs.append(ref)
        self._rows.append(sig)
        self._sigs_cache = None
        try:
            self._by_ref[ref] = item
        except TypeError:
            pass  # unhashable ref: signature_of unsupported for it
        for b, key in enumerate(self._band_keys(sig)):
            self._buckets[b].setdefault(key, []).append(item)
        return item

    def query(self, sig: np.ndarray, top_k: int = 5,
              min_similarity: float = 0.5) -> list[tuple[Any, float]]:
        """Top-k near-dup candidates with signature-agreement scores."""
        sig = np.asarray(sig, dtype=np.uint32)
        if (sig == EMPTY).all():
            return []
        cand: set[int] = set()
        for b, key in enumerate(self._band_keys(sig)):
            cand.update(self._buckets[b].get(key, ()))
        if not cand:
            return []
        ids = np.fromiter(cand, dtype=np.int64)
        sigs = self.signatures
        scores = np.asarray(
            jnp.mean(jnp.asarray(sigs[ids]) == jnp.asarray(sig)[None, :],
                     axis=1, dtype=jnp.float32))
        order = np.argsort(-scores)[:top_k]
        return [(self._refs[int(ids[i])], float(scores[i]))
                for i in order
                if scores[i] >= min_similarity
                and self._refs[int(ids[i])] is not None]

    def remove(self, ref: Any) -> int:
        """Tombstone every item carrying ``ref`` (deleted file).  Bucket
        entries and signature rows stay (append-only ids); queries skip
        tombstones.  Returns the number of items removed."""
        n = 0
        for i, r in enumerate(self._refs):
            if r == ref:
                self._refs[i] = None
                n += 1
        try:
            self._by_ref.pop(ref, None)
        except TypeError:
            pass
        return n

    def signature_of(self, ref: Any) -> np.ndarray | None:
        """Latest stored signature for ``ref`` (None when unindexed or
        removed) — the entry point for ref-keyed near-dup queries."""
        try:
            i = self._by_ref.get(ref)
        except TypeError:
            return None
        return self._rows[i] if i is not None else None

    @property
    def signatures(self) -> np.ndarray:
        """The (N, P) stored signature matrix (for sharded/mesh queries)."""
        if self._sigs_cache is None:
            self._sigs_cache = (np.stack(self._rows) if self._rows
                                else np.zeros((0, self.num_perms), np.uint32))
        return self._sigs_cache

    # -- persistence ------------------------------------------------------

    def save(self, path: str) -> None:
        _atomic_savez(
            path, sigs=self.signatures,
            refs=np.array([json.dumps(r) for r in self._refs], dtype=object),
            num_perms=self.num_perms, bands=self.bands,
            sig_spec=SIG_SPEC_VERSION)

    @classmethod
    def load(cls, path: str) -> "MinHashLSHIndex":
        data = np.load(_npz_path(path), allow_pickle=True)
        spec = int(data["sig_spec"]) if "sig_spec" in data else 1
        if spec != SIG_SPEC_VERSION:
            raise ValueError(
                f"near-dup index snapshot {path!r} holds spec-v{spec} "
                f"signatures, this build computes spec-v{SIG_SPEC_VERSION}; "
                "the sets are not comparable — delete the snapshot and "
                "re-ingest (exact dedup state is unaffected)")
        idx = cls(int(data["num_perms"]), int(data["bands"]))
        sigs = np.asarray(data["sigs"], dtype=np.uint32)
        idx._rows = list(sigs)
        idx._sigs_cache = sigs if len(sigs) else None
        idx._refs = [json.loads(str(r)) for r in data["refs"]]
        for item, sig in enumerate(idx._rows):
            for b, key in enumerate(idx._band_keys(sig)):
                idx._buckets[b].setdefault(key, []).append(item)
        for item, ref in enumerate(idx._refs):
            if ref is not None:
                try:
                    idx._by_ref[ref] = item
                except TypeError:
                    pass
        return idx


def _npz_path(path: str) -> str:
    return path if path.endswith(".npz") else path + ".npz"


def _atomic_savez(path: str, **arrays) -> None:
    """Write-then-rename snapshot (reference: tracker_save_storages() writes
    its ``.dat`` files the same way for crash consistency)."""
    final = _npz_path(path)
    tmp = final + ".tmp"
    np.savez_compressed(tmp, **arrays)
    # np.savez appends .npz to paths without it.
    os.replace(tmp + ".npz", final)
