"""DedupEngine: the upload-path fingerprint pipeline.

Pipeline per ingested byte stream (north star; replaces the scalar CRC32
loop in the reference's ``storage/storage_dio.c:dio_write_file()``):

    bytes ──CDC (gear, position-parallel)──► chunk spans
          ──pad to pow2 buckets──► fixed-shape batches (XLA-friendly)
          ──SHA1 batch + MinHash batch (one jit per bucket shape)──►
          digests + signatures
          ──exact index──► per-chunk write/skip verdicts
          ──LSH index──► file-level near-duplicate candidates

Chunks are padded to power-of-two length buckets so every distinct jitted
shape is reused across files (XLA traces once per bucket, not per file).
The file-level MinHash signature is the element-wise min over its chunks'
signatures — exact for the union of their shingle sets (min of mins), so
near-dup detection works at file granularity without rehashing the file.
"""

from __future__ import annotations

import functools
import hashlib
from dataclasses import dataclass, field

import numpy as np

from fastdfs_tpu.dedup.index import ExactDigestIndex, MinHashLSHIndex
from fastdfs_tpu.ops import gear_cdc
from fastdfs_tpu.ops.minhash import DEFAULT_PERMS, DEFAULT_SHINGLE, minhash_batch
from fastdfs_tpu.ops.sha1 import digest_bytes


def _tpu_available() -> bool:
    import jax
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@dataclass(frozen=True)
class DedupConfig:
    min_size: int = gear_cdc.DEFAULT_MIN_SIZE
    avg_bits: int = gear_cdc.DEFAULT_AVG_BITS
    max_size: int = gear_cdc.DEFAULT_MAX_SIZE
    num_perms: int = DEFAULT_PERMS
    shingle: int = DEFAULT_SHINGLE
    lsh_bands: int = 16
    near_dup_threshold: float = 0.5
    near_dup_top_k: int = 5
    # Fixed row tile per jitted batch: chunks are processed in groups of
    # exactly this many rows (last group padded), so each pow2 length
    # bucket compiles exactly ONE XLA shape — a varying chunk count would
    # otherwise retrace per distinct N and dominate wall-clock.
    row_tile: int = 256
    # None = auto: Pallas kernels on TPU, XLA reference elsewhere.  The
    # two paths are bit-identical (tests/test_pallas_kernels.py).
    use_pallas: bool | None = None
    # Cut-selection policy: CDC_POLICY_DEFAULT (frozen, ref-identical) or
    # the opt-in CDC_POLICY_SKIPMIN.  NEVER change on a live index — the
    # policies are distinct content-address namespaces (the sidecar
    # discards snapshots on mismatch, same as a spec bump).
    cdc_policy: int = gear_cdc.CDC_POLICY_DEFAULT
    # Fingerprint fan-out: shard each (row_tile, blen) batch's rows over
    # this many local devices via parallel.make_fingerprint_step.
    # None = auto (all local devices when >1 and a TPU backend is up;
    # otherwise 1); 1 = single-device paths.  row_tile must divide by it.
    fan_out: int | None = None


@dataclass
class ChunkRecord:
    offset: int
    length: int
    digest: bytes          # 20-byte SHA1
    duplicate: bool
    dup_of: object = None  # ref stored at first sight of this digest


@dataclass
class IngestReport:
    file_ref: str
    size: int
    chunks: list[ChunkRecord] = field(default_factory=list)
    file_signature: np.ndarray | None = None
    near_dups: list[tuple[object, float]] = field(default_factory=list)

    @property
    def bytes_total(self) -> int:
        return self.size

    @property
    def bytes_duplicate(self) -> int:
        return sum(c.length for c in self.chunks if c.duplicate)

    @property
    def dedup_ratio(self) -> float:
        return self.bytes_duplicate / self.size if self.size else 0.0


def _bucket_len(n: int, min_size: int, max_size: int) -> int:
    """Smallest power-of-two >= n, clamped to [min_size, max_size]."""
    b = max(min_size, 1)
    while b < n:
        b <<= 1
    return min(b, max_size) if n <= max_size else n


@functools.lru_cache(maxsize=64)
def _packed_concat(half: int):
    """Jitted (digests..., sigs...) -> one (T, 5+P) array, cached per
    tile count (segment sizes repeat, so arities do too)."""
    import jax
    import jax.numpy as jnp

    def f(*args):
        return jnp.concatenate(
            [jnp.concatenate([args[i], args[half + i]], axis=1)
             for i in range(half)])
    return jax.jit(f)


class DedupEngine:
    """Stateful dedup engine: chunk, fingerprint, and judge byte streams.

    One engine per storage process.  Compute (CDC/SHA1/MinHash) runs on the
    accelerator; index mutation stays on the host.  The verdicts gate disk
    writes in the storage daemon (write unique chunks, reference dups).
    """

    def __init__(self, config: DedupConfig | None = None) -> None:
        self.config = config or DedupConfig()
        if self.config.cdc_policy not in (gear_cdc.CDC_POLICY_DEFAULT,
                                          gear_cdc.CDC_POLICY_SKIPMIN):
            raise ValueError(f"unknown cdc_policy {self.config.cdc_policy}")
        self.exact = ExactDigestIndex()
        self.near = MinHashLSHIndex(self.config.num_perms, self.config.lsh_bands)
        use_pallas = self.config.use_pallas
        if use_pallas is None:
            # The survivor kernel is specialized to the default shingle
            # width; other widths take the (bit-identical) XLA reference.
            use_pallas = _tpu_available() and self.config.shingle == 5
        self._use_pallas = use_pallas
        fan = self.config.fan_out
        if fan is None:
            # Auto fan-out only where it pays: a multi-chip TPU host.  On
            # CPU hosts the XLA sha1 compile cost per bucket shape (~2 min
            # each) dwarfs any parallel win, so auto stays single-path —
            # tests opt in explicitly with tiny geometries.
            if self._use_pallas:
                import jax
                fan = len(jax.local_devices())
            else:
                fan = 1
        if fan > 1 and self.config.row_tile % fan:
            raise ValueError(f"row_tile {self.config.row_tile} must divide "
                             f"by fan_out {fan}")
        self._fan_out = fan
        self._fp_step = None  # built lazily: jitted multi-device step

    def _fingerprint_batch(self, batch: np.ndarray, lens: np.ndarray):
        """Dispatch one (row_tile, blen) batch; returns device arrays
        (futures) so callers can overlap multiple buckets in flight."""
        cfg = self.config
        if self._fan_out > 1:
            # Multi-chip fan-out: rows shard over every local device via
            # ONE jitted shard_map (parallel.make_fingerprint_step) —
            # bit-identical digests/signatures to the single-device
            # paths (tests/test_cdc_kernels.py pins this).
            if self._fp_step is None:
                from fastdfs_tpu.parallel.ingest_step import (
                    fingerprint_mesh, make_fingerprint_step)
                self._fp_step = make_fingerprint_step(
                    fingerprint_mesh(self._fan_out),
                    cfg.num_perms, cfg.shingle)
            # jit owns the transfer here: it splits the rows across the
            # mesh per in_specs, so a manual single-device device_put
            # would only add a copy.
            return self._fp_step(batch, lens.astype(np.int32))
        if self._use_pallas:
            import jax

            from fastdfs_tpu.ops.pallas_minhash import minhash_batch_pallas
            from fastdfs_tpu.ops.pallas_sha1 import sha1_batch_pallas
            # ONE explicit transfer shared by both kernels: passing the
            # numpy batch to each jit would convert (and, on a leaky
            # remote client, strand) a separate host copy per kernel.
            batch = jax.device_put(batch)
            lens = jax.device_put(lens)
            sub = max(1, min(16, batch.shape[0] // 128))
            d = sha1_batch_pallas(batch, lens, int(batch.shape[1]), sub=sub)
            s = minhash_batch_pallas(batch, lens, cfg.num_perms, cfg.shingle)
        else:
            # Host path: hashlib per row.  The XLA sha1_batch exists as the
            # jittable reference (tests/test_sha1.py) but its 80-round
            # unrolled graph costs ~2 minutes of XLA-CPU compile per bucket
            # shape, while hashlib runs at ~1 GB/s with none — off the TPU
            # the scalar loop IS the right tool.
            d = np.zeros((batch.shape[0], 5), dtype=np.uint32)
            for i in range(batch.shape[0]):
                dig = hashlib.sha1(batch[i, :lens[i]].tobytes()).digest()
                d[i] = np.frombuffer(dig, dtype=">u4")
            s = minhash_batch(batch, lens, cfg.num_perms, cfg.shingle)
        return d, s

    # -- pure compute ------------------------------------------------------

    def fingerprint(self, data: bytes, cuts: list[int] | None = None
                    ) -> tuple[list[tuple[int, int]], np.ndarray, np.ndarray]:
        """Chunk + fingerprint a stream: returns (spans, digests, signatures).

        spans: list of (offset, length).  digests: (N, 5) uint32.
        signatures: (N, P) uint32.  No index state is touched.

        ``cuts`` (exclusive chunk ends) skips the chunking pass when the
        caller already ran an identical CDC — the daemon's native AVX2
        chunker shares the gear table, so in sidecar mode the bytes only
        cross the accelerator link once, for hashing.
        """
        cfg = self.config
        if cuts is None:
            cuts = gear_cdc.chunk_stream(data, cfg.min_size, cfg.avg_bits,
                                         cfg.max_size,
                                         cdc_policy=cfg.cdc_policy)
        spans: list[tuple[int, int]] = []
        last = 0
        for c in cuts:
            spans.append((last, c - last))
            last = c
        if not spans:
            return [], np.zeros((0, 5), np.uint32), np.zeros((0, cfg.num_perms), np.uint32)

        digests = np.zeros((len(spans), 5), dtype=np.uint32)
        sigs = np.zeros((len(spans), cfg.num_perms), dtype=np.uint32)
        arr = np.frombuffer(data, dtype=np.uint8)

        # Group chunks by pow2 bucket so each jitted shape is reused.
        by_bucket: dict[int, list[int]] = {}
        for i, (off, ln) in enumerate(spans):
            by_bucket.setdefault(_bucket_len(ln, cfg.min_size, cfg.max_size), []).append(i)

        # Fixed (row_tile, blen) shapes: one compile per bucket, ever.
        # Remote-accelerator discipline (each device<->host transfer pays
        # fixed latency; fresh host buffers transfer ~50x slower than
        # reused ones — measured on this machine's tunnel):
        #   * tiles are packed into REUSED thread-local staging buffers,
        #   * all tiles dispatch asynchronously,
        #   * digests and signatures are concatenated ON DEVICE so the
        #     whole segment costs exactly one two-array fetch.
        # Device memory stays bounded by the segment size the daemon
        # streams (storage.conf:dedup_segment_bytes), not the file size.
        import jax
        import jax.numpy as jnp

        tile = cfg.row_tile
        groups: list[list[int]] = []
        outs_d = []
        outs_s = []
        # Double-buffered staging (ADVICE r5): tiles dispatch
        # asynchronously and are fetched only once at the end, and PJRT
        # host-buffer semantics are backend-dependent — some clients
        # hold the host buffer zero-copy until the transfer completes.
        # Rotate 2 staging slots per bucket size AND block on the tile
        # that last used a slot before reusing it (its outputs being
        # ready implies its input transfer finished) — rotation alone
        # would still overwrite tile N while in flight once tile N+2
        # claims its slot.  Net effect: a pipeline depth of 2 dispatches
        # with reused host buffers.  tests/test_dedup_engine.py pins the
        # digests against the hashlib path on multi-tile input.
        _N_STAGING_SLOTS = 2
        slot_last: dict[tuple[int, int], tuple] = {}
        for blen, idxs in sorted(by_bucket.items()):
            for tile_no, start in enumerate(range(0, len(idxs), tile)):
                slot = tile_no % _N_STAGING_SLOTS
                prev = slot_last.get((blen, slot))
                if prev is not None:
                    jax.block_until_ready(prev)
                batch_buf = gear_cdc.staging_buffer(
                    tile * blen, slot=slot).reshape(tile, blen)
                group = idxs[start:start + tile]
                batch_buf[:] = 0
                lens = np.zeros(tile, dtype=np.int32)
                for row, i in enumerate(group):
                    off, ln = spans[i]
                    batch_buf[row, :ln] = arr[off:off + ln]
                    lens[row] = ln
                d, s = self._fingerprint_batch(batch_buf, lens)
                slot_last[(blen, slot)] = (d, s)
                groups.append(group)
                outs_d.append(d)
                outs_s.append(s)
        # ONE fetched array for the whole segment: digests (T,5) and
        # signatures (T,P) concatenate along axis 1 (both uint32) so the
        # fetch pays a single round-trip latency, then split on host.
        # The concat itself runs as ONE jitted call — as eager ops it
        # would be ~2 dispatches per tile, each a round-trip on a remote
        # backend (measured 20x slower).
        packed = np.asarray(jax.device_get(
            _packed_concat(len(outs_d))(*outs_d, *outs_s)))
        d_all = packed[:, :5]
        s_all = packed[:, 5:]
        for gi, group in enumerate(groups):
            base = gi * tile
            for row, i in enumerate(group):
                digests[i] = d_all[base + row]
                sigs[i] = s_all[base + row]
        return spans, digests, sigs

    def warmup(self) -> None:
        """Compile every jitted shape the fingerprint path can hit (one
        per pow2 length bucket) so the first real upload never pays a
        trace.  Call once at process start (the sidecar does, before it
        binds its socket)."""
        cfg = self.config
        blen = max(cfg.min_size, 1)
        while True:
            batch = np.zeros((cfg.row_tile, blen), dtype=np.uint8)
            lens = np.ones(cfg.row_tile, dtype=np.int32)
            d, s = self._fingerprint_batch(batch, lens)
            np.asarray(d), np.asarray(s)
            if blen >= cfg.max_size:
                break
            blen = min(blen << 1, cfg.max_size)

    # -- stateful ingest ---------------------------------------------------

    def ingest(self, data: bytes, file_ref: str, update_index: bool = True) -> IngestReport:
        """Full upload-path dedup: fingerprint, judge against the indexes,
        optionally commit new digests/signatures to them."""
        report = IngestReport(file_ref=file_ref, size=len(data))
        spans, digests, sigs = self.fingerprint(data)
        if not spans:
            return report

        raw = digest_bytes(digests)
        # Repeats *within* this stream must judge as duplicates even on a
        # dry run, so track first-seen digests locally too.
        seen_here: dict[bytes, list] = {}
        for i, (off, ln) in enumerate(spans):
            dig = raw[i * 20:(i + 1) * 20]
            existing = self.exact.lookup(dig)
            if existing is None:
                existing = seen_here.get(dig)
            if existing is None:
                seen_here[dig] = [file_ref, off]
                if update_index:
                    self.exact.insert(dig, [file_ref, off])
                report.chunks.append(ChunkRecord(off, ln, dig, duplicate=False))
            else:
                report.chunks.append(ChunkRecord(off, ln, dig, duplicate=True,
                                                 dup_of=existing))

        # File-level signature: min over chunk signatures == MinHash of the
        # union of their shingle sets.
        file_sig = sigs.min(axis=0)
        report.file_signature = file_sig
        report.near_dups = [
            (ref, score) for ref, score in self.near.query(
                file_sig, self.config.near_dup_top_k, self.config.near_dup_threshold)
            if ref != file_ref
        ]
        if update_index:
            self.near.add(file_sig, file_ref)
        return report

    # -- persistence -------------------------------------------------------

    def save(self, exact_path: str, near_path: str) -> None:
        self.exact.save(exact_path)
        self.near.save(near_path)

    @classmethod
    def load(cls, exact_path: str, near_path: str,
             config: DedupConfig | None = None) -> "DedupEngine":
        eng = cls(config)
        eng.exact = ExactDigestIndex.load(exact_path)
        eng.near = MinHashLSHIndex.load(near_path)
        return eng
