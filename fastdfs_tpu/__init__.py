"""fastdfs_tpu — a TPU-native distributed file-storage framework.

A ground-up rebuild of the capabilities of FastDFS (reference:
``xigui2013/fastdfs``, a C tracker/storage/client distributed file system)
with a TPU-accelerated content-dedup engine on the storage upload path.

Layout (mirrors SURVEY.md §1's layer map, re-designed TPU-first):

- ``fastdfs_tpu.common``   — L1: wire protocol, file-ID codec, config, CRC32.
  (reference: ``common/fdfs_proto.h``, ``common/fdfs_global.c``)
- ``fastdfs_tpu.ops``      — JAX/Pallas compute kernels: gear-hash CDC,
  batched SHA1, MinHash.  (no reference equivalent; replaces the scalar
  CRC32 loop in ``storage/storage_dio.c:dio_write_file()``)
- ``fastdfs_tpu.dedup``    — the dedup engine + digest/ANN indexes, single
  chip and mesh-sharded.
- ``fastdfs_tpu.parallel`` — device mesh, shardings, collectives.
- ``fastdfs_tpu.client``   — Python client speaking the binary TCP protocol
  (reference: ``client/storage_client.c``, ``client/tracker_client.c``).
- ``native/``              — C++ tracker daemon, storage daemon and client
  library (reference: ``tracker/``, ``storage/``, ``client/``).

The wire protocol is *FastDFS-shaped*: the reference mount was empty at
survey time (see SURVEY.md provenance warning), so numeric constants follow
the documented upstream layout but are not guaranteed byte-compatible.
"""

__version__ = "0.1.0"

FDFS_TPU_VERSION = __version__
