"""Low-level framed connection + connection pool (reference:
libfastcommon sockopt.c tcprecvdata_nb/tcpsenddata_nb, fdfs_proto.c
fdfs_recv_response, and connection_pool.c for the pooling)."""

from __future__ import annotations

import select
import socket
import threading
import time
from collections import deque

from fastdfs_tpu.common.protocol import (HEADER_SIZE, Header, pack_header,
                                         priority_frame, unpack_header,
                                         unpack_retry_after)


class ProtocolError(Exception):
    pass


class StatusError(ProtocolError):
    """Non-zero status byte in a response header.

    ``retry_after_ms``: for EBUSY (16) refusals from the admission
    ladder the daemon's error body carries a retry-after hint; 0 for
    every other status (and for EBUSY sources that predate the hint —
    max_connections refusals, drain refusals)."""

    def __init__(self, status: int, context: str = "",
                 retry_after_ms: int = 0):
        self.status = status
        self.retry_after_ms = retry_after_ms
        super().__init__(f"server returned status {status}"
                         + (f" ({context})" if context else ""))


class Connection:
    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host, self.port = host, port
        self.timeout = timeout
        # Set on any mid-message failure: the stream cannot be resynced,
        # so a pool must discard rather than reuse this connection.
        self.broken = False
        # Distributed tracing: when set (a fastdfs_tpu.trace.TraceContext),
        # every request is prefixed with its TRACE_CTX frame so the
        # daemon's spans stitch into the trace.  Sticky until cleared;
        # the pool clears it on release so a parked connection never
        # leaks one caller's trace onto the next.
        self.trace_ctx = None
        # Request QoS: when set (a PriorityClass int), every request is
        # prefixed with its 1-byte PRIORITY frame so the daemon's
        # admission ladder knows the class (untagged requests get an
        # opcode-derived default server-side).  Sticky like trace_ctx —
        # the daemon consumes one tag per request, so the frame is
        # re-sent each time — and cleared by the pool on release.
        self.priority = None
        self.sock = self._connect()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- framing -----------------------------------------------------------

    def send_request(self, cmd: int, body: bytes = b"",
                     body_len: int | None = None) -> None:
        """Send one framed request.

        ``body`` is either bytes, or an ITERABLE of bytes segments (then
        ``body_len`` — the total — is required): multi-GB uploads stream
        through in bounded segments instead of materializing in memory.
        """
        # The server closes a connection after an error response that left
        # request bytes unread (it cannot resync mid-stream).  A request
        # boundary is the one safe place to reconnect, so retry once — the
        # same recovery the reference's connection pool performs.
        streaming = not isinstance(body, (bytes, bytearray, memoryview))
        if streaming and body_len is None:
            raise ValueError("iterable body requires body_len")
        hdr = pack_header((len(body) if body_len is None else body_len), cmd)
        if self.trace_ctx is not None:
            # Prefix frame first: the daemon stashes the context and
            # applies it to this request (it sends no response of its
            # own, so request/response pairing is unchanged).
            hdr = self.trace_ctx.frame() + hdr
        if self.priority is not None:
            # Same prefix-frame discipline for the QoS class byte.
            hdr = priority_frame(self.priority) + hdr
        first = hdr if streaming else hdr + bytes(body)
        try:
            self.sock.sendall(first)
        except OSError:
            # Nothing of a streamed body has been consumed yet (only the
            # header went to the dead socket), so a single reconnect is
            # still safe for both shapes.
            self.close()
            self.sock = self._connect()
            self.broken = False
            try:
                self.sock.sendall(first)
            except OSError:
                self.broken = True
                raise
        if streaming:
            # Past the header there is no safe resend point: a partially
            # streamed body on a reconnected socket would desync framing.
            # ANY failure — socket error or the source iterable raising
            # (e.g. a closed file wrapper) — marks the connection broken
            # so the pool can never re-issue the desynced stream.
            sent = 0
            try:
                for seg in body:
                    if seg:
                        self.sock.sendall(seg)
                        sent += len(seg)
            except BaseException:
                self.broken = True
                raise
            if sent != body_len:
                self.broken = True
                raise ProtocolError(
                    f"streaming body produced {sent} bytes, "
                    f"declared {body_len}")

    def send_raw(self, data: bytes) -> None:
        try:
            self.sock.sendall(data)
        except OSError:
            self.broken = True
            raise

    def recv_exact(self, n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            try:
                chunk = self.sock.recv(min(n - got, 256 * 1024))
            except OSError:
                self.broken = True
                raise
            if not chunk:
                self.broken = True
                raise ProtocolError("connection closed mid-message")
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def recv_exact_into(self, mv: memoryview) -> None:
        """Fill a writable buffer with exactly len(mv) bytes via
        recv_into — no intermediate bytes objects, so a large download
        costs one copy (kernel -> caller buffer) instead of three."""
        got = 0
        n = len(mv)
        while got < n:
            try:
                k = self.sock.recv_into(mv[got:], n - got)
            except OSError:
                self.broken = True
                raise
            if k == 0:
                self.broken = True
                raise ProtocolError("connection closed mid-message")
            got += k

    def recv_header(self) -> Header:
        return unpack_header(self.recv_exact(HEADER_SIZE))

    def recv_response(self, context: str = "") -> bytes:
        """Header + body; raises StatusError on non-zero status.

        Large bodies (>= 1 MB) are received straight into one
        preallocated buffer via recv_into — the chunk-list-and-join path
        costs an extra full copy plus per-piece overhead that capped
        downloads well below the wire rate."""
        hdr = self.recv_header()
        if hdr.status != 0:
            self._raise_status(hdr, context)
        if not hdr.pkg_len:
            return b""
        if hdr.pkg_len >= (1 << 20):
            buf = bytearray(hdr.pkg_len)
            self.recv_exact_into(memoryview(buf))
            return bytes(buf)
        return self.recv_exact(hdr.pkg_len)

    def _raise_status(self, hdr: Header, context: str) -> None:
        # Error responses may carry a (small) body; drain it so the
        # connection stays framed and reusable.  An EBUSY body is the
        # admission ladder's 8-byte retry-after hint — surface it on
        # the exception (unpack_retry_after answers 0 for the short or
        # absent bodies older EBUSY sources send).
        body = self.recv_exact(hdr.pkg_len) if hdr.pkg_len else b""
        raise StatusError(hdr.status, context,
                          retry_after_ms=(unpack_retry_after(body)
                                          if hdr.status == 16 else 0))

    def recv_response_into(self, mv: memoryview, context: str = "") -> None:
        """Response whose body lands in a caller buffer of EXACTLY the
        expected size (ranged downloads know their length up front).  A
        size mismatch is a framing violation: the connection is marked
        broken (the unread tail cannot be resynced)."""
        hdr = self.recv_header()
        if hdr.status != 0:
            self._raise_status(hdr, context)
        if hdr.pkg_len != len(mv):
            self.broken = True
            raise ProtocolError(
                f"response body is {hdr.pkg_len} bytes, expected {len(mv)}"
                + (f" ({context})" if context else ""))
        self.recv_exact_into(mv)

    def recv_response_stream(self, fh, context: str = "",
                             segment: int = 256 * 1024) -> int:
        """Stream a response body into file object ``fh`` in bounded
        recv_into segments — a multi-GB download holds O(segment) client
        memory, the mirror of send_request's iterable-body path.
        Returns the body length."""
        hdr = self.recv_header()
        if hdr.status != 0:
            self._raise_status(hdr, context)
        remaining = hdr.pkg_len
        if remaining == 0:
            return 0
        buf = bytearray(min(segment, remaining))
        mv = memoryview(buf)
        while remaining > 0:
            want = min(len(buf), remaining)
            try:
                k = self.sock.recv_into(mv[:want], want)
            except OSError:
                self.broken = True
                raise
            if k == 0:
                self.broken = True
                raise ProtocolError("connection closed mid-message")
            try:
                fh.write(mv[:k])
            except BaseException:
                # The SINK failing (ENOSPC, closed file) leaves body
                # bytes unread — the stream cannot be resynced, so the
                # pool must never reuse this connection (the mirror of
                # send_request's any-failure guard on the source side).
                self.broken = True
                raise
            remaining -= k
        return hdr.pkg_len


class ConnectionPool:
    """Endpoint-keyed pool of idle connections with borrow-time health
    checks (reference: libfastcommon connection_pool.c,
    ``g_use_connection_pool``).

    A request/response protocol leaves a healthy connection quiet between
    operations, so an idle socket that polls readable has either been
    closed by the peer (EOF) or desynced (stray bytes) — both discard.
    Connections marked ``broken`` by mid-message failures are never
    pooled.  Thread-safe; callers acquire/release around each operation.

    Dead-peer backoff: after a transport failure the caller reports the
    endpoint via ``mark_dead``; for ``dead_peer_cooldown`` seconds
    ``is_dead`` answers True so routing layers can deprioritize the
    endpoint instead of paying a connect timeout per operation.  The
    mark is advisory — callers with no alternative still connect, and a
    successful fresh connect clears it early.

    Multiplexing (ISSUE 18): the pool tracks in-use connections per
    endpoint; ``max_conns_per_endpoint`` caps idle + in-use together
    (0 = unbounded).  At the cap, ``acquire`` waits up to
    ``cap_wait_seconds`` for a release before connecting over the cap
    anyway (recorded in ``cap_overflows``) — a soft bound, so a leaked
    borrow degrades to the uncapped behavior instead of deadlocking a
    download.

    Hygiene (ISSUE 18): a time-gated ``sweep`` — run from ``release``
    and ``acquire``, or called directly — closes idle connections past
    ``max_idle_seconds`` and drops expired ``_dead`` marks even for
    endpoints no caller ever touches again (peers that left the
    cluster), and ``max_idle_total`` caps the pool-wide parked count by
    evicting the oldest idle connection across all endpoints.
    """

    def __init__(self, max_idle_per_endpoint: int = 8,
                 max_idle_seconds: float = 300.0,
                 dead_peer_cooldown: float = 30.0,
                 max_conns_per_endpoint: int = 0,
                 max_idle_total: int = 64,
                 cap_wait_seconds: float = 5.0,
                 sweep_interval: float = 5.0):
        self.max_idle_per_endpoint = max_idle_per_endpoint
        self.max_idle_seconds = max_idle_seconds
        self.dead_peer_cooldown = dead_peer_cooldown
        self.max_conns_per_endpoint = max_conns_per_endpoint
        self.max_idle_total = max_idle_total
        self.cap_wait_seconds = cap_wait_seconds
        self.sweep_interval = sweep_interval
        self._idle: dict[tuple[str, int], deque] = {}
        self._dead: dict[tuple[str, int], float] = {}
        self._in_use: dict[tuple[str, int], int] = {}
        self._lock = threading.Lock()
        self._released = threading.Condition(self._lock)
        self._last_sweep = time.monotonic()
        self.hits = 0
        self.misses = 0
        self.cap_overflows = 0
        self.swept_idle = 0

    def acquire(self, host: str, port: int,
                timeout: float = 30.0) -> Connection:
        self._maybe_sweep()
        key = (host, port)
        deadline = None
        while True:
            now = time.monotonic()
            with self._lock:
                q = self._idle.get(key)
                entry = q.popleft() if q else None
                if entry is not None:
                    self._in_use[key] = self._in_use.get(key, 0) + 1
                elif (self.max_conns_per_endpoint > 0 and
                      self._in_use.get(key, 0) >=
                      self.max_conns_per_endpoint):
                    # At the cap with nothing parked: wait for a release
                    # (bounded), then overflow rather than deadlock.
                    if deadline is None:
                        deadline = now + max(0.0, self.cap_wait_seconds)
                    remaining = deadline - time.monotonic()
                    if remaining > 0:
                        self._released.wait(remaining)
                        continue
                    self.cap_overflows += 1
                    self._in_use[key] = self._in_use.get(key, 0) + 1
                else:
                    self._in_use[key] = self._in_use.get(key, 0) + 1
            if entry is None:
                break
            conn, parked_at = entry
            if now - parked_at > self.max_idle_seconds or not _quiet(conn):
                conn.close()
                with self._lock:
                    # The dead parked conn is not a borrow; retry the
                    # idle queue without double-counting.
                    self._dec_in_use(key)
                continue
            with self._lock:
                self.hits += 1
            return conn
        with self._lock:
            self.misses += 1
        try:
            conn = Connection(host, port, timeout)
        except OSError:
            with self._lock:
                self._dec_in_use(key)
            raise
        # A fresh connect succeeding is live proof: clear any cooldown
        # early rather than waiting out the timer.
        with self._lock:
            self._dead.pop(key, None)
        return conn

    def _dec_in_use(self, key: tuple[str, int]) -> None:
        # _lock held.  Floor at zero: a double release (or a release of
        # a connection acquired before a pool reconfigure) must never
        # wedge the cap accounting negative.
        n = self._in_use.get(key, 0) - 1
        if n > 0:
            self._in_use[key] = n
        else:
            self._in_use.pop(key, None)
        self._released.notify()

    def in_use_count(self, host: str | None = None,
                     port: int | None = None) -> int:
        """Borrowed (not yet released) connections — one endpoint when
        given, pool-wide otherwise."""
        with self._lock:
            if host is not None:
                return self._in_use.get((host, port), 0)
            return sum(self._in_use.values())

    # -- dead-peer backoff -------------------------------------------------

    def mark_dead(self, host: str, port: int) -> None:
        """Start (or extend) the cooldown for one endpoint after a
        transport failure.  No-op when the cooldown is disabled (<= 0)."""
        if self.dead_peer_cooldown <= 0:
            return
        with self._lock:
            self._dead[(host, port)] = (time.monotonic()
                                        + self.dead_peer_cooldown)

    def is_dead(self, host: str, port: int) -> bool:
        """True while the endpoint is inside its failure cooldown.
        Expired marks are dropped on read, so a peer that stays quiet
        past the cooldown costs nothing."""
        with self._lock:
            deadline = self._dead.get((host, port))
            if deadline is None:
                return False
            if time.monotonic() >= deadline:
                del self._dead[(host, port)]
                return False
            return True

    def release(self, conn: Connection) -> None:
        conn.trace_ctx = None  # a parked conn must not carry a stale trace
        conn.priority = None   # ...nor a stale QoS class
        key = (conn.host, conn.port)
        if conn.broken:
            conn.close()
            with self._lock:
                self._dec_in_use(key)
            self._maybe_sweep()
            return
        to_close = []
        with self._lock:
            self._dec_in_use(key)
            q = self._idle.setdefault(key, deque())
            if any(c is conn for c, _ in q):
                # Double release: parking the same connection twice
                # would hand one socket to two future borrowers.  The
                # deque is bounded (max_idle_per_endpoint), so the scan
                # is O(8).
                return
            if len(q) >= self.max_idle_per_endpoint:
                to_close.append(q.popleft()[0])
            q.append((conn, time.monotonic()))
            # Pool-wide idle cap: evict the globally oldest parked conn
            # so one hot endpoint cannot strand dozens of sockets on
            # endpoints that went quiet.
            while (self.max_idle_total > 0 and
                   sum(len(d) for d in self._idle.values()) >
                   self.max_idle_total):
                oldest_key = min(
                    (k for k, d in self._idle.items() if d),
                    key=lambda k: self._idle[k][0][1])
                to_close.append(self._idle[oldest_key].popleft()[0])
                if not self._idle[oldest_key]:
                    del self._idle[oldest_key]
        for old in to_close:
            old.close()
        self._maybe_sweep()

    # -- hygiene (ISSUE 18) ------------------------------------------------

    def _maybe_sweep(self) -> None:
        with self._lock:
            due = (time.monotonic() - self._last_sweep
                   >= self.sweep_interval)
        if due:
            self.sweep()

    def sweep(self, now: float | None = None) -> None:
        """Close idle connections past their TTL and drop expired
        ``_dead`` marks — including for endpoints that left the cluster
        and will never be acquired again (the leak this fixes: TTLs
        were previously only checked at acquire time)."""
        if now is None:
            now = time.monotonic()
        to_close = []
        with self._lock:
            self._last_sweep = now
            for key in list(self._idle):
                q = self._idle[key]
                while q and now - q[0][1] > self.max_idle_seconds:
                    to_close.append(q.popleft()[0])
                if not q:
                    del self._idle[key]
            for key in list(self._dead):
                if now >= self._dead[key]:
                    del self._dead[key]
            self.swept_idle += len(to_close)
        for conn in to_close:
            conn.close()

    def purge(self, host: str, port: int) -> None:
        """Drop every idle connection to one endpoint (called after an
        operation on a pooled connection fails: a silently-dead peer
        passes the borrow-time check, so its siblings are suspect)."""
        with self._lock:
            q = self._idle.pop((host, port), None)
        for conn, _ in (q or ()):
            conn.close()

    def close_all(self) -> None:
        with self._lock:
            queues = list(self._idle.values())
            self._idle.clear()
        for q in queues:
            for conn, _ in q:
                conn.close()

    def idle_count(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._idle.values())

    def dead_mark_count(self) -> int:
        """Endpoints currently carrying a dead-peer cooldown mark
        (expired marks linger until a read or a sweep drops them)."""
        with self._lock:
            return len(self._dead)


def _quiet(conn: Connection) -> bool:
    """True when the idle socket shows no pending data/EOF (reusable)."""
    try:
        readable, _, _ = select.select([conn.sock], [], [], 0)
        return not readable
    except (OSError, ValueError):
        return False
