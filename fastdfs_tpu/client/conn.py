"""Low-level framed connection (reference: libfastcommon sockopt.c
tcprecvdata_nb/tcpsenddata_nb + fdfs_proto.c fdfs_recv_response)."""

from __future__ import annotations

import socket

from fastdfs_tpu.common.protocol import HEADER_SIZE, Header, pack_header, unpack_header


class ProtocolError(Exception):
    pass


class StatusError(ProtocolError):
    """Non-zero status byte in a response header."""

    def __init__(self, status: int, context: str = ""):
        self.status = status
        super().__init__(f"server returned status {status}"
                         + (f" ({context})" if context else ""))


class Connection:
    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host, self.port = host, port
        self.timeout = timeout
        self.sock = self._connect()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- framing -----------------------------------------------------------

    def send_request(self, cmd: int, body: bytes = b"",
                     body_len: int | None = None) -> None:
        # The server closes a connection after an error response that left
        # request bytes unread (it cannot resync mid-stream).  A request
        # boundary is the one safe place to reconnect, so retry once — the
        # same recovery the reference's connection pool performs.
        hdr = pack_header(len(body) if body_len is None else body_len, cmd)
        try:
            self.sock.sendall(hdr + body)
        except OSError:
            self.close()
            self.sock = self._connect()
            self.sock.sendall(hdr + body)

    def send_raw(self, data: bytes) -> None:
        self.sock.sendall(data)

    def recv_exact(self, n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            chunk = self.sock.recv(min(n - got, 256 * 1024))
            if not chunk:
                raise ProtocolError("connection closed mid-message")
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def recv_header(self) -> Header:
        return unpack_header(self.recv_exact(HEADER_SIZE))

    def recv_response(self, context: str = "") -> bytes:
        """Header + body; raises StatusError on non-zero status."""
        hdr = self.recv_header()
        body = self.recv_exact(hdr.pkg_len) if hdr.pkg_len else b""
        if hdr.status != 0:
            raise StatusError(hdr.status, context)
        return body
