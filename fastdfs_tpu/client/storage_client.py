"""Storage-daemon client: the data-path API.

Reference: ``client/storage_client.c`` — storage_do_upload_file(),
storage_download_file_ex(), storage_delete_file(), metadata get/set,
fdfs_get_file_info().  Wire layouts match the C++ daemon in
``native/storage/server.cc`` (FastDFS-shaped, not byte-compatible with
upstream — see SURVEY.md provenance warning).
"""

from __future__ import annotations

import io
import os
from dataclasses import dataclass

from fastdfs_tpu.client.conn import Connection, ProtocolError
from fastdfs_tpu.common.protocol import (
    GROUP_NAME_MAX_LEN,
    StorageCmd,
    long2buff,
    buff2long,
    pack_ext_name,
    pack_group_name,
    pack_metadata,
    unpack_group_name,
    unpack_metadata,
)

AUTO_STORE_PATH = 0xFF


@dataclass(frozen=True)
class RemoteFileInfo:
    file_size: int
    create_timestamp: int
    crc32: int
    source_ip: str


class StorageClient:
    """One storage server connection (context manager)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.conn = Connection(host, port, timeout)

    def close(self) -> None:
        self.conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- uploads -----------------------------------------------------------

    def upload_buffer(self, data: bytes, ext: str = "",
                      store_path_index: int = AUTO_STORE_PATH,
                      appender: bool = False) -> str:
        """Upload bytes; returns the file ID (``group/Mxx/aa/bb/name.ext``).

        Wire (reference storage_do_upload_file): 1B store-path index
        (0xFF = server picks), 8B file size, 6B ext, then the body.
        """
        cmd = (StorageCmd.UPLOAD_APPENDER_FILE if appender
               else StorageCmd.UPLOAD_FILE)
        fixed = bytes([store_path_index]) + long2buff(len(data)) + pack_ext_name(ext)
        self.conn.send_request(cmd, fixed + data)
        body = self.conn.recv_response("upload")
        if len(body) <= GROUP_NAME_MAX_LEN:
            raise ProtocolError(f"short upload response: {len(body)}")
        group = unpack_group_name(body[:GROUP_NAME_MAX_LEN])
        remote = body[GROUP_NAME_MAX_LEN:].decode()
        return f"{group}/{remote}"

    def upload_file(self, path: str, ext: str | None = None, **kw) -> str:
        if ext is None:
            ext = os.path.splitext(path)[1].lstrip(".")[:6]
        with open(path, "rb") as fh:
            return self.upload_buffer(fh.read(), ext=ext, **kw)

    # -- downloads ---------------------------------------------------------

    def download_to_buffer(self, file_id: str, offset: int = 0,
                           length: int = 0) -> bytes:
        """Download (part of) a file.  length 0 = to EOF."""
        group, remote = _split_id(file_id)
        body = (long2buff(offset) + long2buff(length)
                + pack_group_name(group) + remote.encode())
        self.conn.send_request(StorageCmd.DOWNLOAD_FILE, body)
        return self.conn.recv_response("download")

    def download_to_file(self, file_id: str, local_path: str,
                         offset: int = 0, length: int = 0) -> int:
        data = self.download_to_buffer(file_id, offset, length)
        with open(local_path, "wb") as fh:
            fh.write(data)
        return len(data)

    # -- delete / info -----------------------------------------------------

    def delete_file(self, file_id: str) -> None:
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.DELETE_FILE,
                               pack_group_name(group) + remote.encode())
        self.conn.recv_response("delete")

    def query_file_info(self, file_id: str) -> RemoteFileInfo:
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.QUERY_FILE_INFO,
                               pack_group_name(group) + remote.encode())
        body = self.conn.recv_response("query_file_info")
        if len(body) < 40:
            raise ProtocolError(f"short query response: {len(body)}")
        return RemoteFileInfo(
            file_size=buff2long(body, 0),
            create_timestamp=buff2long(body, 8),
            crc32=buff2long(body, 16) & 0xFFFFFFFF,
            source_ip=body[24:40].rstrip(b"\x00").decode(),
        )

    # -- metadata ----------------------------------------------------------

    def set_metadata(self, file_id: str, meta: dict[str, str],
                     merge: bool = False) -> None:
        group, remote = _split_id(file_id)
        flag = b"M" if merge else b"O"
        name = remote.encode()
        body = (pack_group_name(group) + flag + long2buff(len(name)) + name
                + pack_metadata(meta))
        self.conn.send_request(StorageCmd.SET_METADATA, body)
        self.conn.recv_response("set_metadata")

    def get_metadata(self, file_id: str) -> dict[str, str]:
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.GET_METADATA,
                               pack_group_name(group) + remote.encode())
        return unpack_metadata(self.conn.recv_response("get_metadata"))

    # -- misc --------------------------------------------------------------

    def active_test(self) -> bool:
        self.conn.send_request(StorageCmd.ACTIVE_TEST)
        self.conn.recv_response("active_test")
        return True


def _split_id(file_id: str) -> tuple[str, str]:
    group, sep, remote = file_id.partition("/")
    if not sep or not remote:
        raise ValueError(f"malformed file id: {file_id!r}")
    return group, remote
