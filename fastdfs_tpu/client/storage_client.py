"""Storage-daemon client: the data-path API.

Reference: ``client/storage_client.c`` — storage_do_upload_file(),
storage_download_file_ex(), storage_delete_file(), metadata get/set,
fdfs_get_file_info().  Wire layouts match the C++ daemon in
``native/storage/server.cc`` (FastDFS-shaped, not byte-compatible with
upstream — see SURVEY.md provenance warning).
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass

from fastdfs_tpu.client.conn import Connection, ProtocolError, StatusError
from fastdfs_tpu.common.protocol import (
    GROUP_NAME_MAX_LEN,
    MAX_INLINE_BODY,
    StorageCmd,
    long2buff,
    buff2long,
    pack_ext_name,
    pack_group_name,
    pack_metadata,
    pack_prefix_name,
    pack_profile_ctl,
    unpack_group_name,
    unpack_metadata,
    unpack_ec_stats,
    unpack_scrub_stats,
)

AUTO_STORE_PATH = 0xFF


def _parse_upload_response(body: bytes) -> str:
    """Decode the shared upload response shape (16B group + remote name)
    into a file ID — one definition for every upload variant."""
    if len(body) <= GROUP_NAME_MAX_LEN:
        raise ProtocolError(f"short upload response: {len(body)}")
    group = unpack_group_name(body[:GROUP_NAME_MAX_LEN])
    return f"{group}/{body[GROUP_NAME_MAX_LEN:].decode()}"

# Segment size for streamed request bodies (uploads read the source in
# pieces this big, so a multi-GB file holds O(segment) client memory).
UPLOAD_SEGMENT_BYTES = 1 << 20

# Statuses that mean "this daemon cannot serve a negotiated upload" (95 =
# ENOTSUP: no chunk store; 22 = EINVAL: an OLDER daemon rejecting the
# unknown opcode) — the client falls back to a plain UPLOAD_FILE.
_DEDUP_FALLBACK_STATUSES = (22, 95)


def pack_upload_recipe(store_path_index: int, ext: str, crc32: int,
                       logical_size: int,
                       chunks: list[tuple[int, bytes]]) -> bytes:
    """UPLOAD_RECIPE request body (phase 1 of the negotiated upload).

    ``chunks`` is [(length, 20B raw sha1)] in stream order.  Wire: 1B
    store-path index + 6B ext + 8B crc32 + 8B logical_size + 8B count +
    per chunk (20B digest + 8B length) — the recipe entry encoding every
    chunk-aware opcode shares.  Covered by the ``fdfs_codec ingest-wire``
    cross-language golden.
    """
    parts = [bytes([store_path_index]), pack_ext_name(ext),
             long2buff(crc32 & 0xFFFFFFFF), long2buff(logical_size),
             long2buff(len(chunks))]
    for length, digest in chunks:
        if len(digest) != 20:
            raise ValueError(f"digest must be 20 raw bytes, got {len(digest)}")
        parts.append(digest)
        parts.append(long2buff(length))
    return b"".join(parts)


def unpack_upload_recipe_resp(body: bytes, n_chunks: int) -> tuple[int, bytes]:
    """(session_id, needed-bitmap) from an UPLOAD_RECIPE response; byte i
    of the bitmap is 1 when chunk i must be shipped in phase 2."""
    if len(body) != 8 + n_chunks:
        raise ProtocolError(
            f"bad UPLOAD_RECIPE response: {len(body)} != {8 + n_chunks}")
    return buff2long(body), body[8:]


def pack_upload_chunks_prefix(session_id: int, payload_len: int) -> bytes:
    """UPLOAD_CHUNKS fixed prefix (phase 2): 8B session + 8B payload_len;
    the needed chunks' payloads follow in recipe order."""
    return long2buff(session_id) + long2buff(payload_len)


@dataclass(frozen=True)
class RemoteFileInfo:
    file_size: int
    create_timestamp: int
    crc32: int
    source_ip: str


class StorageClient:
    """One storage server connection (context manager)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 conn: Connection | None = None, release=None):
        # `conn`/`release` inject a pooled connection (ConnectionPool):
        # close() then parks it instead of closing the socket.
        self.conn = conn if conn is not None else Connection(host, port, timeout)
        self._release = release

    def close(self) -> None:
        conn, self.conn = self.conn, None
        if conn is None:
            return  # idempotent: the pool may already own the socket
        if self._release is not None:
            release, self._release = self._release, None
            release(conn)
        else:
            conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- uploads -----------------------------------------------------------

    def upload_buffer(self, data: bytes, ext: str = "",
                      store_path_index: int = AUTO_STORE_PATH,
                      appender: bool = False) -> str:
        """Upload bytes; returns the file ID (``group/Mxx/aa/bb/name.ext``).

        Wire (reference storage_do_upload_file): 1B store-path index
        (0xFF = server picks), 8B file size, 6B ext, then the body.
        """
        cmd = (StorageCmd.UPLOAD_APPENDER_FILE if appender
               else StorageCmd.UPLOAD_FILE)
        fixed = bytes([store_path_index]) + long2buff(len(data)) + pack_ext_name(ext)
        self.conn.send_request(cmd, fixed + data)
        return _parse_upload_response(self.conn.recv_response("upload"))

    def upload_stream(self, fh, size: int, ext: str = "",
                      store_path_index: int = AUTO_STORE_PATH,
                      appender: bool = False,
                      segment: int = UPLOAD_SEGMENT_BYTES) -> str:
        """Upload ``size`` bytes read from file object ``fh`` in bounded
        segments — a multi-GB upload holds O(segment) client memory, not
        O(file) (the body streams through ``conn.send_request``'s
        iterable-body path)."""
        cmd = (StorageCmd.UPLOAD_APPENDER_FILE if appender
               else StorageCmd.UPLOAD_FILE)
        fixed = bytes([store_path_index]) + long2buff(size) + pack_ext_name(ext)

        def gen():
            yield fixed
            remaining = size
            while remaining > 0:
                seg = fh.read(min(segment, remaining))
                if not seg:
                    # Short source: the declared pkg_len cannot be
                    # amended mid-stream; send_request flags the
                    # connection broken and raises.
                    return
                remaining -= len(seg)
                yield seg

        self.conn.send_request(cmd, gen(), body_len=len(fixed) + size)
        return _parse_upload_response(self.conn.recv_response("upload"))

    def upload_file(self, path: str, ext: str | None = None, **kw) -> str:
        if ext is None:
            ext = os.path.splitext(path)[1].lstrip(".")[:6]
        size = os.path.getsize(path)
        with open(path, "rb") as fh:
            return self.upload_stream(fh, size, ext=ext, **kw)

    # -- dedup-aware negotiated upload (UPLOAD_RECIPE / UPLOAD_CHUNKS) ----

    def upload_buffer_dedup(self, data: bytes, ext: str = "",
                            store_path_index: int = AUTO_STORE_PATH,
                            chunks: list[tuple[int, bytes]] | None = None,
                            stats: dict | None = None,
                            segment: int = UPLOAD_SEGMENT_BYTES) -> str:
        """Upload via the negotiated two-round-trip protocol: fingerprint
        locally, ask the daemon which chunks it lacks, ship only those.

        ``chunks`` short-circuits fingerprinting when the caller already
        has [(length, 20B raw sha1)] (FdfsClient computes it once for its
        dup-ratio estimate).  Falls back to a plain ``upload_buffer``
        transparently when the daemon has no chunk store (ENOTSUP), is
        too old to know the opcode (EINVAL), or the session fails
        mid-flight — same file ID semantics either way.  ``stats`` (if
        given) is updated with chunks_total / chunks_missing /
        bytes_sent / fallback for accounting and tests.
        """
        if chunks is None:
            from fastdfs_tpu.client.fingerprint import fingerprint_buffer
            chunks = [(fp.length, fp.digest)
                      for fp in fingerprint_buffer(data)]
        if stats is None:
            stats = {}
        stats.update(chunks_total=len(chunks), chunks_missing=len(chunks),
                     bytes_sent=len(data), fallback="")
        if not chunks:  # empty payload: nothing to negotiate over
            stats["fallback"] = "empty"
            return self.upload_buffer(data, ext=ext,
                                      store_path_index=store_path_index)
        body = pack_upload_recipe(store_path_index, ext, zlib.crc32(data),
                                  len(data), chunks)
        if len(body) > MAX_INLINE_BODY:
            # The daemon refuses (connection close, no status) inline
            # bodies over the wire cap; a ~19 GB payload at the default
            # chunk size gets there.  Gate locally and fall back.
            stats["fallback"] = "recipe_too_large"
            return self.upload_buffer(data, ext=ext,
                                      store_path_index=store_path_index)
        try:
            self.conn.send_request(StorageCmd.UPLOAD_RECIPE, body)
            resp = self.conn.recv_response("upload_recipe")
        except StatusError as e:
            if e.status in _DEDUP_FALLBACK_STATUSES:
                stats["fallback"] = f"status{e.status}"
                return self.upload_buffer(data, ext=ext,
                                          store_path_index=store_path_index)
            raise
        session, needed = unpack_upload_recipe_resp(resp, len(chunks))

        spans: list[tuple[int, int]] = []  # (offset, length) to ship
        payload_len = 0
        offset = 0
        missing = 0
        for (length, _), need in zip(chunks, needed):
            if need:
                spans.append((offset, length))
                payload_len += length
                missing += 1
            offset += length

        def gen():
            yield pack_upload_chunks_prefix(session, payload_len)
            for off, length in spans:
                # Bounded segments even when one span is huge (max chunk
                # is 8 MB, but keep the discipline uniform).
                end = off + length
                while off < end:
                    yield data[off:min(off + segment, end)]
                    off = min(off + segment, end)

        try:
            self.conn.send_request(StorageCmd.UPLOAD_CHUNKS, gen(),
                                   body_len=16 + payload_len)
            body = self.conn.recv_response("upload_chunks")
        except StatusError as e:
            # Session expired / chunk vanished mid-commit: the daemon
            # rolled back; re-send the whole payload the classic way.
            # Honest wire accounting: the failed attempt's payload bytes
            # DID cross the wire on top of the plain re-send.
            stats.update(fallback=f"commit_status{e.status}",
                         chunks_missing=missing,
                         bytes_sent=payload_len + len(data))
            return self.upload_buffer(data, ext=ext,
                                      store_path_index=store_path_index)
        stats.update(chunks_missing=missing, bytes_sent=payload_len)
        return _parse_upload_response(body)

    def upload_slave_buffer(self, master_id: str, prefix: str, data: bytes,
                            ext: str = "") -> str:
        """Upload a derived file addressed by the master's ID + a prefix
        (reference storage_upload_slave_file, cmd 21): the slave lands at
        ``<master stem><prefix>.<ext>`` so clients can reconstruct its ID
        from the master ID alone.

        Wire: 16B group + 8B master_len + 8B size + 16B prefix + 6B ext +
        master_name + body.
        """
        group, remote = _split_id(master_id)
        name = remote.encode()
        body = (pack_group_name(group) + long2buff(len(name))
                + long2buff(len(data)) + pack_prefix_name(prefix)
                + pack_ext_name(ext) + name + data)
        self.conn.send_request(StorageCmd.UPLOAD_SLAVE_FILE, body)
        return _parse_upload_response(self.conn.recv_response("upload_slave"))

    # -- appender-file mutations -------------------------------------------

    def append_buffer(self, file_id: str, data: bytes) -> None:
        """Append bytes to an appender file (cmd APPEND_FILE).

        Wire: 16B group + 8B name_len + 8B length + name + body.
        """
        group, remote = _split_id(file_id)
        name = remote.encode()
        body = (pack_group_name(group) + long2buff(len(name))
                + long2buff(len(data)) + name + data)
        self.conn.send_request(StorageCmd.APPEND_FILE, body)
        self.conn.recv_response("append")

    def modify_buffer(self, file_id: str, offset: int, data: bytes) -> None:
        """Overwrite bytes at ``offset`` inside an appender file (MODIFY_FILE).

        Wire: 16B group + 8B name_len + 8B offset + 8B length + name + body.
        """
        group, remote = _split_id(file_id)
        name = remote.encode()
        body = (pack_group_name(group) + long2buff(len(name))
                + long2buff(offset) + long2buff(len(data)) + name + data)
        self.conn.send_request(StorageCmd.MODIFY_FILE, body)
        self.conn.recv_response("modify")

    def truncate_file(self, file_id: str, new_size: int = 0) -> None:
        """Truncate an appender file to ``new_size`` (TRUNCATE_FILE).

        Wire: 16B group + 8B name_len + 8B new_size + name.
        """
        group, remote = _split_id(file_id)
        name = remote.encode()
        body = (pack_group_name(group) + long2buff(len(name))
                + long2buff(new_size) + name)
        self.conn.send_request(StorageCmd.TRUNCATE_FILE, body)
        self.conn.recv_response("truncate")

    # -- downloads ---------------------------------------------------------

    def _send_download(self, file_id: str, offset: int, length: int) -> None:
        group, remote = _split_id(file_id)
        body = (long2buff(offset) + long2buff(length)
                + pack_group_name(group) + remote.encode())
        self.conn.send_request(StorageCmd.DOWNLOAD_FILE, body)

    def download_to_buffer(self, file_id: str, offset: int = 0,
                           length: int = 0) -> bytes:
        """Download (part of) a file.  length 0 = to EOF."""
        self._send_download(file_id, offset, length)
        return self.conn.recv_response("download")

    def download_stream(self, file_id: str, fh, offset: int = 0,
                        length: int = 0,
                        segment: int = UPLOAD_SEGMENT_BYTES) -> int:
        """Download (part of) a file into file object ``fh`` in bounded
        recv_into segments — O(segment) client memory however large the
        file (the download-side mirror of ``upload_stream``).  Returns
        the byte count written."""
        self._send_download(file_id, offset, length)
        return self.conn.recv_response_stream(fh, "download", segment)

    def download_into(self, file_id: str, mv, offset: int = 0) -> None:
        """Download EXACTLY len(mv) bytes at ``offset`` into a writable
        buffer (memoryview/bytearray) — the zero-copy worker primitive of
        the parallel ranged download (each worker lands its range
        directly in its slice of the shared output buffer)."""
        mv = memoryview(mv)
        self._send_download(file_id, offset, len(mv))
        self.conn.recv_response_into(mv, "download")

    def download_to_file(self, file_id: str, local_path: str,
                         offset: int = 0, length: int = 0) -> int:
        # Stream into a temp file and rename on success: a failed or
        # interrupted download must not truncate an existing local file
        # or leave a silently-partial one.
        tmp = f"{local_path}.part{os.getpid()}"
        try:
            with open(tmp, "wb") as fh:
                n = self.download_stream(file_id, fh, offset, length)
            os.replace(tmp, local_path)
            return n
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- delete / info -----------------------------------------------------

    def delete_file(self, file_id: str) -> None:
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.DELETE_FILE,
                               pack_group_name(group) + remote.encode())
        self.conn.recv_response("delete")

    def query_file_info(self, file_id: str) -> RemoteFileInfo:
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.QUERY_FILE_INFO,
                               pack_group_name(group) + remote.encode())
        body = self.conn.recv_response("query_file_info")
        if len(body) < 40:
            raise ProtocolError(f"short query response: {len(body)}")
        return RemoteFileInfo(
            file_size=buff2long(body, 0),
            create_timestamp=buff2long(body, 8),
            crc32=buff2long(body, 16) & 0xFFFFFFFF,
            source_ip=body[24:40].rstrip(b"\x00").decode(),
        )

    def near_dups(self, file_id: str) -> list[tuple[str, float]]:
        """Ranked near-duplicates of a stored file from the dedup
        engine's MinHash/LSH index (fastdfs_tpu extension, NEAR_DUPS=124).
        Returns [] when the file carries no signature (ENODATA);
        StatusError(95) when the dedup mode has no near index."""
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.NEAR_DUPS,
                               pack_group_name(group) + remote.encode())
        try:
            body = self.conn.recv_response("near_dups")
        except StatusError as e:
            if e.status == 61:  # ENODATA: indexed mode, unindexed file
                return []
            raise
        out: list[tuple[str, float]] = []
        for line in body.decode("utf-8", "replace").splitlines():
            parts = line.rsplit(" ", 1)
            if len(parts) == 2:
                try:
                    out.append((parts[0], float(parts[1])))
                except ValueError:
                    continue
        return out

    # -- metadata ----------------------------------------------------------

    def set_metadata(self, file_id: str, meta: dict[str, str],
                     merge: bool = False) -> None:
        group, remote = _split_id(file_id)
        flag = b"M" if merge else b"O"
        name = remote.encode()
        body = (pack_group_name(group) + flag + long2buff(len(name)) + name
                + pack_metadata(meta))
        self.conn.send_request(StorageCmd.SET_METADATA, body)
        self.conn.recv_response("set_metadata")

    def get_metadata(self, file_id: str) -> dict[str, str]:
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.GET_METADATA,
                               pack_group_name(group) + remote.encode())
        return unpack_metadata(self.conn.recv_response("get_metadata"))

    # -- misc --------------------------------------------------------------

    def active_test(self) -> bool:
        self.conn.send_request(StorageCmd.ACTIVE_TEST)
        self.conn.recv_response("active_test")
        return True

    def stat(self) -> dict:
        """Stats-registry snapshot (STAT 130): per-opcode counters and
        latency histograms, dedup/replication/recovery accounting.  Shape
        per fastdfs_tpu.monitor.decode_registry."""
        self.conn.send_request(StorageCmd.STAT)
        return json.loads(self.conn.recv_response("stat") or b"{}")

    def trace_dump(self) -> dict:
        """Span ring-buffer dump (TRACE_DUMP 131): this daemon's retained
        request/replication/recovery spans.  Shape per
        fastdfs_tpu.trace.decode_dump."""
        self.conn.send_request(StorageCmd.TRACE_DUMP)
        return json.loads(self.conn.recv_response("trace_dump") or b"{}")

    def event_dump(self) -> dict:
        """Flight-recorder dump (EVENT_DUMP 137): this daemon's retained
        structured cluster events (quarantines, GC sweeps, session
        expiries, stalls, slow requests).  Shape per
        fastdfs_tpu.monitor.decode_events."""
        self.conn.send_request(StorageCmd.EVENT_DUMP)
        return json.loads(self.conn.recv_response("event_dump") or b"{}")

    def metrics_history(self, since_us: int = 0) -> dict:
        """Metrics-journal window dump (METRICS_HISTORY 138): every
        retained registry snapshot with ts_us >= ``since_us`` (0 = the
        whole ring — including snapshots from BEFORE the daemon's last
        restart, which is the point).  Shape per
        fastdfs_tpu.monitor.decode_metrics_history; StatusError(95)
        when journaling is off (metrics_journal_mb = 0)."""
        body = long2buff(since_us) if since_us else b""
        self.conn.send_request(StorageCmd.METRICS_HISTORY, body)
        return json.loads(self.conn.recv_response("metrics_history") or b"{}")

    def heat_top(self, k: int = 0) -> dict:
        """Hot-file top-K dump (HEAT_TOP 139): the daemon's
        space-saving sketch ranked by request count, with per-op
        request/byte splits.  k=0 uses the daemon's heat_top_k.  Shape
        per fastdfs_tpu.monitor.decode_heat; StatusError(95) when the
        sketch is off (heat_top_k = 0)."""
        body = long2buff(k) if k else b""
        self.conn.send_request(StorageCmd.HEAT_TOP, body)
        return json.loads(self.conn.recv_response("heat_top") or b"{}")

    def health_status(self) -> dict:
        """Gray-failure health view (HEALTH_STATUS 146): this daemon's
        own gray score (watchdog stalls + disk-path probes) and its
        per-(peer, op class) RPC health table.  Shape per
        fastdfs_tpu.monitor.decode_health_status."""
        self.conn.send_request(StorageCmd.HEALTH_STATUS)
        return json.loads(self.conn.recv_response("health_status") or b"{}")

    def admission_status(self) -> dict:
        """Admission-ladder status (ADMISSION_STATUS 148): current shed
        level, pressure EWMA, per-class shed counts.  Shape per
        fastdfs_tpu.monitor.decode_admission."""
        self.conn.send_request(StorageCmd.ADMISSION_STATUS)
        return json.loads(self.conn.recv_response("admission_status")
                          or b"{}")

    def scrub_status(self) -> dict[str, int]:
        """Integrity-engine status (SCRUB_STATUS 134): named scrub/GC
        counters decoded from the fixed int64 blob (SCRUB_STAT_FIELDS).
        StatusError(95) when the daemon has no chunk store to scrub."""
        self.conn.send_request(StorageCmd.SCRUB_STATUS)
        return unpack_scrub_stats(self.conn.recv_response("scrub_status"))

    def scrub_kick(self) -> None:
        """Force a verify+repair+GC pass now (SCRUB_KICK 135) — works
        even when periodic scrubbing (scrub_interval_s) is off."""
        self.conn.send_request(StorageCmd.SCRUB_KICK)
        self.conn.recv_response("scrub_kick")

    def ec_status(self) -> dict[str, int]:
        """Erasure-coding cold-tier status (EC_STATUS 143): named stripe/
        demotion/reconstruction counters decoded from the fixed int64
        blob (EC_STAT_FIELDS).  StatusError(95) when EC is off
        (ec_k = 0) AND no stripes survive on disk — a drained daemon
        still answers so operators can watch the drain finish."""
        self.conn.send_request(StorageCmd.EC_STATUS)
        return unpack_ec_stats(self.conn.recv_response("ec_status"))

    def ec_kick(self) -> None:
        """Force an EC demotion pass now (EC_KICK 144): the next scrub
        pass treats ec_demote_age_s as 0 so every demotable cold chunk
        stripes immediately — then kick the scrubber itself.
        StatusError(95) when EC is off (ec_k = 0)."""
        self.conn.send_request(StorageCmd.EC_KICK)
        self.conn.recv_response("ec_kick")

    def profile_start(self, hz: int = 97, duration_s: int = 30) -> dict:
        """Arm the in-daemon sampling profiler (PROFILE_CTL 141) for
        ``duration_s`` seconds at ``hz`` samples/s (clamped to the
        daemon's profile_max_hz).  The daemon auto-disarms at the
        deadline, so a dropped connection cannot leave the timer armed.
        Returns the ack {"active": true, "hz": <armed hz>};
        StatusError(95) when profiling is off (profile_max_hz = 0)."""
        self.conn.send_request(StorageCmd.PROFILE_CTL,
                               pack_profile_ctl(True, hz, duration_s))
        return json.loads(self.conn.recv_response("profile_start") or b"{}")

    def profile_stop(self) -> dict:
        """Disarm the profiler early (PROFILE_CTL 141, action 0); the
        captured samples stay available to profile_dump.  Idempotent."""
        self.conn.send_request(StorageCmd.PROFILE_CTL,
                               pack_profile_ctl(False))
        return json.loads(self.conn.recv_response("profile_stop") or b"{}")

    def profile_dump(self) -> dict:
        """Folded-stack dump of the last capture (PROFILE_DUMP 142).
        Shape per fastdfs_tpu.monitor.decode_profile; StatusError(95)
        while no capture was ever started this daemon lifetime."""
        self.conn.send_request(StorageCmd.PROFILE_DUMP)
        return json.loads(self.conn.recv_response("profile_dump") or b"{}")


def _split_id(file_id: str) -> tuple[str, str]:
    group, sep, remote = file_id.partition("/")
    if not sep or not remote:
        raise ValueError(f"malformed file id: {file_id!r}")
    return group, remote
