"""Storage-daemon client: the data-path API.

Reference: ``client/storage_client.c`` — storage_do_upload_file(),
storage_download_file_ex(), storage_delete_file(), metadata get/set,
fdfs_get_file_info().  Wire layouts match the C++ daemon in
``native/storage/server.cc`` (FastDFS-shaped, not byte-compatible with
upstream — see SURVEY.md provenance warning).
"""

from __future__ import annotations

import io
import json
import os
from dataclasses import dataclass

from fastdfs_tpu.client.conn import Connection, ProtocolError, StatusError
from fastdfs_tpu.common.protocol import (
    GROUP_NAME_MAX_LEN,
    StorageCmd,
    long2buff,
    buff2long,
    pack_ext_name,
    pack_group_name,
    pack_metadata,
    pack_prefix_name,
    unpack_group_name,
    unpack_metadata,
)

AUTO_STORE_PATH = 0xFF


@dataclass(frozen=True)
class RemoteFileInfo:
    file_size: int
    create_timestamp: int
    crc32: int
    source_ip: str


class StorageClient:
    """One storage server connection (context manager)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 conn: Connection | None = None, release=None):
        # `conn`/`release` inject a pooled connection (ConnectionPool):
        # close() then parks it instead of closing the socket.
        self.conn = conn if conn is not None else Connection(host, port, timeout)
        self._release = release

    def close(self) -> None:
        conn, self.conn = self.conn, None
        if conn is None:
            return  # idempotent: the pool may already own the socket
        if self._release is not None:
            release, self._release = self._release, None
            release(conn)
        else:
            conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- uploads -----------------------------------------------------------

    def upload_buffer(self, data: bytes, ext: str = "",
                      store_path_index: int = AUTO_STORE_PATH,
                      appender: bool = False) -> str:
        """Upload bytes; returns the file ID (``group/Mxx/aa/bb/name.ext``).

        Wire (reference storage_do_upload_file): 1B store-path index
        (0xFF = server picks), 8B file size, 6B ext, then the body.
        """
        cmd = (StorageCmd.UPLOAD_APPENDER_FILE if appender
               else StorageCmd.UPLOAD_FILE)
        fixed = bytes([store_path_index]) + long2buff(len(data)) + pack_ext_name(ext)
        self.conn.send_request(cmd, fixed + data)
        body = self.conn.recv_response("upload")
        if len(body) <= GROUP_NAME_MAX_LEN:
            raise ProtocolError(f"short upload response: {len(body)}")
        group = unpack_group_name(body[:GROUP_NAME_MAX_LEN])
        remote = body[GROUP_NAME_MAX_LEN:].decode()
        return f"{group}/{remote}"

    def upload_file(self, path: str, ext: str | None = None, **kw) -> str:
        if ext is None:
            ext = os.path.splitext(path)[1].lstrip(".")[:6]
        with open(path, "rb") as fh:
            return self.upload_buffer(fh.read(), ext=ext, **kw)

    def upload_slave_buffer(self, master_id: str, prefix: str, data: bytes,
                            ext: str = "") -> str:
        """Upload a derived file addressed by the master's ID + a prefix
        (reference storage_upload_slave_file, cmd 21): the slave lands at
        ``<master stem><prefix>.<ext>`` so clients can reconstruct its ID
        from the master ID alone.

        Wire: 16B group + 8B master_len + 8B size + 16B prefix + 6B ext +
        master_name + body.
        """
        group, remote = _split_id(master_id)
        name = remote.encode()
        body = (pack_group_name(group) + long2buff(len(name))
                + long2buff(len(data)) + pack_prefix_name(prefix)
                + pack_ext_name(ext) + name + data)
        self.conn.send_request(StorageCmd.UPLOAD_SLAVE_FILE, body)
        resp = self.conn.recv_response("upload_slave")
        if len(resp) <= GROUP_NAME_MAX_LEN:
            raise ProtocolError(f"short upload response: {len(resp)}")
        return (f"{unpack_group_name(resp[:GROUP_NAME_MAX_LEN])}/"
                f"{resp[GROUP_NAME_MAX_LEN:].decode()}")

    # -- appender-file mutations -------------------------------------------

    def append_buffer(self, file_id: str, data: bytes) -> None:
        """Append bytes to an appender file (cmd APPEND_FILE).

        Wire: 16B group + 8B name_len + 8B length + name + body.
        """
        group, remote = _split_id(file_id)
        name = remote.encode()
        body = (pack_group_name(group) + long2buff(len(name))
                + long2buff(len(data)) + name + data)
        self.conn.send_request(StorageCmd.APPEND_FILE, body)
        self.conn.recv_response("append")

    def modify_buffer(self, file_id: str, offset: int, data: bytes) -> None:
        """Overwrite bytes at ``offset`` inside an appender file (MODIFY_FILE).

        Wire: 16B group + 8B name_len + 8B offset + 8B length + name + body.
        """
        group, remote = _split_id(file_id)
        name = remote.encode()
        body = (pack_group_name(group) + long2buff(len(name))
                + long2buff(offset) + long2buff(len(data)) + name + data)
        self.conn.send_request(StorageCmd.MODIFY_FILE, body)
        self.conn.recv_response("modify")

    def truncate_file(self, file_id: str, new_size: int = 0) -> None:
        """Truncate an appender file to ``new_size`` (TRUNCATE_FILE).

        Wire: 16B group + 8B name_len + 8B new_size + name.
        """
        group, remote = _split_id(file_id)
        name = remote.encode()
        body = (pack_group_name(group) + long2buff(len(name))
                + long2buff(new_size) + name)
        self.conn.send_request(StorageCmd.TRUNCATE_FILE, body)
        self.conn.recv_response("truncate")

    # -- downloads ---------------------------------------------------------

    def download_to_buffer(self, file_id: str, offset: int = 0,
                           length: int = 0) -> bytes:
        """Download (part of) a file.  length 0 = to EOF."""
        group, remote = _split_id(file_id)
        body = (long2buff(offset) + long2buff(length)
                + pack_group_name(group) + remote.encode())
        self.conn.send_request(StorageCmd.DOWNLOAD_FILE, body)
        return self.conn.recv_response("download")

    def download_to_file(self, file_id: str, local_path: str,
                         offset: int = 0, length: int = 0) -> int:
        data = self.download_to_buffer(file_id, offset, length)
        with open(local_path, "wb") as fh:
            fh.write(data)
        return len(data)

    # -- delete / info -----------------------------------------------------

    def delete_file(self, file_id: str) -> None:
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.DELETE_FILE,
                               pack_group_name(group) + remote.encode())
        self.conn.recv_response("delete")

    def query_file_info(self, file_id: str) -> RemoteFileInfo:
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.QUERY_FILE_INFO,
                               pack_group_name(group) + remote.encode())
        body = self.conn.recv_response("query_file_info")
        if len(body) < 40:
            raise ProtocolError(f"short query response: {len(body)}")
        return RemoteFileInfo(
            file_size=buff2long(body, 0),
            create_timestamp=buff2long(body, 8),
            crc32=buff2long(body, 16) & 0xFFFFFFFF,
            source_ip=body[24:40].rstrip(b"\x00").decode(),
        )

    def near_dups(self, file_id: str) -> list[tuple[str, float]]:
        """Ranked near-duplicates of a stored file from the dedup
        engine's MinHash/LSH index (fastdfs_tpu extension, NEAR_DUPS=124).
        Returns [] when the file carries no signature (ENODATA);
        StatusError(95) when the dedup mode has no near index."""
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.NEAR_DUPS,
                               pack_group_name(group) + remote.encode())
        try:
            body = self.conn.recv_response("near_dups")
        except StatusError as e:
            if e.status == 61:  # ENODATA: indexed mode, unindexed file
                return []
            raise
        out: list[tuple[str, float]] = []
        for line in body.decode("utf-8", "replace").splitlines():
            parts = line.rsplit(" ", 1)
            if len(parts) == 2:
                try:
                    out.append((parts[0], float(parts[1])))
                except ValueError:
                    continue
        return out

    # -- metadata ----------------------------------------------------------

    def set_metadata(self, file_id: str, meta: dict[str, str],
                     merge: bool = False) -> None:
        group, remote = _split_id(file_id)
        flag = b"M" if merge else b"O"
        name = remote.encode()
        body = (pack_group_name(group) + flag + long2buff(len(name)) + name
                + pack_metadata(meta))
        self.conn.send_request(StorageCmd.SET_METADATA, body)
        self.conn.recv_response("set_metadata")

    def get_metadata(self, file_id: str) -> dict[str, str]:
        group, remote = _split_id(file_id)
        self.conn.send_request(StorageCmd.GET_METADATA,
                               pack_group_name(group) + remote.encode())
        return unpack_metadata(self.conn.recv_response("get_metadata"))

    # -- misc --------------------------------------------------------------

    def active_test(self) -> bool:
        self.conn.send_request(StorageCmd.ACTIVE_TEST)
        self.conn.recv_response("active_test")
        return True

    def stat(self) -> dict:
        """Stats-registry snapshot (STAT 130): per-opcode counters and
        latency histograms, dedup/replication/recovery accounting.  Shape
        per fastdfs_tpu.monitor.decode_registry."""
        self.conn.send_request(StorageCmd.STAT)
        return json.loads(self.conn.recv_response("stat") or b"{}")

    def trace_dump(self) -> dict:
        """Span ring-buffer dump (TRACE_DUMP 131): this daemon's retained
        request/replication/recovery spans.  Shape per
        fastdfs_tpu.trace.decode_dump."""
        self.conn.send_request(StorageCmd.TRACE_DUMP)
        return json.loads(self.conn.recv_response("trace_dump") or b"{}")


def _split_id(file_id: str) -> tuple[str, str]:
    group, sep, remote = file_id.partition("/")
    if not sep or not remote:
        raise ValueError(f"malformed file id: {file_id!r}")
    return group, remote
