"""High-level client: the two-hop tracker→storage dance.

Reference: ``client/fdfs_client.h`` + client_func.c — fdfs_client_init()
from client.conf (tracker_server list), then every operation queries a
tracker for a storage target and talks to it directly.
"""

from __future__ import annotations

import random

from fastdfs_tpu.client.conn import StatusError
from fastdfs_tpu.client.storage_client import RemoteFileInfo, StorageClient
from fastdfs_tpu.client.tracker_client import TrackerClient
from fastdfs_tpu.common.ini_config import IniConfig


class FdfsClient:
    """Tracker-routed client (reference: storage_upload_by_filename1 flow
    in SURVEY.md §3.1)."""

    def __init__(self, tracker_addrs: list[str] | str, timeout: float = 30.0):
        if isinstance(tracker_addrs, str):
            tracker_addrs = [tracker_addrs]
        if not tracker_addrs:
            raise ValueError("need at least one tracker address")
        self.trackers = [_parse_addr(a) for a in tracker_addrs]
        self.timeout = timeout

    @classmethod
    def from_conf(cls, conf_path: str) -> "FdfsClient":
        cfg = IniConfig.load(conf_path)
        addrs = cfg.get_all("tracker_server")
        return cls(addrs, timeout=float(cfg.get_seconds("network_timeout", 30)))

    def _tracker(self) -> TrackerClient:
        # Random start + failover (reference: tracker_get_connection's
        # round-robin over the tracker group).
        addrs = self.trackers[:]
        random.shuffle(addrs)
        last_err: Exception | None = None
        for host, port in addrs:
            try:
                return TrackerClient(host, port, self.timeout)
            except OSError as e:
                last_err = e
        raise ConnectionError(f"no tracker reachable: {last_err}")

    # -- operations --------------------------------------------------------

    def upload_buffer(self, data: bytes, ext: str = "",
                      group: str | None = None, appender: bool = False) -> str:
        with self._tracker() as t:
            tgt = t.query_store(group)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            return s.upload_buffer(data, ext=ext,
                                   store_path_index=tgt.store_path_index,
                                   appender=appender)

    def download_to_buffer(self, file_id: str, offset: int = 0,
                           length: int = 0) -> bytes:
        with self._tracker() as t:
            tgt = t.query_fetch(file_id)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            return s.download_to_buffer(file_id, offset, length)

    def delete_file(self, file_id: str) -> None:
        with self._tracker() as t:
            tgt = t.query_update(file_id)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            s.delete_file(file_id)

    def query_file_info(self, file_id: str) -> RemoteFileInfo:
        with self._tracker() as t:
            tgt = t.query_fetch(file_id)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            return s.query_file_info(file_id)

    def set_metadata(self, file_id: str, meta: dict[str, str],
                     merge: bool = False) -> None:
        with self._tracker() as t:
            tgt = t.query_update(file_id)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            s.set_metadata(file_id, meta, merge)

    def get_metadata(self, file_id: str) -> dict[str, str]:
        with self._tracker() as t:
            tgt = t.query_fetch(file_id)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            return s.get_metadata(file_id)

    def upload_appender_buffer(self, data: bytes, ext: str = "",
                               group: str | None = None) -> str:
        return self.upload_buffer(data, ext=ext, group=group, appender=True)

    def append_buffer(self, file_id: str, data: bytes) -> None:
        """Append to an appender file (routed to the source server, like
        every mutation — reference query_fetch_update update path)."""
        with self._tracker() as t:
            tgt = t.query_update(file_id)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            s.append_buffer(file_id, data)

    def modify_buffer(self, file_id: str, offset: int, data: bytes) -> None:
        with self._tracker() as t:
            tgt = t.query_update(file_id)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            s.modify_buffer(file_id, offset, data)

    def truncate_file(self, file_id: str, new_size: int = 0) -> None:
        with self._tracker() as t:
            tgt = t.query_update(file_id)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            s.truncate_file(file_id, new_size)

    def upload_slave_buffer(self, master_id: str, prefix: str, data: bytes,
                            ext: str = "") -> str:
        """Slave files live on the master's server (same name stem ⇒ same
        group and path), so route via query_update on the master."""
        with self._tracker() as t:
            tgt = t.query_update(master_id)
        with StorageClient(tgt.ip, tgt.port, self.timeout) as s:
            return s.upload_slave_buffer(master_id, prefix, data, ext)

    def list_groups(self) -> list[dict]:
        with self._tracker() as t:
            return t.list_groups()

    def delete_storage(self, group: str, ip: str, port: int) -> None:
        with self._tracker() as t:
            t.delete_storage(group, ip, port)

    def set_trunk_server(self, group: str, ip: str, port: int) -> None:
        # The override must land on the tracker LEADER (followers refuse
        # with EBUSY=16 rather than proxying): ask any tracker who leads,
        # target it, and fall back to trying each tracker in turn.
        with self._tracker() as t:
            leader = t.get_tracker_status().get("leader", "")
        if leader:
            try:
                host, _, p = leader.rpartition(":")
                with TrackerClient(host, int(p), self.timeout) as t:
                    t.set_trunk_server(group, ip, port)
                    return
            except (OSError, StatusError):
                pass
        last: Exception | None = None
        for host, p in self.trackers:
            try:
                with TrackerClient(host, p, self.timeout) as t:
                    t.set_trunk_server(group, ip, port)
                    return
            except (OSError, StatusError) as e:
                last = e
        raise last if last else ConnectionError("no tracker accepted override")

    def tracker_status(self) -> dict:
        with self._tracker() as t:
            return t.get_tracker_status()

    def list_storages(self, group: str) -> list[dict]:
        with self._tracker() as t:
            return t.list_storages(group)


def _parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad tracker address {addr!r} (want host:port)")
    return host, int(port)
