"""High-level client: the two-hop tracker→storage dance.

Reference: ``client/fdfs_client.h`` + client_func.c — fdfs_client_init()
from client.conf (tracker_server list), then every operation queries a
tracker for a storage target and talks to it directly.
"""

from __future__ import annotations

import concurrent.futures
import os
import random
import time
from collections import OrderedDict

from fastdfs_tpu.client.conn import ConnectionPool, ProtocolError, StatusError
from fastdfs_tpu.client.storage_client import RemoteFileInfo, StorageClient
from fastdfs_tpu.client.tracker_client import (FetchTarget, StoreTarget,
                                               TrackerClient)
from fastdfs_tpu.common.ini_config import IniConfig
from fastdfs_tpu.common.jumphash import (jump_hash, placement_key,
                                         replica_for_range)


class FdfsClient:
    """Tracker-routed client (reference: storage_upload_by_filename1 flow
    in SURVEY.md §3.1)."""

    def __init__(self, tracker_addrs: list[str] | str, timeout: float = 30.0,
                 use_pool: bool = True, dedup_uploads: bool = False,
                 dedup_min_bytes: int = 64 * 1024,
                 dedup_min_ratio: float = 0.05,
                 dedup_digest_cache: int = 1 << 16,
                 parallel_downloads: int = 1,
                 download_range_bytes: int = 4 << 20,
                 use_placement: bool = False,
                 dead_peer_cooldown_s: float = 30.0,
                 max_conns_per_endpoint: int = 0,
                 pool_idle_ttl_s: float = 300.0,
                 priority: int | None = None,
                 admission_retries: int = 2,
                 hot_routing: bool = True,
                 hot_map_ttl_s: float = 5.0):
        if isinstance(tracker_addrs, str):
            tracker_addrs = [tracker_addrs]
        if not tracker_addrs:
            raise ValueError("need at least one tracker address")
        self.trackers = [_parse_addr(a) for a in tracker_addrs]
        self.timeout = timeout
        # Pooled, health-checked connections per endpoint (reference:
        # connection_pool.c / client.conf:use_connection_pool); every
        # operation borrows and parks instead of reconnecting twice.
        # The pool also keeps the dead-peer cooldown map: endpoints that
        # failed at the transport level are deprioritized for
        # dead_peer_cooldown_s so each operation does not re-pay a
        # connect timeout against the same silent peer.
        # Multiplexing (ISSUE 18): max_conns_per_endpoint bounds idle +
        # in-use per (host, port) — concurrent borrowers (parallel
        # ranged downloads, threaded callers) grow the pool under load
        # up to the cap instead of serializing through one socket —
        # and pool_idle_ttl_s ages parked sockets out even for
        # endpoints that left the cluster.
        self.pool = (ConnectionPool(dead_peer_cooldown=dead_peer_cooldown_s,
                                    max_conns_per_endpoint=int(
                                        max_conns_per_endpoint),
                                    max_idle_seconds=float(pool_idle_ttl_s))
                     if use_pool else None)
        # Distributed tracing: a fastdfs_tpu.trace.Tracer (or None).
        # While set, every tracker/storage connection this client
        # acquires carries the tracer's current wire context, so daemon
        # spans stitch under the client's open span (trace.traced_upload
        # installs one around a single operation).
        self.tracer = None
        # Request QoS (ISSUE 19): when set (a protocol.PriorityClass
        # int, 0 control .. 4 background), every tracker/storage request
        # this client sends carries a PRIORITY prefix frame so the
        # daemons' admission ladders shed by the caller's declared class
        # instead of the opcode default.  admission_retries bounds how
        # many times an operation shed with a retry-after hint is
        # retried (after honoring the jittered hint) before the EBUSY
        # propagates.
        self.priority = priority
        self.admission_retries = max(int(admission_retries), 0)
        # Dedup-aware negotiated uploads (opt-in): when enabled,
        # upload_buffer routes through upload_buffer_dedup.  The
        # negotiation costs one extra round-trip, so small payloads
        # (< dedup_min_bytes) and payloads whose ESTIMATED dup ratio —
        # the fraction of chunk digests this client has uploaded
        # recently (bounded LRU) — falls below dedup_min_ratio go
        # straight to the classic single-RTT UPLOAD_FILE instead.
        self.dedup_uploads = dedup_uploads
        self.dedup_min_bytes = dedup_min_bytes
        self.dedup_min_ratio = dedup_min_ratio
        self._dedup_digest_cache = dedup_digest_cache
        self._seen_digests: OrderedDict[bytes, None] = OrderedDict()
        # Parallel ranged downloads (opt-in): with parallel_downloads > 1
        # every read over ~one range splits into download_range_bytes
        # ranges fetched concurrently, each from the replica jump-hash
        # picks for (file id, range index) — consistent across clients,
        # so per-replica read caches accumulate hits.  Falls back to the
        # classic single-stream download transparently on any failure.
        self.parallel_downloads = max(int(parallel_downloads), 1)
        self.download_range_bytes = max(int(download_range_bytes), 64 * 1024)
        # Placement routing (opt-in, store_lookup = 3 clusters): keyed
        # uploads route straight to a storage of the jump-hash home group
        # computed over a cached placement epoch (QUERY_PLACEMENT) — no
        # per-upload tracker round-trip.  Any refusal (the epoch drifted:
        # a group started draining and answers EBUSY, a member moved)
        # drops the cache and falls back to the classic tracker hop,
        # which always carries the key so the TRACKER applies the same
        # hash — routing stays correct, only the shortcut is lost.
        self.use_placement = bool(use_placement)
        self._placement: dict | None = None
        self._placement_rr = 0
        # Client-side resilience accounting (stats()): lifetime counts
        # of every transparent fallback this client took.  The paths are
        # silent by design — correctness never depended on the fast
        # path — so without these an operator cannot tell "dedup is
        # winning" from "dedup quietly gave up on every upload".
        self._fallbacks = {"dedup_fallback_plain": 0,
                           "placement_fallback_tracker": 0,
                           "ranged_fallback_single": 0,
                           "dead_peer_skips": 0,
                           "admission_retry_waits": 0,
                           "hot_route_reads": 0,
                           "hot_fallback_reads": 0}
        # Elastic hot replication (ISSUE 20): reads consult a cached
        # QUERY_HOT_MAP snapshot (TTL'd, delta-refreshed) and spread a
        # hot file's downloads across home + extra replica groups with
        # the same stateless jump-hash every client agrees on.  The map
        # is advisory: any miss, stale route, or tracker too old to
        # answer falls back to the classic tracker-routed read.
        self.hot_routing = bool(hot_routing)
        self.hot_map_ttl_s = max(float(hot_map_ttl_s), 0.5)
        self._hot_state: dict | None = None
        self._hot_rr = 0

    @classmethod
    def from_conf(cls, conf_path: str) -> "FdfsClient":
        cfg = IniConfig.load(conf_path)
        addrs = cfg.get_all("tracker_server")
        return cls(addrs, timeout=float(cfg.get_seconds("network_timeout", 30)),
                   use_pool=bool(cfg.get_bool("use_connection_pool", True)),
                   dedup_uploads=bool(cfg.get_bool("dedup_uploads", False)),
                   dedup_min_bytes=int(cfg.get_bytes("dedup_min_bytes",
                                                     64 * 1024)),
                   dedup_min_ratio=float(cfg.get("dedup_min_ratio", 0.05)),
                   parallel_downloads=int(cfg.get("parallel_downloads", 1)),
                   download_range_bytes=int(
                       cfg.get_bytes("download_range_bytes", 4 << 20)),
                   use_placement=bool(cfg.get_bool("use_placement", False)),
                   dead_peer_cooldown_s=float(
                       cfg.get_seconds("dead_peer_cooldown_s", 30)),
                   max_conns_per_endpoint=int(
                       cfg.get("max_conns_per_endpoint", 0)),
                   pool_idle_ttl_s=float(
                       cfg.get_seconds("pool_idle_ttl_s", 300)),
                   priority=(int(cfg.get("request_priority", -1))
                             if int(cfg.get("request_priority", -1)) >= 0
                             else None),
                   admission_retries=int(cfg.get("admission_retries", 2)),
                   hot_routing=bool(cfg.get_bool("hot_routing", True)),
                   hot_map_ttl_s=float(cfg.get_seconds("hot_map_ttl_s", 5)))

    def close(self) -> None:
        if self.pool is not None:
            self.pool.close_all()

    def stats(self) -> dict:
        """Lifetime client-side fallback counters: how often the dedup
        upload fell back to a plain UPLOAD_FILE, the placement shortcut
        fell back to the tracker hop, a parallel ranged download fell
        back to the classic single stream, and routing skipped a peer
        inside its dead-peer cooldown in favor of a live one.  The
        fallbacks are transparent (the call still succeeds), so this is
        the only place their frequency is visible.  ``hot_route_reads``
        counts downloads served by an elastic hot replica (ISSUE 20)
        and ``hot_fallback_reads`` the routed attempts that fell back
        to the classic tracker hop (stale map after a demotion, dead
        member)."""
        return dict(self._fallbacks)

    def _wire_ctx(self):
        return self.tracer.wire_ctx() if self.tracer is not None else None

    def _admission_wait(self, e: StatusError) -> None:
        """Honor an admission shed's retry-after hint: sleep the hinted
        interval plus up to 25% jitter, so a fleet of clients shed in
        the same tick does not stampede back in lockstep.  EBUSY
        WITHOUT a hint (max_connections refusal, non-leader, drain)
        never sleeps — those are answered by a different endpoint, not
        by waiting."""
        if e.retry_after_ms > 0:
            self._fallbacks["admission_retry_waits"] += 1
            time.sleep((e.retry_after_ms / 1000.0)
                       * (1.0 + 0.25 * random.random()))

    def _shed_retry(self, fn):
        """Run ``fn()``; when the admission ladder sheds it (StatusError
        EBUSY carrying a retry-after hint) sleep the jittered hint and
        re-run the WHOLE operation — including the tracker hop, which
        may well route the retry to a less-loaded peer — up to
        admission_retries times before the EBUSY propagates.  A shed
        happens at request-header stage, before any response body
        moves, so every operation here is safe to re-issue."""
        for _ in range(self.admission_retries):
            try:
                return fn()
            except StatusError as e:
                if e.status != 16 or e.retry_after_ms <= 0:
                    raise
                self._admission_wait(e)
        return fn()

    def _routed(self, query, op):
        """The classic two-hop dance (tracker query -> storage op) with
        admission-shed retry wrapped around the whole pair."""
        def run():
            tgt = self._with_tracker(query)
            with self._storage(tgt) as s:
                return op(s)
        return self._shed_retry(run)

    def _tracker(self) -> TrackerClient:
        # Random start + failover (reference: tracker_get_connection's
        # round-robin over the tracker group).  Trackers inside their
        # dead-peer cooldown sort last: they are still tried — the mark
        # is advisory, and with every tracker dead the order is simply
        # unchanged — but a live sibling wins without paying a connect
        # timeout first.
        addrs = self.trackers[:]
        random.shuffle(addrs)
        if self.pool is not None and len(addrs) > 1:
            dead = [a for a in addrs if self.pool.is_dead(*a)]
            if dead and len(dead) < len(addrs):
                addrs = [a for a in addrs if a not in dead] + dead
                self._fallbacks["dead_peer_skips"] += len(dead)
        last_err: Exception | None = None
        for host, port in addrs:
            try:
                if self.pool is not None:
                    conn = self.pool.acquire(host, port, self.timeout)
                    conn.trace_ctx = self._wire_ctx()
                    conn.priority = self.priority
                    return TrackerClient(host, port, self.timeout,
                                         conn=conn, release=self.pool.release)
                t = TrackerClient(host, port, self.timeout)
                t.conn.trace_ctx = self._wire_ctx()
                t.conn.priority = self.priority
                return t
            except OSError as e:
                last_err = e
                if self.pool is not None:
                    self.pool.mark_dead(host, port)
        raise ConnectionError(f"no tracker reachable: {last_err}")

    def _with_tracker(self, fn):
        """Run ``fn(tracker_client)``; a pooled connection to a
        silently-dead tracker passes the borrow check and fails only
        inside the operation, so on transport failure purge that
        endpoint's idle set and fail over (up to one pass per tracker —
        the pre-pool behavior, where connect-time errors drove the
        failover loop)."""
        attempts = max(len(self.trackers), 1) + 1
        last: Exception | None = None
        for _ in range(attempts):
            t = self._tracker()
            endpoint = (t.conn.host, t.conn.port)
            try:
                with t:
                    return fn(t)
            except StatusError as e:
                # A non-zero application status (e.g. ENOENT) is a
                # deterministic answer, not a transport failure: purging
                # the pool and retrying every tracker would just repeat
                # it.  EBUSY (16) is the exception — endpoint-specific
                # load (max_connections refusal, non-leader) that another
                # tracker may well answer; retry WITHOUT purging (the
                # transport is fine).  Crucially it must NOT mark the
                # endpoint dead either — an admission shed means "alive
                # but shedding", and a dead-mark would steer the next
                # dead_peer_cooldown_s of traffic away from a healthy
                # tracker.  A shed's retry-after hint is honored
                # (jittered) before the next attempt.
                if e.status != 16:
                    raise
                last = e
                self._admission_wait(e)
            except (OSError, ProtocolError) as e:
                last = e
                if self.pool is not None:
                    self.pool.purge(*endpoint)
                    self.pool.mark_dead(*endpoint)
        raise last if last is not None else ConnectionError("no tracker")

    def _storage(self, tgt) -> StorageClient:
        if self.pool is not None:
            conn = self.pool.acquire(tgt.ip, tgt.port, self.timeout)
            conn.trace_ctx = self._wire_ctx()
            conn.priority = self.priority
            return StorageClient(tgt.ip, tgt.port, self.timeout,
                                 conn=conn, release=self.pool.release)
        s = StorageClient(tgt.ip, tgt.port, self.timeout)
        s.conn.trace_ctx = self._wire_ctx()
        s.conn.priority = self.priority
        return s

    # -- operations --------------------------------------------------------

    def upload_buffer(self, data: bytes, ext: str = "",
                      group: str | None = None, appender: bool = False,
                      key: str | None = None) -> str:
        """``key``: optional placement key (store_lookup = 3 clusters).
        The tracker — or this client directly, with ``use_placement`` —
        jump-hashes it over the placement epoch so the same key always
        homes in the same group; other cluster policies ignore it."""
        if self.dedup_uploads and not appender:
            return self.upload_buffer_dedup(data, ext=ext, group=group,
                                            key=key)
        return self._upload_buffer_plain(data, ext=ext, group=group,
                                         appender=appender, key=key)

    def _placement_route(self, key: str) -> StoreTarget | None:
        """Storage target for ``key`` from the cached placement epoch —
        or None when no epoch is available (tracker too old, no active
        group), which means: take the classic tracker hop."""
        table = self._placement
        if table is None:
            try:
                table = self._with_tracker(lambda t: t.query_placement())
            except (StatusError, ProtocolError, ConnectionError, OSError):
                return None
            self._placement = table
        active = [g for g in table["groups"]
                  if g["state"] == 0 and g["members"]]
        if not active:
            return None
        g = active[jump_hash(placement_key(key), len(active))]
        self._placement_rr += 1
        members = g["members"]
        idx = self._placement_rr % len(members)
        if (self.pool is not None
                and self.pool.is_dead(members[idx]["ip"],
                                      members[idx]["port"])):
            # Round-robin landed on a member inside its dead-peer
            # cooldown: advance to the next live one (all-dead keeps the
            # pick — the upload path's own fallback covers the failure).
            live = [i for i in range(len(members))
                    if not self.pool.is_dead(members[i]["ip"],
                                             members[i]["port"])]
            if live:
                idx = live[self._placement_rr % len(live)]
                self._fallbacks["dead_peer_skips"] += 1
        m = members[idx]
        return StoreTarget(group=g["group"], ip=m["ip"], port=m["port"],
                           store_path_index=0xFF)

    def _upload_buffer_plain(self, data: bytes, ext: str = "",
                             group: str | None = None,
                             appender: bool = False,
                             key: str | None = None) -> str:
        # The classic single-RTT path; also every dedup fallback's target
        # (it must never re-enter the dedup gate, or a fallback recurses).
        if key is not None and group is None and self.use_placement:
            tgt = self._placement_route(key)
            if tgt is not None:
                try:
                    with self._storage(tgt) as s:
                        return s.upload_buffer(
                            data, ext=ext,
                            store_path_index=tgt.store_path_index,
                            appender=appender)
                except (StatusError, ProtocolError, OSError):
                    # Epoch drift (EBUSY from a now-draining group) or a
                    # dead member: forget the cache, fall through to the
                    # tracker, which re-hashes the key itself.
                    self._placement = None
                    self._fallbacks["placement_fallback_tracker"] += 1

        def run():
            tgt = self._with_tracker(
                lambda t: t.query_store(group, key=key))
            with self._storage(tgt) as s:
                return s.upload_buffer(data, ext=ext,
                                       store_path_index=tgt.store_path_index,
                                       appender=appender)
        return self._shed_retry(run)

    def _remember_digests(self, chunks) -> None:
        cache = self._seen_digests
        for _, digest in chunks:
            cache[digest] = None
            cache.move_to_end(digest)
        while len(cache) > self._dedup_digest_cache:
            cache.popitem(last=False)

    def upload_buffer_dedup(self, data: bytes, ext: str = "",
                            group: str | None = None,
                            min_dup_ratio: float | None = None,
                            stats: dict | None = None,
                            key: str | None = None) -> str:
        """Dedup-aware negotiated upload (UPLOAD_RECIPE/UPLOAD_CHUNKS):
        fingerprint locally, then ship only chunks the storage daemon's
        content-addressed store lacks — a warm re-upload moves ~0 data
        bytes.  Falls back to a plain UPLOAD_FILE transparently when:

        - the payload is small (< dedup_min_bytes — below the daemon's
          chunking threshold the recipe cannot be stored anyway);
        - the estimated dup ratio (recently-uploaded-digest LRU hit
          fraction) is under ``min_dup_ratio`` — fresh content would pay
          the extra round-trip for nothing (pass 0 to always negotiate);
        - the daemon lacks the opcodes or a chunk store, or the session
          fails mid-flight (StorageClient-level fallback).
        """
        if stats is None:
            stats = {}
        ratio_floor = (self.dedup_min_ratio if min_dup_ratio is None
                       else min_dup_ratio)
        if len(data) < self.dedup_min_bytes:
            stats.update(fallback="small", bytes_sent=len(data))
            self._fallbacks["dedup_fallback_plain"] += 1
            return self._upload_buffer_plain(data, ext=ext, group=group,
                                             key=key)
        from fastdfs_tpu.client.fingerprint import fingerprint_buffer
        chunks = [(fp.length, fp.digest) for fp in fingerprint_buffer(data)]
        if ratio_floor > 0:
            hits = sum(1 for _, d in chunks if d in self._seen_digests)
            estimate = hits / len(chunks) if chunks else 0.0
            stats["estimated_dup_ratio"] = estimate
            if estimate < ratio_floor:
                self._remember_digests(chunks)
                stats.update(fallback="low_estimate", bytes_sent=len(data))
                self._fallbacks["dedup_fallback_plain"] += 1
                return self._upload_buffer_plain(data, ext=ext, group=group,
                                                 key=key)
        self._remember_digests(chunks)
        tgt = self._with_tracker(lambda t: t.query_store(group, key=key))
        with self._storage(tgt) as s:
            fid = s.upload_buffer_dedup(
                data, ext=ext, store_path_index=tgt.store_path_index,
                chunks=chunks, stats=stats)
        # StorageClient-level bail-outs (daemon lacks the opcodes / a
        # chunk store, mid-session failure) report through the same
        # stats dict — one counter covers every dedup→plain path.
        if stats.get("fallback"):
            self._fallbacks["dedup_fallback_plain"] += 1
        return fid

    def download_to_buffer(self, file_id: str, offset: int = 0,
                           length: int = 0) -> bytes:
        if self.parallel_downloads > 1:
            return self.download_ranged(file_id, offset, length)
        return self._download_single(file_id, offset, length)

    def _download_single(self, file_id: str, offset: int = 0,
                         length: int = 0) -> bytes:
        # The classic one-connection path; also the ranged download's
        # transparent fallback target (it must never re-enter the
        # parallel gate, or a fallback recurses).  Hot routing rides in
        # front: when the cached hot map lists extra replica groups for
        # this file and the spread hash picks one, the read goes there
        # directly; None (not hot, home pick, or any failure) falls
        # through to the tracker hop.
        if self.hot_routing:
            data = self._hot_download(file_id, offset, length)
            if data is not None:
                return data
        return self._routed(lambda t: t.query_fetch(file_id),
                            lambda s: s.download_to_buffer(file_id, offset,
                                                           length))

    def _hot_groups(self, file_id: str) -> list[str] | None:
        """Extra replica groups for ``file_id`` from the cached hot map,
        refreshing it at most once per ``hot_map_ttl_s`` (delta query
        carrying the cached version; a tombstone delta entry — zero
        groups — evicts a demoted key).  Every refresh failure keeps the
        stale map and waits for the next TTL window: the map is
        advisory, never load-bearing."""
        now = time.monotonic()
        st = self._hot_state
        if st is None:
            st = {"version": -1, "entries": {}, "fetched": float("-inf")}
            self._hot_state = st
        if now - st["fetched"] >= self.hot_map_ttl_s:
            st["fetched"] = now  # one attempt per window, pass or fail
            try:
                since = st["version"] if st["version"] >= 0 else None
                resp = self._with_tracker(lambda t: t.query_hot_map(since))
                if resp["full"]:
                    st["entries"] = {e["key"]: e["groups"]
                                     for e in resp["entries"] if e["groups"]}
                else:
                    for e in resp["entries"]:
                        if e["groups"]:
                            st["entries"][e["key"]] = e["groups"]
                        else:
                            st["entries"].pop(e["key"], None)
                st["version"] = resp["version"]
            except Exception:  # noqa: BLE001 — advisory map, incl. old
                # trackers (unknown command) and monkeypatched mocks;
                # back off harder on a protocol-level refusal so a
                # pre-hot-map tracker is not re-asked every window.
                st["fetched"] = now + 11 * self.hot_map_ttl_s
        return st["entries"].get(file_id)

    def _hot_member(self, group: str) -> StoreTarget | None:
        """An ACTIVE member of ``group`` from the cached placement epoch
        (round-robin across members, dead peers skipped) — or None when
        the group is unknown/empty, meaning: no hot shortcut."""
        table = self._placement
        if table is None:
            try:
                table = self._with_tracker(lambda t: t.query_placement())
            except Exception:  # noqa: BLE001 — shortcut only
                return None
            if not isinstance(table, dict) or "groups" not in table:
                return None  # monkeypatched tracker hop: no shortcut
            self._placement = table
        for g in table["groups"]:
            if g["group"] != group or g["state"] != 0 or not g["members"]:
                continue
            members = g["members"]
            self._placement_rr += 1
            idx = self._placement_rr % len(members)
            if (self.pool is not None
                    and self.pool.is_dead(members[idx]["ip"],
                                          members[idx]["port"])):
                live = [i for i in range(len(members))
                        if not self.pool.is_dead(members[i]["ip"],
                                                 members[i]["port"])]
                if live:
                    idx = live[self._placement_rr % len(live)]
                    self._fallbacks["dead_peer_skips"] += 1
            m = members[idx]
            return StoreTarget(group=group, ip=m["ip"], port=m["port"],
                               store_path_index=0xFF)
        return None

    def _hot_download(self, file_id: str, offset: int,
                      length: int) -> bytes | None:
        """One hot-routed read attempt; None means 'take the classic
        path' (not hot, the spread hash picked the home group, no
        placement info, or the routed attempt failed — stale map after
        a demotion, member down).  The replica set is home + the map's
        extra groups in map order, so every client spreads reads with
        the same ``jump_hash(sha1(file_id#i), n_replicas)`` choice and
        per-replica caches accumulate hits."""
        groups = self._hot_groups(file_id)
        if not groups or "/" not in file_id:
            return None
        home, remote = file_id.split("/", 1)
        replicas = [home] + [g for g in groups if g != home]
        if len(replicas) < 2:
            return None
        self._hot_rr += 1
        pick = replicas[replica_for_range(file_id, self._hot_rr,
                                          len(replicas))]
        if pick == home:
            return None  # the classic tracker hop serves home reads
        tgt = self._hot_member(pick)
        if tgt is None:
            return None
        try:
            with self._storage(tgt) as s:
                data = s.download_to_buffer(f"{pick}/{remote}", offset,
                                            length)
            self._fallbacks["hot_route_reads"] += 1
            return data
        except Exception:  # noqa: BLE001 — transparent fallback
            # A stale route (the copy was demoted and dropped after the
            # map was cached) or a dead member: evict the cached entry
            # so this file stops routing until the next refresh, and
            # let the classic path serve the read.
            st = self._hot_state
            if st is not None:
                st["entries"].pop(file_id, None)
            self._fallbacks["hot_fallback_reads"] += 1
            return None

    def download_stream(self, file_id: str, fh, offset: int = 0,
                        length: int = 0) -> int:
        """Stream (part of) a file into ``fh`` with O(segment) client
        memory (StorageClient.download_stream underneath).  Returns the
        byte count written.  Shed-retry is safe here: a shed answers
        the request header, so no body byte has reached ``fh`` yet."""
        return self._routed(lambda t: t.query_fetch(file_id),
                            lambda s: s.download_stream(file_id, fh, offset,
                                                        length))

    def download_to_file(self, file_id: str, local_path: str,
                         offset: int = 0, length: int = 0,
                         parallel: int | None = None) -> int:
        parallel = self.parallel_downloads if parallel is None else parallel
        if parallel > 1:
            # Ranged bytes land in memory first; the write-out still
            # goes via temp + rename so a failed local write (ENOSPC,
            # kill) can never truncate an existing file or leave a
            # silently-partial one.
            data = self.download_ranged(file_id, offset, length,
                                        parallel=parallel)
            tmp = f"{local_path}.part{os.getpid()}"
            try:
                with open(tmp, "wb") as fh:
                    fh.write(data)
                os.replace(tmp, local_path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            return len(data)
        # Single stream: StorageClient owns the temp-file + rename
        # discipline (one implementation of the no-partial-file rule).
        return self._routed(lambda t: t.query_fetch(file_id),
                            lambda s: s.download_to_file(file_id, local_path,
                                                         offset, length))

    def download_ranged(self, file_id: str, offset: int = 0,
                        length: int = 0, parallel: int | None = None,
                        range_bytes: int | None = None) -> bytes:
        """Parallel ranged download: split [offset, offset+length) into
        download_range_bytes ranges and fetch them concurrently across
        the group's read-safe replicas (tracker QUERY_FETCH_ALL), each
        range from the replica ``jump_hash(file id, range index)`` picks
        — the stateless consistent choice every client agrees on, so
        per-replica hot-chunk caches accumulate hits (cache affinity).
        Each worker lands its range directly in its slice of the shared
        output buffer (DOWNLOAD_FILE's offset+count head fields carry
        the range; every daemon generation serves them).  ANY failure —
        an unreachable replica, a short/oversized body, a tracker too
        old to list replicas — falls back transparently to the classic
        single-stream download."""
        parallel = self.parallel_downloads if parallel is None else parallel
        range_bytes = (self.download_range_bytes if range_bytes is None
                       else range_bytes)
        if parallel <= 1:
            return self._download_single(file_id, offset, length)
        try:
            replicas = self._with_tracker(
                lambda t: t.query_fetch_all(file_id))
            if not replicas:
                raise ProtocolError("tracker listed no read replicas")
            with self._storage(replicas[replica_for_range(
                    file_id, 0, len(replicas))]) as s:
                size = s.query_file_info(file_id).file_size
            total = max(size - offset, 0)
            if length:
                total = min(total, length)
            if total <= range_bytes:  # one range: no split to win from
                return self._download_single(file_id, offset, length)
            ranges = []
            off = offset
            while off < offset + total:
                ln = min(range_bytes, offset + total - off)
                ranges.append((len(ranges), off, ln))
                off += ln
            buf = bytearray(total)
            mv = memoryview(buf)

            def fetch(idx: int, off: int, ln: int) -> None:
                # Cache-affinity pick first; a replica inside its
                # dead-peer cooldown yields to the next live one (the
                # affinity win is worthless against a connect timeout).
                # All-dead keeps the original pick — the mark is
                # advisory, and the outer fallback still covers failure.
                k = replica_for_range(file_id, idx, len(replicas))
                if (self.pool is not None
                        and self.pool.is_dead(replicas[k].ip,
                                              replicas[k].port)):
                    for step in range(1, len(replicas)):
                        alt = (k + step) % len(replicas)
                        if not self.pool.is_dead(replicas[alt].ip,
                                                 replicas[alt].port):
                            k = alt
                            self._fallbacks["dead_peer_skips"] += 1
                            break
                tgt = replicas[k]
                try:
                    with self._storage(tgt) as s:
                        s.download_into(file_id,
                                        mv[off - offset:off - offset + ln],
                                        offset=off)
                except OSError:
                    if self.pool is not None:
                        self.pool.mark_dead(tgt.ip, tgt.port)
                    raise

            with concurrent.futures.ThreadPoolExecutor(
                    min(parallel, len(ranges))) as ex:
                futs = [ex.submit(fetch, *r) for r in ranges]
                for f in futs:
                    f.result()  # re-raise the first failure
            return bytes(buf)
        except Exception:  # noqa: BLE001 — transparent whole-file fallback
            self._fallbacks["ranged_fallback_single"] += 1
            return self._download_single(file_id, offset, length)

    def delete_file(self, file_id: str) -> None:
        self._routed(lambda t: t.query_update(file_id),
                     lambda s: s.delete_file(file_id))

    def query_file_info(self, file_id: str) -> RemoteFileInfo:
        return self._routed(lambda t: t.query_fetch(file_id),
                            lambda s: s.query_file_info(file_id))

    def near_dups(self, file_id: str) -> list[tuple[str, float]]:
        """Ranked (file_id, score) near-duplicates of a stored file
        (dedup-engine MinHash index; fastdfs_tpu extension)."""
        tgt = self._with_tracker(lambda t: t.query_fetch(file_id))
        with self._storage(tgt) as s:
            return s.near_dups(file_id)

    def set_metadata(self, file_id: str, meta: dict[str, str],
                     merge: bool = False) -> None:
        self._routed(lambda t: t.query_update(file_id),
                     lambda s: s.set_metadata(file_id, meta, merge))

    def get_metadata(self, file_id: str) -> dict[str, str]:
        return self._routed(lambda t: t.query_fetch(file_id),
                            lambda s: s.get_metadata(file_id))

    def upload_appender_buffer(self, data: bytes, ext: str = "",
                               group: str | None = None) -> str:
        return self.upload_buffer(data, ext=ext, group=group, appender=True)

    def append_buffer(self, file_id: str, data: bytes) -> None:
        """Append to an appender file (routed to the source server, like
        every mutation — reference query_fetch_update update path)."""
        self._routed(lambda t: t.query_update(file_id),
                     lambda s: s.append_buffer(file_id, data))

    def modify_buffer(self, file_id: str, offset: int, data: bytes) -> None:
        self._routed(lambda t: t.query_update(file_id),
                     lambda s: s.modify_buffer(file_id, offset, data))

    def truncate_file(self, file_id: str, new_size: int = 0) -> None:
        self._routed(lambda t: t.query_update(file_id),
                     lambda s: s.truncate_file(file_id, new_size))

    def upload_slave_buffer(self, master_id: str, prefix: str, data: bytes,
                            ext: str = "") -> str:
        """Slave files live on the master's server (same name stem ⇒ same
        group and path), so route via query_update on the master."""
        tgt = self._with_tracker(lambda t: t.query_update(master_id))
        with self._storage(tgt) as s:
            return s.upload_slave_buffer(master_id, prefix, data, ext)

    def list_groups(self) -> list[dict]:
        return self._with_tracker(lambda t: t.list_groups())

    def delete_storage(self, group: str, ip: str, port: int) -> None:
        self._with_tracker(lambda t: t.delete_storage(group, ip, port))

    def set_trunk_server(self, group: str, ip: str, port: int) -> None:
        # The override must land on the tracker LEADER (followers refuse
        # with EBUSY=16 rather than proxying): ask any tracker who leads,
        # target it, and fall back to trying each tracker in turn.
        leader = self._with_tracker(lambda t: t.get_tracker_status().get("leader", ""))
        if leader:
            try:
                host, _, p = leader.rpartition(":")
                with TrackerClient(host, int(p), self.timeout) as t:
                    t.set_trunk_server(group, ip, port)
                    return
            except (OSError, StatusError):
                pass
        last: Exception | None = None
        for host, p in self.trackers:
            try:
                with TrackerClient(host, p, self.timeout) as t:
                    t.set_trunk_server(group, ip, port)
                    return
            except (OSError, StatusError) as e:
                last = e
        raise last if last else ConnectionError("no tracker accepted override")

    def tracker_status(self) -> dict:
        return self._with_tracker(lambda t: t.get_tracker_status())

    def list_storages(self, group: str) -> list[dict]:
        return self._with_tracker(lambda t: t.list_storages(group))

    def cluster_stat(self, group: str | None = None) -> dict:
        """Tracker-held cluster observability dump (role, groups,
        per-storage liveness + named beat stats)."""
        return self._with_tracker(lambda t: t.cluster_stat(group))

    def storage_stat(self, ip: str, port: int) -> dict:
        """One storage daemon's stats-registry snapshot (STAT opcode)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.stat()

    def storage_events(self, ip: str, port: int) -> dict:
        """One storage daemon's flight-recorder dump (EVENT_DUMP)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.event_dump()

    def storage_metrics_history(self, ip: str, port: int,
                                since_us: int = 0) -> dict:
        """One storage daemon's metrics-journal window (METRICS_HISTORY)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.metrics_history(since_us)

    def storage_heat_top(self, ip: str, port: int, k: int = 0) -> dict:
        """One storage daemon's hot-file top-K (HEAT_TOP)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.heat_top(k)

    def storage_profile_start(self, ip: str, port: int, hz: int = 97,
                              duration_s: int = 30) -> dict:
        """Arm one storage daemon's sampling profiler (PROFILE_CTL)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.profile_start(hz, duration_s)

    def storage_profile_stop(self, ip: str, port: int) -> dict:
        """Disarm one storage daemon's profiler early (idempotent)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.profile_stop()

    def storage_profile_dump(self, ip: str, port: int) -> dict:
        """One storage daemon's folded-stack dump (PROFILE_DUMP); shape
        per fastdfs_tpu.monitor.decode_profile."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.profile_dump()

    def health_matrix(self) -> dict:
        """The tracker's gray-failure differential matrix
        (HEALTH_MATRIX); shape per monitor.decode_health_matrix."""
        return self._with_tracker(lambda t: t.health_matrix())

    def storage_health_status(self, ip: str, port: int) -> dict:
        """One storage daemon's gray-failure health view (HEALTH_STATUS);
        shape per monitor.decode_health_status."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.health_status()

    def storage_admission_status(self, ip: str, port: int) -> dict:
        """One storage daemon's admission-ladder status
        (ADMISSION_STATUS); shape per monitor.decode_admission.  Born
        control-class server-side, so it answers even at reads-only."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.admission_status()

    def tracker_admission_status(self) -> dict:
        """The tracker's own admission-ladder status (ADMISSION_STATUS);
        shape per monitor.decode_admission."""
        return self._with_tracker(lambda t: t.admission_status())

    def scrub_status(self, ip: str, port: int) -> dict[str, int]:
        """One storage daemon's integrity-engine status (SCRUB_STATUS)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.scrub_status()

    def scrub_kick(self, ip: str, port: int) -> None:
        """Force a scrub pass on one storage daemon (SCRUB_KICK)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            s.scrub_kick()

    def ec_status(self, ip: str, port: int) -> dict[str, int]:
        """One storage daemon's erasure-coding status (EC_STATUS)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            return s.ec_status()

    def ec_kick(self, ip: str, port: int) -> None:
        """Force an EC demotion pass on one storage daemon (EC_KICK)."""
        with self._storage(FetchTarget(ip=ip, port=port)) as s:
            s.ec_kick()

    # -- placement epoch / group lifecycle ---------------------------------

    def _leader_call(self, fn):
        """Run ``fn(tracker_client)`` against the tracker LEADER
        (followers refuse leader-only admin ops with EBUSY=16 rather
        than proxying): ask any tracker who leads, target it, then fall
        back to trying each tracker in turn.  A deterministic refusal
        (unknown group, invalid transition) propagates immediately —
        another tracker would only repeat it."""
        leader = self._with_tracker(
            lambda t: t.get_tracker_status().get("leader", ""))
        if leader:
            host, _, p = leader.rpartition(":")
            try:
                with TrackerClient(host, int(p), self.timeout) as t:
                    return fn(t)
            except StatusError as e:
                if e.status != 16:
                    raise
            except OSError:
                pass
        last: Exception | None = None
        for host, p in self.trackers:
            try:
                with TrackerClient(host, p, self.timeout) as t:
                    return fn(t)
            except StatusError as e:
                if e.status != 16:
                    raise
                last = e
            except OSError as e:
                last = e
        raise last if last else ConnectionError("no tracker accepted the call")

    def query_placement(self) -> dict:
        """The placement epoch (group order + lifecycle states + active
        members), as any tracker serves it (QUERY_PLACEMENT)."""
        return self._with_tracker(lambda t: t.query_placement())

    def query_hot_map(self, since_version: int | None = None) -> dict:
        """The elastic hot-replication map (QUERY_HOT_MAP): published
        hot files and the extra groups serving each; ``since_version``
        asks for a delta (zero-group entries are tombstones)."""
        return self._with_tracker(lambda t: t.query_hot_map(since_version))

    def group_drain(self, group: str) -> int:
        """Start draining ``group`` (leader-routed GROUP_DRAIN).  Returns
        the new placement version."""
        return self._leader_call(lambda t: t.group_drain(group))

    def group_reactivate(self, group: str) -> int:
        """Cancel a drain (leader-routed GROUP_REACTIVATE).  Returns the
        new placement version."""
        return self._leader_call(lambda t: t.group_reactivate(group))


def _parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad tracker address {addr!r} (want host:port)")
    return host, int(port)
