"""Tracker client: cluster queries.

Reference: ``client/tracker_client.c`` — tracker_query_storage_store(),
tracker_query_storage_fetch(), tracker_list_groups().  Hot-path queries are
fixed-width binary; list/monitor responses are JSON (this rebuild's
FastDFS-shaped protocol, served by ``native/tracker/``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from fastdfs_tpu.client.conn import Connection, ProtocolError
from fastdfs_tpu.common.protocol import (
    GROUP_NAME_MAX_LEN,
    IP_ADDRESS_SIZE,
    TrackerCmd,
    buff2long,
    long2buff,
    pack_group_name,
    pack_profile_ctl,
    unpack_group_name,
)


@dataclass(frozen=True)
class StoreTarget:
    group: str
    ip: str
    port: int
    store_path_index: int


@dataclass(frozen=True)
class FetchTarget:
    ip: str
    port: int


class TrackerClient:
    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 conn: Connection | None = None, release=None):
        # `conn`/`release` inject a pooled connection (ConnectionPool):
        # close() then parks it instead of closing the socket.
        self.conn = conn if conn is not None else Connection(host, port, timeout)
        self._release = release

    def close(self) -> None:
        conn, self.conn = self.conn, None
        if conn is None:
            return  # idempotent: the pool may already own the socket
        if self._release is not None:
            release, self._release = self._release, None
            release(conn)
        else:
            conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- service queries (upload/download routing) -------------------------

    def query_store(self, group: str | None = None,
                    key: str | None = None) -> StoreTarget:
        """Which storage should take an upload (reference:
        tracker_query_storage_store).  Resp: 16B group + 16B ip + 8B port +
        1B store path index.  ``key`` (groupless form only) is the client's
        placement key — store_lookup = 3 trackers jump-hash it over the
        placement epoch; other policies ignore it."""
        if group is None:
            self.conn.send_request(
                TrackerCmd.SERVICE_QUERY_STORE_WITHOUT_GROUP_ONE,
                key.encode() if key else b"")
        else:
            self.conn.send_request(TrackerCmd.SERVICE_QUERY_STORE_WITH_GROUP_ONE,
                                   pack_group_name(group))
        body = self.conn.recv_response("query_store")
        if len(body) < GROUP_NAME_MAX_LEN + IP_ADDRESS_SIZE + 9:
            raise ProtocolError(f"short query_store response: {len(body)}")
        return StoreTarget(
            group=unpack_group_name(body[:16]),
            ip=body[16:32].rstrip(b"\x00").decode(),
            port=buff2long(body, 32),
            store_path_index=body[40],
        )

    def _query_fetch(self, cmd: int, file_id: str) -> FetchTarget:
        group, _, remote = file_id.partition("/")
        body = pack_group_name(group) + remote.encode()
        self.conn.send_request(cmd, body)
        resp = self.conn.recv_response("query_fetch")
        if len(resp) < IP_ADDRESS_SIZE + 8:
            raise ProtocolError(f"short query_fetch response: {len(resp)}")
        return FetchTarget(ip=resp[:16].rstrip(b"\x00").decode(),
                           port=buff2long(resp, 16))

    def query_fetch(self, file_id: str) -> FetchTarget:
        """Which replica can serve a read (sync-timestamp-safe routing)."""
        return self._query_fetch(TrackerCmd.SERVICE_QUERY_FETCH_ONE, file_id)

    def query_update(self, file_id: str) -> FetchTarget:
        """Which server takes mutations (metadata/delete) for this file."""
        return self._query_fetch(TrackerCmd.SERVICE_QUERY_UPDATE, file_id)

    def _parse_target_list(self, resp: bytes) -> tuple[str, int, list[FetchTarget]]:
        """ALL-variant reply: 16B group + 1B path idx + 8B count + count x
        (16B ip + 8B port)."""
        if len(resp) < GROUP_NAME_MAX_LEN + 9:
            raise ProtocolError(f"short target-list response: {len(resp)}")
        group = unpack_group_name(resp[:16])
        path_idx = resp[16]
        count = buff2long(resp, 17)
        rec = IP_ADDRESS_SIZE + 8
        if count < 0 or count > (len(resp) - 25) // rec:
            raise ProtocolError(f"bad target-list count {count}")
        targets = []
        for i in range(count):
            off = 25 + i * rec
            targets.append(FetchTarget(
                ip=resp[off:off + 16].rstrip(b"\x00").decode(),
                port=buff2long(resp, off + 16)))
        return group, path_idx, targets

    def query_store_all(self, group: str | None = None) \
            -> tuple[str, list[FetchTarget]]:
        """All writable storages of the picked group (reference:
        QUERY_STORE_WITHOUT_GROUP_ALL 106 / WITH_GROUP_ALL 107 — the client
        retries among them)."""
        if group is None:
            self.conn.send_request(
                TrackerCmd.SERVICE_QUERY_STORE_WITHOUT_GROUP_ALL)
        else:
            self.conn.send_request(
                TrackerCmd.SERVICE_QUERY_STORE_WITH_GROUP_ALL,
                pack_group_name(group))
        g, _, targets = self._parse_target_list(
            self.conn.recv_response("query_store_all"))
        return g, targets

    def query_fetch_all(self, file_id: str) -> list[FetchTarget]:
        """Every replica currently safe to read this file (reference:
        QUERY_FETCH_ALL 105)."""
        group, _, remote = file_id.partition("/")
        self.conn.send_request(TrackerCmd.SERVICE_QUERY_FETCH_ALL,
                               pack_group_name(group) + remote.encode())
        _, _, targets = self._parse_target_list(
            self.conn.recv_response("query_fetch_all"))
        return targets

    # -- monitor / ops (JSON responses) ------------------------------------

    def list_groups(self) -> list[dict]:
        self.conn.send_request(TrackerCmd.SERVER_LIST_ALL_GROUPS)
        return json.loads(self.conn.recv_response("list_groups") or b"[]")

    def list_one_group(self, group: str) -> dict:
        self.conn.send_request(TrackerCmd.SERVER_LIST_ONE_GROUP,
                               pack_group_name(group))
        return json.loads(self.conn.recv_response("list_one_group") or b"{}")

    def get_parameters(self) -> dict[str, str]:
        """Cluster-global storage parameters (storage_param_getter.c)."""
        self.conn.send_request(TrackerCmd.STORAGE_PARAMETER_REQ)
        text = self.conn.recv_response("get_parameters").decode()
        out: dict[str, str] = {}
        for line in text.splitlines():
            key, _, value = line.partition("=")
            if key and _:
                out[key] = value
        return out

    def cluster_stat(self, group: str | None = None) -> dict:
        """One-RPC observability dump (SERVER_CLUSTER_STAT 95): tracker
        role/leader plus every group and storage with the full named
        last-beat stat payload.  Optional group filter."""
        body = pack_group_name(group) if group else b""
        self.conn.send_request(TrackerCmd.SERVER_CLUSTER_STAT, body)
        return json.loads(self.conn.recv_response("cluster_stat") or b"{}")

    def list_storages(self, group: str) -> list[dict]:
        self.conn.send_request(TrackerCmd.SERVER_LIST_STORAGE,
                               pack_group_name(group))
        return json.loads(self.conn.recv_response("list_storages") or b"[]")

    def delete_storage(self, group: str, ip: str, port: int) -> None:
        body = pack_group_name(group) + f"{ip}:{port}".encode()
        self.conn.send_request(TrackerCmd.SERVER_DELETE_STORAGE, body)
        self.conn.recv_response("delete_storage")

    def set_trunk_server(self, group: str, ip: str, port: int) -> None:
        """Operator override of the elected trunk server (cmd 94)."""
        body = pack_group_name(group) + f"{ip}:{port}".encode()
        self.conn.send_request(TrackerCmd.SERVER_SET_TRUNK_SERVER, body)
        self.conn.recv_response("set_trunk_server")

    # -- placement epoch / group lifecycle (fastdfs_tpu extension) ---------

    def query_placement(self) -> dict:
        """The placement epoch (QUERY_PLACEMENT 64): version + the ordered
        group list with lifecycle states and each group's ACTIVE members.
        Wire: 8B BE version + 8B BE entry count + per entry (16B group +
        1B state + 8B BE member count + per member (16B ip + 8B port))."""
        self.conn.send_request(TrackerCmd.QUERY_PLACEMENT)
        resp = self.conn.recv_response("query_placement")
        if len(resp) < 16:
            raise ProtocolError(f"short query_placement response: {len(resp)}")
        version = buff2long(resp, 0)
        count = buff2long(resp, 8)
        off = 16
        names = {0: "active", 1: "draining", 2: "retired"}
        groups = []
        for _ in range(count):
            if off + GROUP_NAME_MAX_LEN + 9 > len(resp):
                raise ProtocolError("truncated query_placement entry")
            group = unpack_group_name(resp[off:off + 16])
            state = resp[off + 16]
            members_n = buff2long(resp, off + 17)
            off += GROUP_NAME_MAX_LEN + 9
            rec = IP_ADDRESS_SIZE + 8
            if members_n < 0 or members_n > (len(resp) - off) // rec:
                raise ProtocolError(f"bad member count {members_n}")
            members = []
            for m in range(members_n):
                p = off + m * rec
                members.append({"ip": resp[p:p + 16].rstrip(b"\x00").decode(),
                                "port": buff2long(resp, p + 16)})
            off += members_n * rec
            groups.append({"group": group, "state": state,
                           "state_name": names.get(state, "?"),
                           "members": members})
        return {"version": version, "groups": groups}

    def query_hot_map(self, since_version: int | None = None) -> dict:
        """The elastic hot-replication map (QUERY_HOT_MAP 75): published
        hot entries and the extra replica groups serving each.  Empty
        body = full snapshot; 8B BE since_version = delta of changes
        after that version (a delta entry with zero groups is a
        tombstone — the key was demoted).  The tracker falls back to a
        full snapshot when the requested delta predates its changelog.
        Wire: 8B BE version + 1B full flag + 8B BE entry count + per
        entry (8B BE key_len + key + 8B BE group count + n x 16B group
        names)."""
        body = b"" if since_version is None else long2buff(since_version)
        self.conn.send_request(TrackerCmd.QUERY_HOT_MAP, body)
        resp = self.conn.recv_response("query_hot_map")
        if len(resp) < 17:
            raise ProtocolError(f"short query_hot_map response: {len(resp)}")
        version = buff2long(resp, 0)
        full = resp[8] != 0
        count = buff2long(resp, 9)
        off = 17
        entries = []
        for _ in range(count):
            if off + 8 > len(resp):
                raise ProtocolError("truncated query_hot_map entry")
            key_len = buff2long(resp, off)
            off += 8
            if key_len < 0 or off + key_len + 8 > len(resp):
                raise ProtocolError(f"bad hot-map key length {key_len}")
            key = resp[off:off + key_len].decode()
            off += key_len
            ngroups = buff2long(resp, off)
            off += 8
            if ngroups < 0 or \
                    ngroups > (len(resp) - off) // GROUP_NAME_MAX_LEN:
                raise ProtocolError(f"bad hot-map group count {ngroups}")
            groups = []
            for g in range(ngroups):
                p = off + g * GROUP_NAME_MAX_LEN
                groups.append(
                    unpack_group_name(resp[p:p + GROUP_NAME_MAX_LEN]))
            off += ngroups * GROUP_NAME_MAX_LEN
            entries.append({"key": key, "groups": groups})
        return {"version": version, "full": full, "entries": entries}

    def _group_admin(self, cmd: int, group: str, what: str) -> int:
        self.conn.send_request(cmd, pack_group_name(group))
        resp = self.conn.recv_response(what)
        if len(resp) < 8:
            raise ProtocolError(f"short {what} response: {len(resp)}")
        return buff2long(resp, 0)

    def group_drain(self, group: str) -> int:
        """Start draining a group (GROUP_DRAIN 65, tracker leader only):
        no new writes land there; its members migrate every file to its
        jump-hash home and the leader auto-retires the group when all
        report done.  Returns the new placement version."""
        return self._group_admin(TrackerCmd.GROUP_DRAIN, group, "group_drain")

    def group_reactivate(self, group: str) -> int:
        """Cancel a drain (GROUP_REACTIVATE 66, leader only).  Retired
        groups are refused (StatusError 22) — their data already moved.
        Returns the new placement version."""
        return self._group_admin(TrackerCmd.GROUP_REACTIVATE, group,
                                 "group_reactivate")

    def active_test(self) -> bool:
        self.conn.send_request(TrackerCmd.ACTIVE_TEST)
        self.conn.recv_response("active_test")
        return True

    def trace_dump(self) -> dict:
        """Span ring-buffer dump (TRACE_DUMP 96): this tracker's retained
        request spans.  Shape per fastdfs_tpu.trace.decode_dump."""
        self.conn.send_request(TrackerCmd.TRACE_DUMP)
        return json.loads(self.conn.recv_response("trace_dump") or b"{}")

    def stat(self) -> dict:
        """The tracker's own stats-registry snapshot (STAT 97): event-loop
        lag, dispatched ops, request accounting.  Same JSON contract as
        the storage STAT (fastdfs_tpu.monitor.decode_registry)."""
        self.conn.send_request(TrackerCmd.STAT)
        return json.loads(self.conn.recv_response("stat") or b"{}")

    def event_dump(self) -> dict:
        """Flight-recorder dump (EVENT_DUMP 98): membership transitions
        and slow requests.  Shape per fastdfs_tpu.monitor.decode_events."""
        self.conn.send_request(TrackerCmd.EVENT_DUMP)
        return json.loads(self.conn.recv_response("event_dump") or b"{}")

    def health_matrix(self) -> dict:
        """Gray-failure differential matrix (HEALTH_MATRIX 69): every
        storage's self-reported gray score from its beat trailer against
        what its group peers score it, with the tracker's verdict
        (ok/gray/sick/unknown).  Shape per
        fastdfs_tpu.monitor.decode_health_matrix."""
        self.conn.send_request(TrackerCmd.HEALTH_MATRIX)
        return json.loads(self.conn.recv_response("health_matrix") or b"{}")

    def admission_status(self) -> dict:
        """Admission-ladder status (ADMISSION_STATUS 148): current shed
        level, pressure EWMA, per-class shed counts.  Shape per
        fastdfs_tpu.monitor.decode_admission."""
        self.conn.send_request(TrackerCmd.ADMISSION_STATUS)
        return json.loads(self.conn.recv_response("admission_status")
                          or b"{}")

    def metrics_history(self, since_us: int = 0) -> dict:
        """Metrics-journal window dump (METRICS_HISTORY 99): the
        tracker's retained registry snapshots with ts_us >= since_us
        (0 = all).  Shape per
        fastdfs_tpu.monitor.decode_metrics_history; StatusError(95)
        when journaling is off."""
        from fastdfs_tpu.common.protocol import long2buff
        body = long2buff(since_us) if since_us else b""
        self.conn.send_request(TrackerCmd.METRICS_HISTORY, body)
        return json.loads(self.conn.recv_response("metrics_history") or b"{}")

    def profile_start(self, hz: int = 97, duration_s: int = 30) -> dict:
        """Arm the tracker's sampling profiler (PROFILE_CTL 67); same
        contract as StorageClient.profile_start — ack {"active", "hz"},
        StatusError(95) when profile_max_hz = 0, auto-disarm at the
        duration deadline."""
        self.conn.send_request(TrackerCmd.PROFILE_CTL,
                               pack_profile_ctl(True, hz, duration_s))
        return json.loads(self.conn.recv_response("profile_start") or b"{}")

    def profile_stop(self) -> dict:
        """Disarm early (PROFILE_CTL 67, action 0); idempotent, samples
        kept for profile_dump."""
        self.conn.send_request(TrackerCmd.PROFILE_CTL,
                               pack_profile_ctl(False))
        return json.loads(self.conn.recv_response("profile_stop") or b"{}")

    def profile_dump(self) -> dict:
        """Folded-stack dump (PROFILE_DUMP 68).  Shape per
        fastdfs_tpu.monitor.decode_profile; StatusError(95) while no
        capture was ever started."""
        self.conn.send_request(TrackerCmd.PROFILE_DUMP)
        return json.loads(self.conn.recv_response("profile_dump") or b"{}")

    def get_tracker_status(self) -> dict:
        """Multi-tracker relationship probe (TRACKER_GET_STATUS 70):
        whether this tracker is the leader and who it believes leads."""
        self.conn.send_request(TrackerCmd.TRACKER_GET_STATUS)
        resp = self.conn.recv_response("get_tracker_status")
        if len(resp) < 1 + IP_ADDRESS_SIZE + 8:
            raise ProtocolError(f"short tracker status: {len(resp)}")
        ip = resp[1:17].rstrip(b"\x00").decode()
        port = buff2long(resp, 17)
        leader = f"{ip}:{port}" if ip and port > 0 else ""
        return {"am_leader": resp[0] == 1, "leader": leader}
