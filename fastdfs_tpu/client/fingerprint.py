"""Client-side chunk fingerprinting for dedup-aware negotiated uploads.

The negotiated upload protocol (UPLOAD_RECIPE / UPLOAD_CHUNKS) moves the
fingerprint work from the storage daemon to the ingest edge: the client
chunks and hashes the payload locally, and only ships chunk bytes the
daemon's content-addressed store has never seen.  Correctness therefore
depends on the client producing the SAME cut points and digests as every
daemon-side path:

- cut points come from the shared gear CDC spec (``ops.gear_cdc``: one
  generated table, 32-byte window, identical greedy selection) — the
  NumPy twin ``chunk_stream_np`` on plain hosts, the JAX/Pallas
  ``chunk_stream`` when a TPU backend is up;
- digests are SHA1 over the raw chunk bytes — ``hashlib`` on plain
  hosts (C speed, no batch to amortize), ``ops.sha1.sha1_batch`` on TPU
  where the batched kernel amortizes the device round-trip.

Like every dedup feature here, this is an optimization layer: a caller
getting ``fingerprint_buffer`` wrong cannot corrupt the store (the
daemon re-verifies SHA1(payload) == digest before admitting any byte).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from fastdfs_tpu.ops import gear_cdc


@dataclass(frozen=True)
class ChunkFingerprint:
    length: int
    digest: bytes  # 20-byte raw SHA1


def _tpu_up() -> bool:
    """True only when JAX is importable AND its default backend is a real
    TPU — a thin client on a CPU host must not pay a JAX import/compile
    just to fingerprint an upload."""
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _digests_tpu(data: bytes, cuts: list[int]) -> list[bytes] | None:
    """Batched SHA1 on the accelerator, bucketed by pow2 chunk length so
    each shape compiles once (the dedup engine's discipline).  None on
    any failure — the caller falls back to hashlib."""
    try:
        import numpy as np

        from fastdfs_tpu.ops.sha1 import sha1_batch

        out: list[bytes | None] = [None] * len(cuts)
        by_bucket: dict[int, list[int]] = {}
        start = 0
        spans = []
        for i, end in enumerate(cuts):
            spans.append((start, end))
            blen = 1
            while blen < end - start:
                blen <<= 1
            by_bucket.setdefault(blen, []).append(i)
            start = end
        for blen, idxs in by_bucket.items():
            batch = np.zeros((len(idxs), blen), dtype=np.uint8)
            lens = np.zeros(len(idxs), dtype=np.int32)
            for row, i in enumerate(idxs):
                s, e = spans[i]
                batch[row, : e - s] = np.frombuffer(data[s:e], dtype=np.uint8)
                lens[row] = e - s
            words = np.asarray(sha1_batch(batch, lens), dtype=np.uint32)
            raw = words.astype(">u4").tobytes()
            for row, i in enumerate(idxs):
                out[i] = raw[row * 20 : row * 20 + 20]
        return out  # type: ignore[return-value]
    except Exception:
        return None


def fingerprint_buffer(
    data: bytes,
    min_size: int = gear_cdc.DEFAULT_MIN_SIZE,
    avg_bits: int = gear_cdc.DEFAULT_AVG_BITS,
    max_size: int = gear_cdc.DEFAULT_MAX_SIZE,
    cdc_policy: int = gear_cdc.CDC_POLICY_DEFAULT,
) -> list[ChunkFingerprint]:
    """CDC-chunk ``data`` and SHA1 each chunk, exactly as the daemons do.

    Returns one :class:`ChunkFingerprint` per chunk, in stream order
    (lengths sum to ``len(data)``).  Empty input -> empty list.

    ``cdc_policy`` must match the target group's policy (the default is
    the frozen ref-identical rule); a client chunking under a different
    policy than the daemon simply gets zero dedup hits — never
    corruption, since the daemon re-verifies every digest.
    """
    if not data:
        return []
    use_tpu = _tpu_up()
    if use_tpu:
        cuts = gear_cdc.chunk_stream(data, min_size, avg_bits, max_size,
                                     cdc_policy=cdc_policy)
    else:
        cuts = gear_cdc.chunk_stream_np(data, min_size, avg_bits, max_size,
                                        cdc_policy=cdc_policy)
    digests = _digests_tpu(data, cuts) if use_tpu else None
    if digests is None:
        digests = []
        start = 0
        for end in cuts:
            digests.append(hashlib.sha1(data[start:end]).digest())
            start = end
    out = []
    start = 0
    for end, dig in zip(cuts, digests):
        out.append(ChunkFingerprint(length=end - start, digest=dig))
        start = end
    return out
