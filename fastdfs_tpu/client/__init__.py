"""Client library (reference: ``client/`` — fdfs_client.h, tracker_client.c,
storage_client.c).  Pure-Python implementation of the binary TCP protocol;
the C++ daemons are the servers."""

from fastdfs_tpu.client.storage_client import StorageClient  # noqa: F401
from fastdfs_tpu.client.tracker_client import TrackerClient  # noqa: F401
from fastdfs_tpu.client.client import FdfsClient  # noqa: F401
