"""Cluster observability: decode the STAT feeds, render `fdfs_monitor`
output, and emit Prometheus text exposition.

Reference: ``client/fdfs_monitor.c`` renders tracker-held per-storage
stat structs; this rebuild gets the same data in one RPC
(``TrackerCmd.SERVER_CLUSTER_STAT`` — tracker role, every group's
capacity, every storage's liveness and named last-beat stat payload)
plus a per-daemon registry dump (``StorageCmd.STAT`` — per-opcode
counters and latency histograms, per-peer sync lag, dedup and recovery
accounting).  The registry JSON shape is the cross-language contract
covered by tests/test_monitor.py's golden check:

    {"counters": {name: int}, "gauges": {name: int},
     "histograms": {name: {"bounds": [...], "counts": [...],
                           "sum": int, "count": int}}}

histogram ``counts`` has ``len(bounds) + 1`` entries, NON-cumulative,
last = overflow; ``bounds`` are inclusive upper bounds.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field

from fastdfs_tpu.common.protocol import (BEAT_STAT_COUNT, BEAT_STAT_FIELDS,
                                         GROUP_NAME_MAX_LEN, buff2long)


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------

def beat_stats(values: list[int]) -> dict[str, int]:
    """Name a beat stat vector (missing tail slots read 0 — the wire
    contract is append-only)."""
    vals = list(values)[:BEAT_STAT_COUNT]
    vals += [0] * (BEAT_STAT_COUNT - len(vals))
    return dict(zip(BEAT_STAT_FIELDS, vals))


def decode_registry(obj: dict) -> dict:
    """Validate and normalize a native stats-registry snapshot.

    Raises ValueError on shape violations so a truncated or foreign
    payload fails loudly instead of rendering garbage.
    """
    if not isinstance(obj, dict):
        raise ValueError(f"registry snapshot must be an object, got {type(obj)}")
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for section in ("counters", "gauges"):
        for name, value in obj.get(section, {}).items():
            if not isinstance(value, int):
                raise ValueError(f"{section}[{name}] is not an int: {value!r}")
            out[section][name] = value
    for name, h in obj.get("histograms", {}).items():
        bounds, counts = h.get("bounds"), h.get("counts")
        if (not isinstance(bounds, list) or not isinstance(counts, list)
                or len(counts) != len(bounds) + 1
                or not all(isinstance(v, int) for v in bounds + counts)
                or not isinstance(h.get("sum"), int)
                or not isinstance(h.get("count"), int)):
            raise ValueError(f"histograms[{name}] malformed: {h!r}")
        if sum(counts) != h["count"]:
            raise ValueError(
                f"histograms[{name}]: bucket sum {sum(counts)} != count "
                f"{h['count']}")
        out["histograms"][name] = {
            "bounds": list(bounds), "counts": list(counts),
            "sum": h["sum"], "count": h["count"],
        }
    return out


@dataclass
class ClusterSnapshot:
    """Everything the monitor shows: the tracker dump plus (best-effort)
    each storage's own registry snapshot keyed by "ip:port"."""
    now: int = 0
    tracker: dict = field(default_factory=dict)
    groups: list = field(default_factory=list)
    storage_stats: dict[str, dict] = field(default_factory=dict)
    storage_errors: dict[str, str] = field(default_factory=dict)


def gather(client, with_storage_stats: bool = True,
           group: str | None = None) -> ClusterSnapshot:
    """Collect a full snapshot via an ``FdfsClient``.

    ``group`` filters server-side (the tracker's 16B group filter), so
    the per-storage STAT round-trips only touch that group's members.
    The STAT calls are best-effort: a dead storage still appears in the
    tracker section (that IS the liveness signal), with the error
    recorded instead of its registry."""
    cs = client.cluster_stat(group)
    snap = ClusterSnapshot(now=cs.get("now", 0),
                           tracker=cs.get("tracker", {}),
                           groups=cs.get("groups", []))
    if not with_storage_stats:
        return snap
    for g in snap.groups:
        for s in g.get("storages", []):
            addr = f"{s['ip']}:{s['port']}"
            try:
                snap.storage_stats[addr] = decode_registry(
                    client.storage_stat(s["ip"], s["port"]))
            except Exception as e:  # noqa: BLE001 — record, keep going
                snap.storage_errors[addr] = f"{type(e).__name__}: {e}"
    return snap


# ---------------------------------------------------------------------------
# flight-recorder decoding (EVENT_DUMP; native/common/eventlog.h)
# ---------------------------------------------------------------------------

_EVENT_SEVERITIES = ("info", "warn", "error")


@dataclass(frozen=True)
class ClusterEvent:
    """One structured flight-recorder event."""
    seq: int
    ts_us: int
    severity: str
    type: str
    key: str
    detail: str
    node: str = ""  # "role addr" of the daemon that recorded it


def decode_events(obj: dict, node: str = "") -> list[ClusterEvent]:
    """Validate and decode one daemon's EVENT_DUMP JSON.

    Raises ValueError on shape violations so a truncated or foreign
    payload fails loudly (same discipline as decode_registry).  Unknown
    extra keys on an event are ignored — the wire contract is
    append-only."""
    if not isinstance(obj, dict) or not isinstance(obj.get("events"), list):
        raise ValueError(f"event dump must have an events list: {obj!r}")
    if node == "":
        node = f"{obj.get('role', '')}:{obj.get('port', '')}"
    out: list[ClusterEvent] = []
    for e in obj["events"]:
        try:
            sev = str(e["severity"])
            if sev not in _EVENT_SEVERITIES:
                raise ValueError(f"unknown severity {sev!r}")
            out.append(ClusterEvent(
                seq=int(e["seq"]), ts_us=int(e["ts_us"]), severity=sev,
                type=str(e["type"]), key=str(e["key"]),
                detail=str(e.get("detail", "")), node=node))
        except (KeyError, TypeError, ValueError) as err:
            raise ValueError(f"malformed event {e!r}: {err}") from None
    return out


# ---------------------------------------------------------------------------
# metrics-history decoding (METRICS_HISTORY; native/common/metrog.h)
# ---------------------------------------------------------------------------

def decode_metrics_history(obj: dict) -> list[dict]:
    """Validate and decode one daemon's METRICS_HISTORY JSON into
    ``[{"ts_us": int, "registry": <decode_registry shape>}, ...]``
    (oldest first — the wire order).

    Each snapshot is a full absolute registry view (the journal's
    on-disk delta encoding never reaches the wire), so every snapshot
    revalidates through decode_registry and the fdfs_top histogram math
    applies between consecutive entries unchanged."""
    if not isinstance(obj, dict) or not isinstance(obj.get("snapshots"), list):
        raise ValueError(f"metrics history must have a snapshots list: "
                         f"{type(obj)}")
    out: list[dict] = []
    for s in obj["snapshots"]:
        if not isinstance(s, dict) or not isinstance(s.get("ts_us"), int):
            raise ValueError(f"malformed snapshot: {s!r}")
        out.append({"ts_us": s["ts_us"], "registry": decode_registry(s)})
    # Wire order is journal APPEND order, which is causally correct even
    # when the daemon's wall clock stepped backwards between ticks (NTP):
    # keep it, don't sort, and don't reject — one odd ts pair must not
    # cost the whole post-mortem window (report_series floors dt anyway).
    return out


# ---------------------------------------------------------------------------
# heat decoding (HEAT_TOP; native/common/heatsketch.h)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class HeatEntry:
    """One hot file-id from a daemon's space-saving sketch.  ``hits`` is
    an overcount bounded by ``err_bound``: the true request count lies
    in [hits - err_bound, hits]."""
    key: str
    hits: int
    err_bound: int
    bytes: int
    err: int
    ops: dict  # op name -> {"count": int, "bytes": int}


def decode_heat(obj: dict) -> list[HeatEntry]:
    """Validate and decode one daemon's HEAT_TOP JSON (entries arrive
    sorted by hits descending; unknown extra keys are ignored — the
    wire contract is append-only)."""
    if not isinstance(obj, dict) or not isinstance(obj.get("entries"), list):
        raise ValueError(f"heat dump must have an entries list: {obj!r}")
    out: list[HeatEntry] = []
    for e in obj["entries"]:
        try:
            ops = {}
            for op, c in dict(e.get("ops", {})).items():
                ops[str(op)] = {"count": int(c["count"]),
                                "bytes": int(c["bytes"])}
            out.append(HeatEntry(
                key=str(e["key"]), hits=int(e["hits"]),
                err_bound=int(e.get("err_bound", 0)),
                bytes=int(e.get("bytes", 0)), err=int(e.get("err", 0)),
                ops=ops))
        except (KeyError, TypeError, ValueError) as err:
            raise ValueError(f"malformed heat entry {e!r}: {err}") from None
    if any(a.hits < b.hits for a, b in zip(out, out[1:])):
        raise ValueError("heat entries not sorted by hits descending")
    return out


# ---------------------------------------------------------------------------
# hot-map decoding (QUERY_HOT_MAP; native/common/heatwire.h).  The wire
# shape is pinned cross-language by the fdfs_codec hot-map golden.
# ---------------------------------------------------------------------------

def decode_hot_map(body: bytes) -> dict:
    """Decode a QUERY_HOT_MAP response body (the elastic-hot-replication
    map, ISSUE 20): 8B BE version + 1B full flag + 8B BE entry count +
    per entry (8B BE key_len + key + 8B BE group count + n x 16B group
    names).  ``full`` False means a delta, where an entry with zero
    groups is a tombstone (the key was demoted).  Raises ValueError on
    shape violations so a truncated payload fails loudly."""
    if len(body) < 17:
        raise ValueError(f"hot-map body too short: {len(body)}")
    version = buff2long(body, 0)
    full = body[8] != 0
    count = buff2long(body, 9)
    off = 17
    entries = []
    for _ in range(count):
        if off + 8 > len(body):
            raise ValueError("truncated hot-map entry")
        key_len = buff2long(body, off)
        off += 8
        if key_len < 0 or off + key_len + 8 > len(body):
            raise ValueError(f"bad hot-map key length {key_len}")
        key = body[off:off + key_len].decode()
        off += key_len
        ngroups = buff2long(body, off)
        off += 8
        if ngroups < 0 or ngroups > (len(body) - off) // GROUP_NAME_MAX_LEN:
            raise ValueError(f"bad hot-map group count {ngroups}")
        groups = []
        for g in range(ngroups):
            p = off + g * GROUP_NAME_MAX_LEN
            groups.append(
                body[p:p + GROUP_NAME_MAX_LEN].rstrip(b"\x00").decode())
        off += ngroups * GROUP_NAME_MAX_LEN
        entries.append({"key": key, "groups": groups})
    return {"version": version, "full": full, "entries": entries}


# ---------------------------------------------------------------------------
# profile decoding (PROFILE_DUMP; native/common/profiler.h).  The wire
# shape is pinned cross-language by the fdfs_codec profile-json golden.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ProfileStack:
    """One folded stack: ``thread;outermost;...;leaf`` with how many
    SIGPROF samples landed there."""
    stack: str
    count: int

    @property
    def thread(self) -> str:
        return self.stack.split(";", 1)[0]


@dataclass(frozen=True)
class ProfileDump:
    role: str            # "storage" | "tracker"
    port: int
    active: bool         # capture still armed at dump time
    hz: int              # as armed (post profile_max_hz clamp)
    duration_s: int
    samples: int         # handler captures (kept + aggregated)
    dropped: int         # slab-overflow drops — nonzero means the
    #                      profile under-represents the busiest window
    overhead_us: int     # cumulative handler wall time
    max_frames: int      # stack truncation depth (deeper frames lost)
    stacks: tuple        # ProfileStack, count-descending


def decode_profile(obj: dict) -> ProfileDump:
    """Validate and decode one daemon's PROFILE_DUMP JSON (stacks arrive
    sorted by count descending; unknown extra keys are ignored — the
    wire contract is append-only)."""
    if not isinstance(obj, dict) or not isinstance(obj.get("stacks"), list):
        raise ValueError(f"profile dump must have a stacks list: {obj!r}")
    rows: list[ProfileStack] = []
    for s in obj["stacks"]:
        try:
            rows.append(ProfileStack(stack=str(s["stack"]),
                                     count=int(s["count"])))
        except (KeyError, TypeError, ValueError) as err:
            raise ValueError(f"malformed profile stack {s!r}: {err}") from None
    if any(a.count < b.count for a, b in zip(rows, rows[1:])):
        raise ValueError("profile stacks not sorted by count descending")
    try:
        return ProfileDump(
            role=str(obj["role"]), port=int(obj["port"]),
            active=bool(obj["active"]), hz=int(obj["hz"]),
            duration_s=int(obj["duration_s"]), samples=int(obj["samples"]),
            dropped=int(obj["dropped"]),
            overhead_us=int(obj.get("overhead_us", 0)),
            max_frames=int(obj.get("max_frames", 0)),
            stacks=tuple(rows))
    except (KeyError, TypeError, ValueError) as err:
        raise ValueError(f"malformed profile dump: {err}") from None


def render_folded(dump: ProfileDump) -> str:
    """Collapsed-stack text: one ``frames count`` line per row, the
    input format of flamegraph.pl and speedscope (OPERATIONS.md
    "Profiling & the thread ledger" has the full recipe)."""
    return "\n".join(f"{s.stack} {s.count}" for s in dump.stacks)


_THREAD_GAUGE_SUFFIXES = (".cpu_pct", ".utime_ms", ".stime_ms")


def thread_ledger(reg: dict) -> list[dict]:
    """Per-thread CPU rows from one registry snapshot's ``thread.*``
    gauges (ThreadRegistry::SampleInto), cpu%-descending then by name.
    Thread names contain dots and slashes (``dio.worker/1``), so parse
    by stripping the known prefix and suffix — never by splitting."""
    rows: dict[str, dict] = {}
    for name, v in reg.get("gauges", {}).items():
        if not name.startswith("thread."):
            continue
        for suffix in _THREAD_GAUGE_SUFFIXES:
            if name.endswith(suffix):
                tname = name[len("thread."):-len(suffix)]
                rows.setdefault(tname, {"name": tname, "cpu_pct": 0,
                                        "utime_ms": 0, "stime_ms": 0})
                rows[tname][suffix[1:]] = v
                break
    return sorted(rows.values(),
                  key=lambda r: (-r["cpu_pct"], r["name"]))


# ---------------------------------------------------------------------------
# gray-failure health decoding (HEALTH_STATUS / HEALTH_MATRIX;
# native/common/healthmon.h + tracker/cluster.cc).  Wire shapes pinned
# cross-language by the fdfs_codec health-status / health-matrix goldens.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class HealthPeerRow:
    """One (peer address, op class) row from a daemon's health table.
    ``score`` is 0..100 (100 = healthy); the peer's composite score is
    the MINIMUM across its op classes."""
    addr: str
    op: str
    score: int
    rpc_ewma_us: int
    error_pct: int
    timeout_pct: int
    ops: int
    errors: int
    timeouts: int
    age_s: int


@dataclass(frozen=True)
class HealthStatus:
    """One daemon's HEALTH_STATUS view: its own gray score (watchdog +
    disk probes) plus its per-peer RPC health table."""
    role: str
    port: int
    score: int           # SelfScore: 0..100
    stalled_threads: int
    probe_read_us: int
    probe_write_us: int
    probe_threshold_ms: int
    peers: tuple         # HealthPeerRow, (addr, op)-sorted


def decode_health_status(obj: dict) -> HealthStatus:
    """Validate and decode one daemon's HEALTH_STATUS JSON (rows arrive
    (addr, op)-sorted; unknown extra keys are ignored — the wire
    contract is append-only)."""
    if not isinstance(obj, dict) or not isinstance(obj.get("peers"), list):
        raise ValueError(f"health status must have a peers list: {obj!r}")
    rows: list[HealthPeerRow] = []
    for p in obj["peers"]:
        try:
            rows.append(HealthPeerRow(
                addr=str(p["addr"]), op=str(p["op"]), score=int(p["score"]),
                rpc_ewma_us=int(p["rpc_ewma_us"]),
                error_pct=int(p["error_pct"]),
                timeout_pct=int(p["timeout_pct"]), ops=int(p["ops"]),
                errors=int(p["errors"]), timeouts=int(p["timeouts"]),
                age_s=int(p["age_s"])))
        except (KeyError, TypeError, ValueError) as err:
            raise ValueError(f"malformed health peer {p!r}: {err}") from None
    if any((a.addr, a.op) > (b.addr, b.op) for a, b in zip(rows, rows[1:])):
        raise ValueError("health peers not (addr, op)-sorted")
    try:
        probe = dict(obj.get("probe", {}))
        return HealthStatus(
            role=str(obj["role"]), port=int(obj["port"]),
            score=int(obj["score"]),
            stalled_threads=int(obj["stalled_threads"]),
            probe_read_us=int(probe.get("read_us", 0)),
            probe_write_us=int(probe.get("write_us", 0)),
            probe_threshold_ms=int(probe.get("threshold_ms", 0)),
            peers=tuple(rows))
    except (KeyError, TypeError, ValueError) as err:
        raise ValueError(f"malformed health status: {err}") from None


_HEALTH_VERDICTS = ("ok", "gray", "sick", "unknown")


@dataclass(frozen=True)
class HealthMatrixNode:
    """One node's row in the tracker's N x N differential matrix:
    what it SAYS about itself (``self_score``, -1 = never reported)
    against what its group peers SAY about it (``peer_avg``, -1 = no
    reports).  ``verdict`` is the tracker's call: a "gray" node claims
    healthy while peers score it under the threshold."""
    group: str
    addr: str
    self_score: int
    peer_avg: int
    reports: int
    verdict: str
    age_s: int
    peers: dict  # addr -> score THIS node reported about its peers


@dataclass(frozen=True)
class HealthMatrix:
    role: str
    port: int
    gray_threshold: int
    nodes: tuple  # HealthMatrixNode


def decode_health_matrix(obj: dict) -> HealthMatrix:
    """Validate and decode the tracker's HEALTH_MATRIX JSON (unknown
    extra keys are ignored — the wire contract is append-only)."""
    if not isinstance(obj, dict) or not isinstance(obj.get("nodes"), list):
        raise ValueError(f"health matrix must have a nodes list: {obj!r}")
    nodes: list[HealthMatrixNode] = []
    for n in obj["nodes"]:
        try:
            verdict = str(n["verdict"])
            if verdict not in _HEALTH_VERDICTS:
                raise ValueError(f"unknown verdict {verdict!r}")
            nodes.append(HealthMatrixNode(
                group=str(n["group"]), addr=str(n["addr"]),
                self_score=int(n["self"]), peer_avg=int(n["peer_avg"]),
                reports=int(n["reports"]), verdict=verdict,
                age_s=int(n["age_s"]),
                peers={str(a): int(s)
                       for a, s in dict(n.get("peers", {})).items()}))
        except (KeyError, TypeError, ValueError) as err:
            raise ValueError(f"malformed matrix node {n!r}: {err}") from None
    try:
        return HealthMatrix(
            role=str(obj["role"]), port=int(obj["port"]),
            gray_threshold=int(obj["gray_threshold"]), nodes=tuple(nodes))
    except (KeyError, TypeError, ValueError) as err:
        raise ValueError(f"malformed health matrix: {err}") from None


# ---------------------------------------------------------------------------
# admission-ladder decoding (ADMISSION_STATUS; native/storage/admission.h).
# Wire shape pinned cross-language by the fdfs_codec admission-json golden.
# ---------------------------------------------------------------------------

# Ladder rung names, index == level (mirror of AdmissionController's
# level_name(); level L sheds every class c with c + L > 4).
ADMISSION_LEVELS = ("admit-all", "shed-background", "shed-bulk", "reads-only")

# Priority-class names, index == class byte (mirror of
# PriorityClassName / protocol.PriorityClass).
PRIORITY_CLASSES = ("control", "interactive", "normal", "bulk", "background")


@dataclass(frozen=True)
class AdmissionStatus:
    """One daemon's ADMISSION_STATUS view: where the shed ladder sits
    right now and what it has refused so far."""
    role: str
    port: int
    enabled: bool
    level: int
    level_name: str
    pressure: float
    ewma: float
    tighten_threshold: float
    relax_threshold: float
    tightens: int
    relaxes: int
    retry_after_ms: int
    admitted: int
    shed: int
    shed_by_class: dict  # class name -> lifetime shed count


def decode_admission(obj: dict) -> AdmissionStatus:
    """Validate and decode one daemon's ADMISSION_STATUS JSON (unknown
    extra keys are ignored — the wire contract is append-only)."""
    if not isinstance(obj, dict):
        raise ValueError(f"admission status must be an object: {obj!r}")
    try:
        level = int(obj["level"])
        name = str(obj["level_name"])
        if not 0 <= level < len(ADMISSION_LEVELS):
            raise ValueError(f"level {level} out of range")
        if name != ADMISSION_LEVELS[level]:
            raise ValueError(f"level_name {name!r} does not match "
                             f"level {level}")
        by_class = {str(k): int(v)
                    for k, v in dict(obj.get("shed_by_class", {})).items()}
        if any(k not in PRIORITY_CLASSES for k in by_class):
            unknown = sorted(set(by_class) - set(PRIORITY_CLASSES))
            raise ValueError(f"unknown shed classes {unknown}")
        return AdmissionStatus(
            role=str(obj["role"]), port=int(obj["port"]),
            enabled=bool(obj["enabled"]), level=level, level_name=name,
            pressure=float(obj["pressure"]), ewma=float(obj["ewma"]),
            tighten_threshold=float(obj["tighten_threshold"]),
            relax_threshold=float(obj["relax_threshold"]),
            tightens=int(obj["tightens"]), relaxes=int(obj["relaxes"]),
            retry_after_ms=int(obj["retry_after_ms"]),
            admitted=int(obj["admitted"]), shed=int(obj["shed"]),
            shed_by_class=by_class)
    except (KeyError, TypeError, ValueError) as err:
        raise ValueError(f"malformed admission status: {err}") from None


# ---------------------------------------------------------------------------
# SLO rule table (mirror of native/common/sloeval.cc; the fdfs_codec
# slo-conf golden pins the two parsers against each other)
# ---------------------------------------------------------------------------

# (name, threshold, clear) — breach when EWMA(reading) > threshold,
# recover when EWMA <= clear.  Must stay field-identical to
# SloEvaluator::DefaultRules().
DEFAULT_SLO_RULES = (
    ("error_rate_pct", 5.0, 2.5),
    ("request_p99_ms", 1000.0, 500.0),
    ("loop_lag_p99_ms", 250.0, 125.0),
    ("dio_wait_p99_ms", 500.0, 250.0),
    ("sync_lag_s", 300.0, 150.0),
    ("scrub_unrepairable", 0.5, 0.25),
    ("disk_fill_pct", 90.0, 85.0),
    ("peer_rpc_p99_ms", 1000.0, 500.0),
    ("probe_write_ms", 1000.0, 500.0),
)

_SLO_TRUE = {"1", "yes", "true", "on"}


def parse_slo_rules(text: str) -> list[tuple[str, float, float, bool]]:
    """conf/slo.conf -> [(name, threshold, clear, enabled)], applying
    ``<rule>_threshold`` / ``<rule>_clear`` / ``<rule>_enabled``
    overrides onto DEFAULT_SLO_RULES exactly like the C++ loader
    (including the proportional clear rescale when only the threshold
    is overridden)."""
    kv: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, sep, value = line.partition("=")
        if sep:
            kv[key.strip()] = value.strip()

    def fget(key: str) -> float | None:
        # strtod semantics, like the C++ loader: parse the longest
        # numeric PREFIX and ignore trailing garbage ("70%" -> 70.0,
        # "300s" -> 300.0) — float() would reject those and silently
        # report the compiled-in default for a threshold the daemon is
        # actually enforcing.
        m = re.match(r"\s*[-+]?(\d+\.?\d*|\.\d+)([eE][-+]?\d+)?",
                     kv.get(key, ""))
        return float(m.group(0)) if m else None

    out = []
    for name, dflt_threshold, dflt_clear in DEFAULT_SLO_RULES:
        threshold = fget(f"{name}_threshold")
        clear = fget(f"{name}_clear")
        if threshold is None:
            threshold = dflt_threshold
            if clear is None:
                clear = dflt_clear
        elif clear is None:
            clear = (threshold * (dflt_clear / dflt_threshold)
                     if dflt_threshold > 0 else dflt_clear)
        if clear > threshold:
            clear = threshold
        flag = kv.get(f"{name}_enabled", "").lower()
        if flag in _SLO_TRUE:
            enabled = True
        elif flag in {"0", "no", "false", "off"}:
            enabled = False
        else:
            enabled = True  # absent or unparseable: the C++ loader's default
        out.append((name, threshold, clear, enabled))
    return out


# ---------------------------------------------------------------------------
# histogram delta quantiles (the fdfs_top math)
# ---------------------------------------------------------------------------

def hist_delta(prev: dict | None, cur: dict) -> dict:
    """Bucket-wise delta of two registry histogram snapshots of the same
    metric — the distribution of observations BETWEEN the two polls.
    prev=None (first poll, or the daemon restarted and counts went
    backwards) returns cur unchanged.  Per-bucket deltas are CLAMPED at
    0: a restart the total-count guard cannot see (more new
    observations than the old lifetime had) must never render negative
    bucket mass."""
    if (prev is None or prev.get("bounds") != cur.get("bounds")
            or prev.get("count", 0) > cur.get("count", 0)):
        return cur
    counts = [max(c - p, 0) for p, c in zip(prev["counts"], cur["counts"])]
    return {
        "bounds": cur["bounds"],
        "counts": counts,
        "sum": max(cur["sum"] - prev["sum"], 0),
        "count": sum(counts),
    }


def hist_quantile(h: dict, q: float) -> float | None:
    """Upper-bound estimate of quantile ``q`` from a (delta) histogram:
    the inclusive upper bound of the bucket the quantile falls in.

    None — rendered as ``-`` — whenever no finite estimate exists: the
    histogram saw no observations, carries no buckets at all, or the
    quantile lands in the overflow bucket (all that is known there is
    "beyond the last bound"; an inf-ish number formatted into a latency
    column misleads more than it informs)."""
    bounds, counts = h.get("bounds"), h.get("counts")
    if not bounds or not counts:
        return None
    total = h.get("count", 0)
    if total <= 0:
        return None
    rank = q * total
    seen = 0
    for bound, cnt in zip(bounds, counts):
        seen += cnt
        if seen >= rank:
            return float(bound)
    return None  # overflow bucket: no finite upper bound exists


# ---------------------------------------------------------------------------
# fdfs_top: sampling, delta rates, rendering
# ---------------------------------------------------------------------------

@dataclass
class NodeSample:
    role: str                    # "tracker" | "storage"
    addr: str                    # "ip:port"
    registry: dict | None = None
    error: str = ""


@dataclass
class TopSample:
    """One fdfs_top poll: every node's registry + the merged new events."""
    ts: float = 0.0
    nodes: dict[str, NodeSample] = field(default_factory=dict)
    events: list[ClusterEvent] = field(default_factory=list)
    cluster: dict = field(default_factory=dict)


def gather_top(client, group: str | None = None,
               seen_seq: dict[str, tuple[int, int]] | None = None
               ) -> TopSample:
    """Poll STAT + EVENT_DUMP across the cluster (trackers from the
    client's config, storages from SERVER_CLUSTER_STAT).  Best-effort
    like gather(): a dead node becomes a row with an error, never an
    exception.  ``seen_seq`` (mutated in place) maps node -> (seq,
    ts_us) of the newest event already consumed, so only NEW events
    land in the sample — with the ts doubling as an incarnation check
    (a restarted daemon's ring reuses low seqs with different
    timestamps)."""
    from fastdfs_tpu.client.storage_client import StorageClient
    from fastdfs_tpu.client.tracker_client import TrackerClient

    if seen_seq is None:
        seen_seq = {}
    out = TopSample(ts=time.time())

    def take_events(node: str, dump: dict) -> None:
        evs = decode_events(dump, node)
        last, last_ts = seen_seq.get(node, (0, 0))
        top = max((e.seq for e in evs), default=0)
        # Restart detection: the ring (1-based process-monotonic seq)
        # dies with the process, so after a restart everything in the
        # dump is new and must NOT be filtered against the dead
        # incarnation's high-water.  Two tells: the top seq regressed,
        # or the event still sitting at our high-water seq carries a
        # different timestamp than the one we consumed there.
        restarted = bool(top) and top < last
        if not restarted and last and last_ts:
            marker = next((e for e in evs if e.seq == last), None)
            restarted = marker is not None and marker.ts_us != last_ts
        if restarted:
            last = 0
        fresh = [e for e in evs if e.seq > last]
        if top:
            newest = max(evs, key=lambda e: e.seq)
            seen_seq[node] = (newest.seq, newest.ts_us)
        out.events.extend(fresh)

    storages: list[tuple[str, int]] = []
    for host, port in client.trackers:
        addr = f"{host}:{port}"
        node = NodeSample(role="tracker", addr=addr)
        try:
            with TrackerClient(host, port, client.timeout) as tc:
                node.registry = decode_registry(tc.stat())
                take_events(f"tracker {addr}", tc.event_dump())
                if not out.cluster:
                    out.cluster = tc.cluster_stat(group)
                    for g in out.cluster.get("groups", []):
                        for s in g.get("storages", []):
                            storages.append((s["ip"], s["port"]))
        except Exception as e:  # noqa: BLE001 — a dead node is a row
            node.error = f"{type(e).__name__}: {e}"
        out.nodes[f"tracker {addr}"] = node
    for ip, port in sorted(set(storages)):
        addr = f"{ip}:{port}"
        node = NodeSample(role="storage", addr=addr)
        try:
            with StorageClient(ip, port, client.timeout) as sc:
                node.registry = decode_registry(sc.stat())
                take_events(f"storage {addr}", sc.event_dump())
        except Exception as e:  # noqa: BLE001
            node.error = f"{type(e).__name__}: {e}"
        out.nodes[f"storage {addr}"] = node
    return out


def _counter_sum(reg: dict, pattern: re.Pattern) -> int:
    return sum(v for name, v in reg["counters"].items()
               if pattern.fullmatch(name))


_OP_COUNT_RE = re.compile(r"op\.\w+\.count")
_OP_ERROR_RE = re.compile(r"op\.\w+\.errors")


def top_rates(prev: TopSample | None, cur: TopSample) -> dict[str, dict]:
    """Per-node delta rates between two polls: ops/s, err/s, MB/s in and
    out, cache hit %, loop-lag p99 and dio queue-wait p99 (µs, from
    histogram deltas), plus instantaneous queue depth and connections.
    With prev=None (first frame) every rate reads 0 — the gauges and
    quantiles of the lifetime histograms still render."""
    dt = max(cur.ts - prev.ts, 1e-3) if prev is not None else None
    out: dict[str, dict] = {}
    for node, s in cur.nodes.items():
        if s.registry is None:
            out[node] = {"error": s.error}
            continue
        reg = s.registry
        p = prev.nodes.get(node) if prev is not None else None
        preg = p.registry if p is not None and p.registry is not None else None

        def counters(r): return r["counters"]
        def gauge(r, name): return r["gauges"].get(name, 0)

        def crate(cur_v: int, prev_v: int) -> float:
            if dt is None or cur_v < prev_v:  # first frame / restart
                return 0.0
            return (cur_v - prev_v) / dt

        if s.role == "tracker":
            ops = counters(reg).get("server.requests", 0)
            errs = counters(reg).get("server.errors", 0)
            pops = counters(preg).get("server.requests", 0) if preg else 0
            perrs = counters(preg).get("server.errors", 0) if preg else 0
            up = down = pup = pdown = 0
            hits = misses = phits = pmisses = 0
        else:
            ops = _counter_sum(reg, _OP_COUNT_RE)
            errs = _counter_sum(reg, _OP_ERROR_RE)
            pops = _counter_sum(preg, _OP_COUNT_RE) if preg else 0
            perrs = _counter_sum(preg, _OP_ERROR_RE) if preg else 0
            up, down = gauge(reg, "store.bytes_uploaded"), gauge(
                reg, "store.bytes_downloaded")
            pup = gauge(preg, "store.bytes_uploaded") if preg else 0
            pdown = gauge(preg, "store.bytes_downloaded") if preg else 0
            hits, misses = gauge(reg, "cache.hits"), gauge(reg, "cache.misses")
            phits = gauge(preg, "cache.hits") if preg else 0
            pmisses = gauge(preg, "cache.misses") if preg else 0

        dh, dm = max(hits - phits, 0), max(misses - pmisses, 0)
        lag = reg["histograms"].get("nio.loop_lag_us")
        dio = reg["histograms"].get("dio.queue_wait_us")
        plag = preg["histograms"].get("nio.loop_lag_us") if preg else None
        pdio = preg["histograms"].get("dio.queue_wait_us") if preg else None
        # Counter reset = daemon restart between polls.  Every delta is
        # clamped at 0 (crate/hist_delta do that), and the row carries an
        # explicit flag so the operator sees WHY its rates read zero —
        # a silently-zero row after a crash looks like "idle", which is
        # the opposite of the truth.
        restarted = preg is not None and (ops < pops or errs < perrs)
        out[node] = {
            "role": s.role,
            "restarted": restarted,
            "ops_s": round(crate(ops, pops), 1),
            "err_s": round(crate(errs, perrs), 1),
            "in_mb_s": round(crate(up, pup) / 1e6, 2),
            "out_mb_s": round(crate(down, pdown) / 1e6, 2),
            "cache_hit_pct": (round(100.0 * dh / (dh + dm), 1)
                              if dh + dm > 0 else None),
            "loop_p99_us": (hist_quantile(hist_delta(plag, lag), 0.99)
                            if lag else None),
            "dio_wait_p99_us": (hist_quantile(hist_delta(pdio, dio), 0.99)
                                if dio else None),
            "dio_depth": reg["gauges"].get("dio.queue_depth"),
            "conns": reg["gauges"].get("nio.conns_active", 0),
            "slo_breaches": reg["gauges"].get("slo.breaches_active", 0),
            # Gray-failure health gauges (healthmon.h PublishGauges).
            # None = this daemon publishes no health (tracker, or a
            # storage predating the health layer) — the HEALTH pane
            # skips it rather than showing a fake 100.
            "health_score": reg["gauges"].get("health.score"),
            "stalled_threads": reg["gauges"].get(
                "watchdog.stalled_threads", 0),
            "worst_peer": _worst_peer_gauge(reg),
            # Admission-ladder gauges (admission.h PublishGauges).
            # None = this daemon predates the admission layer — the
            # ADMISSION pane skips it rather than inventing level 0.
            "admission_level": reg["gauges"].get("admission.level"),
            "shed_s": round(crate(gauge(reg, "admission.shed_total"),
                                  gauge(preg, "admission.shed_total")
                                  if preg else 0), 1),
        }
    return out


def _worst_peer_gauge(reg: dict) -> tuple[str, int] | None:
    """(addr, score) of the lowest-scored peer in this registry's
    ``peer.<addr>.score`` gauge family, or None when the family is
    empty.  Addresses contain dots and colons, so parse by stripping
    the known prefix and suffix — never by splitting."""
    worst: tuple[str, int] | None = None
    for name, v in reg["gauges"].items():
        if not name.startswith("peer.") or not name.endswith(".score"):
            continue
        addr = name[len("peer."):-len(".score")]
        if worst is None or v < worst[1]:
            worst = (addr, v)
    return worst


def _fmt_us(v: float | None) -> str:
    if v is None:
        return "-"
    if v >= 1e6:
        return f"{v / 1e6:.1f}s"
    if v >= 1000:
        return f"{v / 1000:.1f}ms"
    return f"{v:.0f}us"


def render_top(cur: TopSample, rates: dict[str, dict],
               recent_events: list[ClusterEvent],
               max_events: int = 10,
               alerts: dict[str, list[str]] | None = None,
               heat: dict[str, list["HeatEntry"]] | None = None,
               heat_rows: int = 5,
               threads: dict[str, list[dict]] | None = None,
               thread_rows: int = 8,
               hot_map: dict | None = None) -> str:
    """The fdfs_top frame: a per-node saturation table, an ALERTS line
    (active SLO breaches per node), the scrolling recent-events pane,
    with ``heat`` a per-node hot-file pane, and with ``threads`` a
    per-node THREADS pane (the thread ledger, cpu%-descending).  Pure
    string building so tests (and --json consumers) can drive it
    headless."""
    cols = (f"{'node':<32} {'ops/s':>8} {'err/s':>6} {'in MB/s':>8} "
            f"{'out MB/s':>8} {'hit%':>6} {'loop p99':>9} {'dio p99':>9} "
            f"{'depth':>5} {'conns':>5}")
    lines = [time.strftime("fdfs_top  %H:%M:%S", time.localtime(cur.ts)),
             cols, "-" * len(cols)]
    for node, r in rates.items():
        if "error" in r and "role" not in r:
            lines.append(f"{node:<32} DOWN: {r['error']}")
            continue
        hit = "-" if r["cache_hit_pct"] is None else f"{r['cache_hit_pct']}"
        depth = "-" if r["dio_depth"] is None else str(r["dio_depth"])
        # A restarted daemon's rates read 0 by clamping; say why.
        mark = "  RESTARTED" if r.get("restarted") else ""
        lines.append(
            f"{node:<32} {r['ops_s']:>8} {r['err_s']:>6} {r['in_mb_s']:>8} "
            f"{r['out_mb_s']:>8} {hit:>6} {_fmt_us(r['loop_p99_us']):>9} "
            f"{_fmt_us(r['dio_wait_p99_us']):>9} {depth:>5} {r['conns']:>5}"
            f"{mark}")
    # GROUPS line: shown only while a group is draining/retired — the
    # aggregate rebalance progress of the multi-group scale-out story.
    drains = [g for g in (cur.cluster or {}).get("groups", [])
              if g.get("state", "active") != "active"]
    if drains:
        parts = []
        for g in drains:
            moved = pending = errors = done = n = 0
            for s in g.get("storages", []):
                st = beat_stats_from_storage(s)
                moved += st.get("rebalance_files_moved", 0)
                pending += st.get("rebalance_files_pending", 0)
                errors += st.get("rebalance_errors", 0)
                done += 1 if st.get("rebalance_done", 0) else 0
                n += 1
            parts.append(f"{g['name']} {g['state']}: moved={moved} "
                         f"pending={pending} errors={errors} done={done}/{n}")
        lines.append("")
        lines.append("GROUPS: " + "; ".join(parts))
    # ALERTS line: one glance answers "is anything red right now".
    # Event-tracked alerts name their rules; nodes whose breach predates
    # this fdfs_top (no slo.breach event seen, only the gauge) fall back
    # to a count — summed over the NOT-already-named nodes only, so a
    # live alert on one node cannot hide or double-count another's.
    active = [(node, rules) for node, rules in sorted((alerts or {}).items())
              if rules]
    named = {node for node, _ in active}
    breach_gauges = sum(r.get("slo_breaches") or 0
                        for node, r in rates.items()
                        if "role" in r and node not in named)
    parts = [f"{node}: {','.join(rules)}" for node, rules in active]
    if breach_gauges:
        parts.append(f"{breach_gauges} pre-existing breach(es) "
                     "(details in events pane)")
    if parts:
        lines.append("")
        lines.append("ALERTS: " + "; ".join(parts))
    # HEALTH line: the gray-failure glance — each health-publishing
    # node's self score, stalled-thread count, and its worst-scored
    # peer.  Sorted worst-first so the gray node leads the line.
    health = []
    for node, r in rates.items():
        if r.get("health_score") is None:
            continue
        part = f"{node}: self={r['health_score']}"
        if r.get("stalled_threads"):
            part += f" stalled={r['stalled_threads']}"
        if r.get("worst_peer") is not None:
            paddr, pscore = r["worst_peer"]
            part += f" worst-peer={paddr}={pscore}"
        health.append((r["health_score"], part))
    if health:
        lines.append("")
        lines.append("HEALTH: " +
                     "; ".join(p for _, p in sorted(
                         health, key=lambda h: (h[0], h[1]))))
    # ADMISSION line: shown only while some node is actually shedding
    # (level > 0 or a nonzero shed rate) — at admit-all it is noise.
    # Sorted tightest-first so the overloaded node leads the line.
    admission = []
    for node, r in rates.items():
        lvl = r.get("admission_level")
        if lvl is None or (lvl == 0 and not r.get("shed_s")):
            continue
        name = (ADMISSION_LEVELS[lvl] if 0 <= lvl < len(ADMISSION_LEVELS)
                else str(lvl))
        admission.append(
            (-lvl, node, f"{node}: {name} shed/s={r.get('shed_s', 0)}"))
    if admission:
        lines.append("")
        lines.append("ADMISSION: " +
                     "; ".join(p for _, _, p in sorted(admission)))
    # HOT line: the elastic-replication glance — shown only while the
    # tracker's hot map actually publishes entries (a decoded
    # QUERY_HOT_MAP snapshot, monitor.decode_hot_map shape).
    if hot_map and hot_map.get("entries"):
        shown = hot_map["entries"][:3]
        parts = [f"{e['key']}->{','.join(e['groups'])}" for e in shown]
        extra = len(hot_map["entries"]) - len(shown)
        if extra > 0:
            parts.append(f"(+{extra} more)")
        lines.append("")
        lines.append(f"HOT: v{hot_map.get('version', 0)} "
                     f"published={len(hot_map['entries'])}; "
                     + "; ".join(parts))
    lines.append("")
    lines.append(f"recent events (last {max_events}):")
    for e in recent_events[-max_events:]:
        ts = time.strftime("%H:%M:%S", time.localtime(e.ts_us / 1e6))
        lines.append(f"  {ts} {e.severity.upper():<5} [{e.node}] "
                     f"{e.type} {e.key} {e.detail}".rstrip())
    if not recent_events:
        lines.append("  (none)")
    if heat is not None:
        lines.append("")
        lines.append(f"hot files (top {heat_rows} per node, "
                     "hits / err-bound / MB / ops):")
        lines.extend(_heat_table_lines(heat, heat_rows))
    if threads is not None:
        lines.append("")
        lines.append(f"THREADS (top {thread_rows} per node, "
                     "cpu% / user ms / sys ms):")
        lines.extend(_thread_table_lines(threads, thread_rows))
    return "\n".join(lines)


def _heat_table_lines(heat: dict[str, list["HeatEntry"]],
                      heat_rows: int) -> list[str]:
    """Shared per-node hot-file table body — fdfs_top's --heat pane and
    fdfs_report's heat section must render the same HeatEntry data
    identically."""
    lines: list[str] = []
    for node in sorted(heat):
        lines.append(f"  {node}:")
        entries = heat[node][:heat_rows]
        if not entries:
            lines.append("    (none)")
        for he in entries:
            ops = " ".join(f"{op}={c['count']}"
                           for op, c in sorted(he.ops.items())
                           if c["count"] > 0)
            lines.append(f"    {he.hits:>8} ±{he.err_bound:<6} "
                         f"{he.bytes / 1e6:>8.1f}MB  {he.key}  [{ops}]")
    return lines


def _thread_table_lines(threads: dict[str, list[dict]],
                        thread_rows: int) -> list[str]:
    """Per-node thread-ledger table body (rows from thread_ledger),
    shared so fdfs_top's THREADS pane and any report renderer show the
    same numbers identically."""
    lines: list[str] = []
    for node in sorted(threads):
        lines.append(f"  {node}:")
        rows = threads[node][:thread_rows]
        if not rows:
            lines.append("    (none)")
        for r in rows:
            lines.append(f"    {r['cpu_pct']:>4}% {r['utime_ms']:>8}u "
                         f"{r['stime_ms']:>8}s  {r['name']}")
    return lines


# ---------------------------------------------------------------------------
# text rendering (fdfs_monitor.c analogue)
# ---------------------------------------------------------------------------

def render_text(snap: ClusterSnapshot) -> str:
    t = snap.tracker
    lines = [
        f"tracker: leader={t.get('leader', '')!s} "
        f"am_leader={t.get('am_leader', False)} "
        f"groups={t.get('groups', len(snap.groups))}",
        f"group count: {len(snap.groups)}",
    ]
    for g in snap.groups:
        lines.append("")
        state = g.get("state", "active")
        lines.append(
            f"Group: {g['name']}  state={state}  members={g['members']} "
            f"active={g['active']} free={g['free_mb']}MB "
            f"trunk_server={g.get('trunk_server', '') or '-'}")
        for s in g.get("storages", []):
            addr = f"{s['ip']}:{s['port']}"
            st = beat_stats_from_storage(s)
            lines.append(
                f"  {addr} {s.get('status_name', s['status'])} "
                f"beat_age={s.get('beat_age_s', -1)}s "
                f"disk={s['free_mb']}/{s['total_mb']}MB "
                f"upload={st['success_upload']}/{st['total_upload']} "
                f"download={st['success_download']}/{st['total_download']} "
                f"delete={st['success_delete']}/{st['total_delete']} "
                f"dedup_hits={st['dedup_hits']} "
                f"saved={st['dedup_bytes_saved']}B "
                f"wire_saved={st['sync_bytes_saved_wire']}B "
                f"sync_lag={st['sync_lag_s']}s "
                f"recovery={st['recovery_chunks_fetched']}f/"
                f"{st['recovery_chunks_local']}l")
            if state != "active":
                done = " done" if st.get("rebalance_done", 0) else ""
                lines[-1] += (
                    f" rebalance={st.get('rebalance_files_moved', 0)}moved/"
                    f"{st.get('rebalance_files_pending', 0)}pending"
                    f"{done}")
            reg = snap.storage_stats.get(addr)
            if reg is not None:
                ops = []
                for name, v in sorted(reg["counters"].items()):
                    m = re.fullmatch(r"op\.(\w+)\.count", name)
                    if m and v > 0:
                        ops.append(f"{m.group(1)}={v}")
                if ops:
                    lines.append(f"    ops: {' '.join(ops)}")
            err = snap.storage_errors.get(addr)
            if err is not None:
                lines.append(f"    stat error: {err}")
    return "\n".join(lines)


def beat_stats_from_storage(s: dict) -> dict[str, int]:
    """Named beat stats from a cluster_stat storage entry; tolerates both
    the named dict (native tracker) and a raw vector."""
    st = s.get("stats", {})
    if isinstance(st, list):
        return beat_stats(st)
    return {name: int(st.get(name, 0)) for name in BEAT_STAT_FIELDS}


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(raw: str, prefix: str = "fdfs") -> str:
    name = _NAME_RE.sub("_", raw)
    if name and name[0].isdigit():
        name = "_" + name
    return f"{prefix}_{name}"


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _labels(**kv) -> str:
    inner = ",".join(f'{k}="{_esc(v)}"'
                     for k, v in kv.items() if v is not None)
    return "{" + inner + "}" if inner else ""


def to_prometheus(snap: ClusterSnapshot, prefix: str = "fdfs") -> str:
    """Text exposition format (one scrape = one cluster snapshot).

    Beat stats become per-storage series labelled {group,storage};
    registry counters/gauges keep their registry name (sanitized) with a
    {storage} label; registry histograms become standard cumulative
    ``_bucket{le=...}`` series."""
    out: list[str] = []

    def emit(name: str, mtype: str, samples: list[tuple[str, int | float]]):
        out.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            out.append(f"{name}{labels} {value}")

    t = snap.tracker
    emit(f"{prefix}_tracker_is_leader", "gauge",
         [(_labels(leader=t.get("leader", "")),
           1 if t.get("am_leader") else 0)])
    emit(f"{prefix}_group_active_storages", "gauge",
         [(_labels(group=g["name"]), g["active"]) for g in snap.groups])
    emit(f"{prefix}_group_free_mb", "gauge",
         [(_labels(group=g["name"]), g["free_mb"]) for g in snap.groups])

    storages = [(g, s) for g in snap.groups for s in g.get("storages", [])]
    if storages:
        emit(f"{prefix}_storage_status", "gauge",
             [(_labels(group=g["name"], storage=f"{s['ip']}:{s['port']}"),
               s["status"]) for g, s in storages])
        emit(f"{prefix}_storage_beat_age_seconds", "gauge",
             [(_labels(group=g["name"], storage=f"{s['ip']}:{s['port']}"),
               s.get("beat_age_s", -1)) for g, s in storages])
        for fname in BEAT_STAT_FIELDS:
            mtype = "gauge" if fname in _BEAT_GAUGES else "counter"
            emit(f"{prefix}_storage_{fname}", mtype,
                 [(_labels(group=g["name"],
                           storage=f"{s['ip']}:{s['port']}"),
                   beat_stats_from_storage(s)[fname])
                  for g, s in storages])

    # Registry metrics must be grouped BY NAME across storages first: the
    # text format allows exactly one TYPE line per metric name, and the
    # multi-storage case would otherwise repeat it (scrapers reject the
    # whole exposition on a duplicate TYPE line).
    counters: dict[str, list] = {}
    gauges: dict[str, list] = {}
    hists: dict[str, list] = {}
    # peer.<addr>.<metric> health gauges become ONE labeled family per
    # metric ({storage, peer}) instead of one mangled metric name per
    # peer address — the generic sanitizer would mint unbounded metric
    # names as peers churn, which scrapers treat as new series forever.
    peer_rows: dict[str, list] = {}
    _PEER_METRICS = ("score", "rpc_ewma_us", "error_pct", "timeout_pct")
    for addr in sorted(snap.storage_stats):
        reg = snap.storage_stats[addr]
        for name, v in reg["counters"].items():
            counters.setdefault(name, []).append((addr, v))
        for name, v in reg["gauges"].items():
            peered = False
            if name.startswith("peer."):
                for m in _PEER_METRICS:
                    if name.endswith("." + m):
                        peer = name[len("peer."):-len(m) - 1]
                        peer_rows.setdefault(m, []).append((addr, peer, v))
                        peered = True
                        break
            if not peered:
                gauges.setdefault(name, []).append((addr, v))
        for name, h in reg["histograms"].items():
            hists.setdefault(name, []).append((addr, h))
    for name in sorted(counters):
        emit(_metric_name(name, prefix), "counter",
             [(_labels(storage=addr), v) for addr, v in counters[name]])
    for name in sorted(gauges):
        emit(_metric_name(name, prefix), "gauge",
             [(_labels(storage=addr), v) for addr, v in gauges[name]])
    for m in sorted(peer_rows):
        emit(f"{prefix}_peer_{m}", "gauge",
             [(_labels(storage=addr, peer=peer), v)
              for addr, peer, v in peer_rows[m]])
    for name in sorted(hists):
        base = _metric_name(name, prefix)
        out.append(f"# TYPE {base} histogram")
        for addr, h in hists[name]:
            cum = 0
            for bound, cnt in zip(h["bounds"], h["counts"]):
                cum += cnt
                out.append(f'{base}_bucket{_labels(storage=addr, le=bound)} '
                           f"{cum}")
            cum += h["counts"][-1]
            out.append(f'{base}_bucket{_labels(storage=addr, le="+Inf")} '
                       f"{cum}")
            out.append(f"{base}_sum{_labels(storage=addr)} {h['sum']}")
            out.append(f"{base}_count{_labels(storage=addr)} {h['count']}")
    return "\n".join(out) + "\n"


# Beat fields that are levels, not monotonic totals.
_BEAT_GAUGES = frozenset({
    "last_source_update", "connections", "sync_lag_s",
    "rebalance_files_pending", "rebalance_done",
})


# ---------------------------------------------------------------------------
# fdfs_report: retrospective time-series from the metrics journal +
# breach timeline + heat tables (cli.py report)
# ---------------------------------------------------------------------------

@dataclass
class ReportData:
    """Everything one fdfs_report run gathered, per node."""
    since_us: int = 0
    # node -> [{"ts_us", "registry"}, ...] (decode_metrics_history shape)
    history: dict[str, list[dict]] = field(default_factory=dict)
    # node -> [ClusterEvent, ...] (full EVENT_DUMP, slo.* filtered later)
    events: dict[str, list[ClusterEvent]] = field(default_factory=dict)
    # node -> [HeatEntry, ...] (storages only)
    heat: dict[str, list[HeatEntry]] = field(default_factory=dict)
    errors: dict[str, str] = field(default_factory=dict)


def gather_report(client, since_us: int = 0, group: str | None = None,
                  heat_k: int = 0) -> ReportData:
    """Poll METRICS_HISTORY + EVENT_DUMP (+ HEAT_TOP on storages) across
    the cluster.  Best-effort per node (a dead or journal-less node
    becomes an errors entry), so a post-mortem of a half-up cluster
    still reports everything reachable."""
    from fastdfs_tpu.client.conn import StatusError
    from fastdfs_tpu.client.storage_client import StorageClient
    from fastdfs_tpu.client.tracker_client import TrackerClient

    out = ReportData(since_us=since_us)
    storages: list[tuple[str, int]] = []
    for host, port in client.trackers:
        node = f"tracker {host}:{port}"
        try:
            with TrackerClient(host, port, client.timeout) as tc:
                if not storages:
                    cs = tc.cluster_stat(group)
                    for g in cs.get("groups", []):
                        for s in g.get("storages", []):
                            storages.append((s["ip"], s["port"]))
                out.history[node] = decode_metrics_history(
                    tc.metrics_history(since_us))
                out.events[node] = decode_events(tc.event_dump(), node)
        except StatusError as e:
            out.errors[node] = ("no metrics journal (ENOTSUP)"
                                if e.status == 95 else str(e))
        except Exception as e:  # noqa: BLE001 — a dead node is a row
            out.errors[node] = f"{type(e).__name__}: {e}"
    for ip, port in sorted(set(storages)):
        node = f"storage {ip}:{port}"
        try:
            with StorageClient(ip, port, client.timeout) as sc:
                out.history[node] = decode_metrics_history(
                    sc.metrics_history(since_us))
                out.events[node] = decode_events(sc.event_dump(), node)
                try:
                    out.heat[node] = decode_heat(sc.heat_top(heat_k))
                except StatusError as e:
                    if e.status != 95:  # heat off is fine, else surface
                        raise
        except StatusError as e:
            out.errors[node] = ("no metrics journal (ENOTSUP)"
                                if e.status == 95 else str(e))
        except Exception as e:  # noqa: BLE001
            out.errors[node] = f"{type(e).__name__}: {e}"
    return out


_OP_LATENCY_RE = re.compile(r"op\.\w+\.latency_us")


def report_series(history: list[dict]) -> list[dict]:
    """Per-interval derived rows from one node's journal window: for
    each consecutive snapshot pair, the interval's ops/s, err/s, MB/s
    in/out, request p99, loop-lag p99 and dio-wait p99 (same delta math
    as fdfs_top, applied retrospectively).  Counter resets inside the
    window (the daemon restarted mid-journal) clamp to zero-rate rows
    flagged ``restarted`` rather than rendering garbage."""
    rows: list[dict] = []
    for prev, cur in zip(history, history[1:]):
        preg, reg = prev["registry"], cur["registry"]
        dt = max((cur["ts_us"] - prev["ts_us"]) / 1e6, 1e-3)

        ops = _counter_sum(reg, _OP_COUNT_RE) + reg["counters"].get(
            "server.requests", 0)
        pops = _counter_sum(preg, _OP_COUNT_RE) + preg["counters"].get(
            "server.requests", 0)
        errs = _counter_sum(reg, _OP_ERROR_RE) + reg["counters"].get(
            "server.errors", 0)
        perrs = _counter_sum(preg, _OP_ERROR_RE) + preg["counters"].get(
            "server.errors", 0)
        up = reg["gauges"].get("store.bytes_uploaded", 0)
        pup = preg["gauges"].get("store.bytes_uploaded", 0)
        down = reg["gauges"].get("store.bytes_downloaded", 0)
        pdown = preg["gauges"].get("store.bytes_downloaded", 0)
        restarted = ops < pops or errs < perrs

        def rate(c, p):
            return 0.0 if restarted or c < p else (c - p) / dt

        # Merged per-op latency delta (all op histograms share bounds).
        merged = None
        for name, h in reg["histograms"].items():
            if not (_OP_LATENCY_RE.fullmatch(name)
                    or name == "server.request_us"):
                continue
            d = hist_delta(preg["histograms"].get(name), h)
            if merged is None:
                merged = {"bounds": list(d["bounds"]),
                          "counts": list(d["counts"]),
                          "sum": d["sum"], "count": d["count"]}
            elif merged["bounds"] == d["bounds"]:
                merged["counts"] = [a + b for a, b in
                                    zip(merged["counts"], d["counts"])]
                merged["sum"] += d["sum"]
                merged["count"] += d["count"]

        def p99(name):
            h = reg["histograms"].get(name)
            if h is None:
                return None
            return hist_quantile(
                hist_delta(preg["histograms"].get(name), h), 0.99)

        rows.append({
            "ts_us": cur["ts_us"],
            "dt_s": round(dt, 3),
            "restarted": restarted,
            "ops_s": round(rate(ops, pops), 1),
            "err_s": round(rate(errs, perrs), 1),
            "in_mb_s": round(rate(up, pup) / 1e6, 2),
            "out_mb_s": round(rate(down, pdown) / 1e6, 2),
            "req_p99_us": (hist_quantile(merged, 0.99)
                           if merged is not None else None),
            "loop_p99_us": p99("nio.loop_lag_us"),
            "dio_wait_p99_us": p99("dio.queue_wait_us"),
            "slo_breaches": reg["gauges"].get("slo.breaches_active", 0),
        })
    return rows


def breach_timeline(events: dict[str, list[ClusterEvent]],
                    since_us: int = 0,
                    history: dict[str, list[dict]] | None = None
                    ) -> list[ClusterEvent]:
    """Every slo.breach / slo.recovered event across the cluster, time
    ordered — the report's alert timeline.

    The flight-recorder ring is RAM: a kill -9 takes its events with
    it.  The journal survives, and it carries the slo.breaches_active
    gauge per tick — so for any window OLDER than a node's oldest live
    event (crash, restart, or ring wrap), breach/recovery transitions
    are reconstructed from consecutive journal snapshots and appear as
    synthesized entries (key ``breaches_active``, detail
    ``source=journal``).  Live ring events always win inside their own
    coverage window — they carry the rule name and readings."""
    out = [e for evs in events.values() for e in evs
           if e.type in ("slo.breach", "slo.recovered")
           and e.ts_us >= since_us]
    for node, hist in (history or {}).items():
        live = events.get(node, [])
        ring_start = min((e.ts_us for e in live), default=float("inf"))
        for prev, cur in zip(hist, hist[1:]):
            if cur["ts_us"] >= ring_start:
                break  # the live ring covers it from here on
            was = prev["registry"]["gauges"].get("slo.breaches_active", 0)
            now = cur["registry"]["gauges"].get("slo.breaches_active", 0)
            if now == was or cur["ts_us"] < since_us:
                continue
            out.append(ClusterEvent(
                seq=0, ts_us=cur["ts_us"],
                severity="error" if now > was else "info",
                type="slo.breach" if now > was else "slo.recovered",
                key="breaches_active",
                detail=f"source=journal active={now}", node=node))
    return sorted(out, key=lambda e: (e.ts_us, e.node, e.seq))


def render_report(data: ReportData, max_rows: int = 12,
                  heat_rows: int = 5) -> str:
    """The fdfs_report text: per-node rate/latency time-series over the
    journal window (last ``max_rows`` intervals), the SLO breach
    timeline, and the per-node heat tables."""
    lines: list[str] = []
    for node in sorted(data.history):
        rows = report_series(data.history[node])
        lines.append(f"== {node}  ({len(data.history[node])} snapshots, "
                     f"{len(rows)} intervals)")
        if not rows:
            lines.append("   (not enough history for rates)")
            continue
        cols = (f"   {'time':<8} {'ops/s':>8} {'err/s':>6} {'in MB/s':>8} "
                f"{'out MB/s':>8} {'req p99':>9} {'loop p99':>9} "
                f"{'dio p99':>9} {'slo':>4}")
        lines.append(cols)
        for r in rows[-max_rows:]:
            ts = time.strftime("%H:%M:%S", time.localtime(r["ts_us"] / 1e6))
            mark = " RESTARTED" if r["restarted"] else ""
            lines.append(
                f"   {ts:<8} {r['ops_s']:>8} {r['err_s']:>6} "
                f"{r['in_mb_s']:>8} {r['out_mb_s']:>8} "
                f"{_fmt_us(r['req_p99_us']):>9} "
                f"{_fmt_us(r['loop_p99_us']):>9} "
                f"{_fmt_us(r['dio_wait_p99_us']):>9} "
                f"{r['slo_breaches']:>4}{mark}")
    lines.append("")
    lines.append("SLO breach timeline:")
    timeline = breach_timeline(data.events, data.since_us, data.history)
    if not timeline:
        lines.append("  (no breaches in the window)")
    for e in timeline:
        ts = time.strftime("%H:%M:%S", time.localtime(e.ts_us / 1e6))
        lines.append(f"  {ts} {e.severity.upper():<5} [{e.node}] "
                     f"{e.type} {e.key} {e.detail}".rstrip())
    if data.heat:
        lines.append("")
        lines.append(f"hot files (top {heat_rows} per node, "
                     "hits / err-bound / MB / ops):")
        lines.extend(_heat_table_lines(data.heat, heat_rows))
    for node, err in sorted(data.errors.items()):
        lines.append(f"{node}  error: {err}")
    return "\n".join(lines)
