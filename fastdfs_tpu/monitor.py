"""Cluster observability: decode the STAT feeds, render `fdfs_monitor`
output, and emit Prometheus text exposition.

Reference: ``client/fdfs_monitor.c`` renders tracker-held per-storage
stat structs; this rebuild gets the same data in one RPC
(``TrackerCmd.SERVER_CLUSTER_STAT`` — tracker role, every group's
capacity, every storage's liveness and named last-beat stat payload)
plus a per-daemon registry dump (``StorageCmd.STAT`` — per-opcode
counters and latency histograms, per-peer sync lag, dedup and recovery
accounting).  The registry JSON shape is the cross-language contract
covered by tests/test_monitor.py's golden check:

    {"counters": {name: int}, "gauges": {name: int},
     "histograms": {name: {"bounds": [...], "counts": [...],
                           "sum": int, "count": int}}}

histogram ``counts`` has ``len(bounds) + 1`` entries, NON-cumulative,
last = overflow; ``bounds`` are inclusive upper bounds.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from fastdfs_tpu.common.protocol import BEAT_STAT_COUNT, BEAT_STAT_FIELDS


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------

def beat_stats(values: list[int]) -> dict[str, int]:
    """Name a beat stat vector (missing tail slots read 0 — the wire
    contract is append-only)."""
    vals = list(values)[:BEAT_STAT_COUNT]
    vals += [0] * (BEAT_STAT_COUNT - len(vals))
    return dict(zip(BEAT_STAT_FIELDS, vals))


def decode_registry(obj: dict) -> dict:
    """Validate and normalize a native stats-registry snapshot.

    Raises ValueError on shape violations so a truncated or foreign
    payload fails loudly instead of rendering garbage.
    """
    if not isinstance(obj, dict):
        raise ValueError(f"registry snapshot must be an object, got {type(obj)}")
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for section in ("counters", "gauges"):
        for name, value in obj.get(section, {}).items():
            if not isinstance(value, int):
                raise ValueError(f"{section}[{name}] is not an int: {value!r}")
            out[section][name] = value
    for name, h in obj.get("histograms", {}).items():
        bounds, counts = h.get("bounds"), h.get("counts")
        if (not isinstance(bounds, list) or not isinstance(counts, list)
                or len(counts) != len(bounds) + 1
                or not all(isinstance(v, int) for v in bounds + counts)
                or not isinstance(h.get("sum"), int)
                or not isinstance(h.get("count"), int)):
            raise ValueError(f"histograms[{name}] malformed: {h!r}")
        if sum(counts) != h["count"]:
            raise ValueError(
                f"histograms[{name}]: bucket sum {sum(counts)} != count "
                f"{h['count']}")
        out["histograms"][name] = {
            "bounds": list(bounds), "counts": list(counts),
            "sum": h["sum"], "count": h["count"],
        }
    return out


@dataclass
class ClusterSnapshot:
    """Everything the monitor shows: the tracker dump plus (best-effort)
    each storage's own registry snapshot keyed by "ip:port"."""
    now: int = 0
    tracker: dict = field(default_factory=dict)
    groups: list = field(default_factory=list)
    storage_stats: dict[str, dict] = field(default_factory=dict)
    storage_errors: dict[str, str] = field(default_factory=dict)


def gather(client, with_storage_stats: bool = True,
           group: str | None = None) -> ClusterSnapshot:
    """Collect a full snapshot via an ``FdfsClient``.

    ``group`` filters server-side (the tracker's 16B group filter), so
    the per-storage STAT round-trips only touch that group's members.
    The STAT calls are best-effort: a dead storage still appears in the
    tracker section (that IS the liveness signal), with the error
    recorded instead of its registry."""
    cs = client.cluster_stat(group)
    snap = ClusterSnapshot(now=cs.get("now", 0),
                           tracker=cs.get("tracker", {}),
                           groups=cs.get("groups", []))
    if not with_storage_stats:
        return snap
    for g in snap.groups:
        for s in g.get("storages", []):
            addr = f"{s['ip']}:{s['port']}"
            try:
                snap.storage_stats[addr] = decode_registry(
                    client.storage_stat(s["ip"], s["port"]))
            except Exception as e:  # noqa: BLE001 — record, keep going
                snap.storage_errors[addr] = f"{type(e).__name__}: {e}"
    return snap


# ---------------------------------------------------------------------------
# text rendering (fdfs_monitor.c analogue)
# ---------------------------------------------------------------------------

def render_text(snap: ClusterSnapshot) -> str:
    t = snap.tracker
    lines = [
        f"tracker: leader={t.get('leader', '')!s} "
        f"am_leader={t.get('am_leader', False)} "
        f"groups={t.get('groups', len(snap.groups))}",
        f"group count: {len(snap.groups)}",
    ]
    for g in snap.groups:
        lines.append("")
        lines.append(
            f"Group: {g['name']}  members={g['members']} "
            f"active={g['active']} free={g['free_mb']}MB "
            f"trunk_server={g.get('trunk_server', '') or '-'}")
        for s in g.get("storages", []):
            addr = f"{s['ip']}:{s['port']}"
            st = beat_stats_from_storage(s)
            lines.append(
                f"  {addr} {s.get('status_name', s['status'])} "
                f"beat_age={s.get('beat_age_s', -1)}s "
                f"disk={s['free_mb']}/{s['total_mb']}MB "
                f"upload={st['success_upload']}/{st['total_upload']} "
                f"download={st['success_download']}/{st['total_download']} "
                f"delete={st['success_delete']}/{st['total_delete']} "
                f"dedup_hits={st['dedup_hits']} "
                f"saved={st['dedup_bytes_saved']}B "
                f"wire_saved={st['sync_bytes_saved_wire']}B "
                f"sync_lag={st['sync_lag_s']}s "
                f"recovery={st['recovery_chunks_fetched']}f/"
                f"{st['recovery_chunks_local']}l")
            reg = snap.storage_stats.get(addr)
            if reg is not None:
                ops = []
                for name, v in sorted(reg["counters"].items()):
                    m = re.fullmatch(r"op\.(\w+)\.count", name)
                    if m and v > 0:
                        ops.append(f"{m.group(1)}={v}")
                if ops:
                    lines.append(f"    ops: {' '.join(ops)}")
            err = snap.storage_errors.get(addr)
            if err is not None:
                lines.append(f"    stat error: {err}")
    return "\n".join(lines)


def beat_stats_from_storage(s: dict) -> dict[str, int]:
    """Named beat stats from a cluster_stat storage entry; tolerates both
    the named dict (native tracker) and a raw vector."""
    st = s.get("stats", {})
    if isinstance(st, list):
        return beat_stats(st)
    return {name: int(st.get(name, 0)) for name in BEAT_STAT_FIELDS}


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(raw: str, prefix: str = "fdfs") -> str:
    name = _NAME_RE.sub("_", raw)
    if name and name[0].isdigit():
        name = "_" + name
    return f"{prefix}_{name}"


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _labels(**kv) -> str:
    inner = ",".join(f'{k}="{_esc(v)}"'
                     for k, v in kv.items() if v is not None)
    return "{" + inner + "}" if inner else ""


def to_prometheus(snap: ClusterSnapshot, prefix: str = "fdfs") -> str:
    """Text exposition format (one scrape = one cluster snapshot).

    Beat stats become per-storage series labelled {group,storage};
    registry counters/gauges keep their registry name (sanitized) with a
    {storage} label; registry histograms become standard cumulative
    ``_bucket{le=...}`` series."""
    out: list[str] = []

    def emit(name: str, mtype: str, samples: list[tuple[str, int | float]]):
        out.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            out.append(f"{name}{labels} {value}")

    t = snap.tracker
    emit(f"{prefix}_tracker_is_leader", "gauge",
         [(_labels(leader=t.get("leader", "")),
           1 if t.get("am_leader") else 0)])
    emit(f"{prefix}_group_active_storages", "gauge",
         [(_labels(group=g["name"]), g["active"]) for g in snap.groups])
    emit(f"{prefix}_group_free_mb", "gauge",
         [(_labels(group=g["name"]), g["free_mb"]) for g in snap.groups])

    storages = [(g, s) for g in snap.groups for s in g.get("storages", [])]
    if storages:
        emit(f"{prefix}_storage_status", "gauge",
             [(_labels(group=g["name"], storage=f"{s['ip']}:{s['port']}"),
               s["status"]) for g, s in storages])
        emit(f"{prefix}_storage_beat_age_seconds", "gauge",
             [(_labels(group=g["name"], storage=f"{s['ip']}:{s['port']}"),
               s.get("beat_age_s", -1)) for g, s in storages])
        for fname in BEAT_STAT_FIELDS:
            mtype = "gauge" if fname in _BEAT_GAUGES else "counter"
            emit(f"{prefix}_storage_{fname}", mtype,
                 [(_labels(group=g["name"],
                           storage=f"{s['ip']}:{s['port']}"),
                   beat_stats_from_storage(s)[fname])
                  for g, s in storages])

    # Registry metrics must be grouped BY NAME across storages first: the
    # text format allows exactly one TYPE line per metric name, and the
    # multi-storage case would otherwise repeat it (scrapers reject the
    # whole exposition on a duplicate TYPE line).
    counters: dict[str, list] = {}
    gauges: dict[str, list] = {}
    hists: dict[str, list] = {}
    for addr in sorted(snap.storage_stats):
        reg = snap.storage_stats[addr]
        for name, v in reg["counters"].items():
            counters.setdefault(name, []).append((addr, v))
        for name, v in reg["gauges"].items():
            gauges.setdefault(name, []).append((addr, v))
        for name, h in reg["histograms"].items():
            hists.setdefault(name, []).append((addr, h))
    for name in sorted(counters):
        emit(_metric_name(name, prefix), "counter",
             [(_labels(storage=addr), v) for addr, v in counters[name]])
    for name in sorted(gauges):
        emit(_metric_name(name, prefix), "gauge",
             [(_labels(storage=addr), v) for addr, v in gauges[name]])
    for name in sorted(hists):
        base = _metric_name(name, prefix)
        out.append(f"# TYPE {base} histogram")
        for addr, h in hists[name]:
            cum = 0
            for bound, cnt in zip(h["bounds"], h["counts"]):
                cum += cnt
                out.append(f'{base}_bucket{_labels(storage=addr, le=bound)} '
                           f"{cum}")
            cum += h["counts"][-1]
            out.append(f'{base}_bucket{_labels(storage=addr, le="+Inf")} '
                       f"{cum}")
            out.append(f"{base}_sum{_labels(storage=addr)} {h['sum']}")
            out.append(f"{base}_count{_labels(storage=addr)} {h['count']}")
    return "\n".join(out) + "\n"


# Beat fields that are levels, not monotonic totals.
_BEAT_GAUGES = frozenset({
    "last_source_update", "connections", "sync_lag_s",
})
