"""Dedup sidecar: the TPU fingerprint engine behind the storage daemon.

This is the server half of the daemon's ``dedup_mode = sidecar`` plugin
(C++ client: ``native/storage/dedup.cc:SidecarDedup``): a unix-socket
service speaking the standard 10-byte framing with the DEDUP_* opcodes.
The storage daemon streams each chunk-eligible upload through cmd 120 and
writes only the chunks its content-addressed store has never seen — this
process supplies the cut-points and digests, computed by the JAX/TPU
pipeline (position-parallel gear CDC + batched SHA1; the replacement for
the scalar CRC32 loop in the reference's
``storage/storage_dio.c:dio_write_file()``).

Opcodes
-------
* ``DEDUP_FINGERPRINT`` (120): body = 8B BE session id + 8B BE
  base_offset + raw segment bytes.  Response: 8B BE chunk count, then
  per chunk 8B BE offset + 8B BE length + 20B raw SHA1.  The session id
  (minted by the daemon per upload — ``SidecarDedup::BeginChunked``)
  scopes ALL pending state: the accumulated file signature and the
  per-chunk digest attributions stay buffered under the session until
  commit/abort, so concurrent uploads cannot interleave and nothing
  provisional ever reaches the indexes or their snapshots.
* ``DEDUP_QUERY`` (121): body = 40-hex whole-file SHA1.  Response: the
  canonical file id if known (whole-file dedup for sub-threshold files).
* ``DEDUP_COMMIT`` (122): text body, one of
  ``commitfile <sha1hex> <file_id>`` |
  ``commitchunks <session> <file_id>`` | ``abort <session>`` |
  ``forget <file_id>`` | ``stats``.  ``abort`` is sent on flat-fallback
  or a failed upload; sessions older than ``_SESSION_TTL`` seconds are
  reaped in case a daemon dies without either message.  ``stats``
  returns the service counters as JSON (fingerprint_bytes, chunks,
  requests, lock_wait_us, engine_us) — the bench harness reads it to
  price the engine serialization.
* ``DEDUP_NEARDUPS`` (123): body = file id text.  Response: ranked text
  lines ``<file_id> <score>`` from the MinHash/LSH index (the operator
  query surface behind the daemon's ``NEAR_DUPS`` command); status 61
  when the file carries no signature.
* ``DEDUP_VERIFY`` (136): batched chunk-integrity verify for the storage
  scrubber (``native/storage/scrub.cc``).  Body = 8B count + per chunk
  (8B length + 20B expected raw SHA1) + payloads concatenated; response
  = count bytes (0 = match, 1 = mismatch).  Hashing runs on the
  accelerator via ``ops/sha1.sha1_batch``; the daemon falls back to its
  serial host SHA1 when this RPC is unavailable.
* ``DEDUP_FINGERPRINT_CUTS`` (125): DEDUP_FINGERPRINT with the cut
  offsets precomputed by the caller's native CDC (8B session + 8B
  base_offset + 8B n_cuts + n_cuts x 8B ends + bytes) — the production
  daemon path: chunking stays on the CPU (AVX2, identical cut points),
  the accelerator round-trip only carries the hash work.

State: whole-file digest map + the DedupEngine's exact/LSH indexes;
snapshotted to ``<state_dir>/sidecar_*.json`` on SIGTERM and every
``--snapshot-interval`` seconds.

Run: ``python -m fastdfs_tpu.sidecar --socket /path/dedup.sock``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import struct
import sys
import threading
import time

import numpy as np

from fastdfs_tpu.common.protocol import HEADER_SIZE, StorageCmd, unpack_header
from fastdfs_tpu.dedup.engine import DedupConfig, DedupEngine

_I64 = struct.Struct(">q")

_SESSION_TTL = 600.0  # seconds before an uncommitted session is reaped


class _Session:
    """Pending per-upload state: accumulated file signature + the digest
    attributions to insert (with the real file id) at commit time."""

    __slots__ = ("sig", "digests", "touched")

    def __init__(self) -> None:
        self.sig: np.ndarray | None = None
        self.digests: list[tuple[bytes, int]] = []  # (raw digest, offset)
        self.touched = time.monotonic()


def _pack_header(pkg_len: int, cmd: int, status: int = 0) -> bytes:
    return struct.pack(">qBB", pkg_len, cmd, status)


def _parse_session(token: str) -> int:
    try:
        return int(token)
    except ValueError:
        return -1


class DedupSidecar:
    """Unix-socket dedup service around a :class:`DedupEngine`.

    One engine (and one TPU context) serves every daemon connection;
    engine calls are serialized under a lock — batching happens inside
    the engine's bucketed jit calls, not across requests.
    """

    def __init__(self, socket_path: str, state_dir: str | None = None,
                 config: DedupConfig | None = None) -> None:
        self.socket_path = socket_path
        self.state_dir = state_dir
        self.engine = DedupEngine(config)
        self.files: dict[str, str] = {}       # whole-file sha1 -> file id
        self.by_file: dict[str, str] = {}     # file id -> sha1
        self._sessions: dict[int, _Session] = {}  # session id -> pending
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener: socket.socket | None = None
        # RSS watchdog (see _housekeeping_loop): 0 disables; restart_argv
        # is the CLI argv to re-exec with.
        self.max_rss_mb: int = 0
        self.restart_argv: list[str] = []
        self._started = time.monotonic()
        # lock_wait_us / engine_us price the one-engine-serialization
        # design: lock_wait is time requests spent queued on _lock,
        # engine is time actually inside engine.fingerprint.  Read via
        # the `stats` commit subcommand (bench stage attribution).
        self.stats = {"fingerprint_bytes": 0, "chunks": 0, "requests": 0,
                      "lock_wait_us": 0, "engine_us": 0}
        if state_dir:
            self._load_state()

    # -- state -------------------------------------------------------------

    def _state_paths(self) -> tuple[str, str, str]:
        d = self.state_dir or "."
        return (os.path.join(d, "sidecar_files.json"),
                os.path.join(d, "sidecar_exact.npz"),
                os.path.join(d, "sidecar_near.npz"))

    def _load_state(self) -> None:
        from fastdfs_tpu.ops.gear_cdc import CDC_SPEC_VERSION

        files_p, exact_p, near_p = self._state_paths()
        if os.path.exists(files_p):
            with open(files_p) as fh:
                blob = json.load(fh)
            # Current format: {"cdc_spec": N, "cdc_policy": P,
            # "files": {...}}; round-4 snapshots were the flat files dict
            # (spec 1 implicitly); pre-round-13 ones carry no policy
            # field (policy 1 implicitly).
            if isinstance(blob, dict) and "files" in blob:
                spec = int(blob.get("cdc_spec", 1))
                policy = int(blob.get("cdc_policy", 1))
                files = blob["files"]
            else:
                spec, policy, files = 1, 1, blob
            if spec != CDC_SPEC_VERSION:
                # Stale chunker spec: the same bytes now chunk at
                # different offsets, so every stored chunk digest would
                # miss — discard ALL dedup state (cold restart; recipes
                # and reads are unaffected) instead of silently serving
                # a dead index.
                print(f"dedup sidecar: discarding snapshot built with "
                      f"chunker spec v{spec} (current v{CDC_SPEC_VERSION})",
                      flush=True)
                return
            if policy != self.engine.config.cdc_policy:
                # Same rule for the cut-selection policy: default and
                # skip-min cuts are different content-address namespaces,
                # so an index built under one is dead weight (and silent
                # ~0% dedup) under the other.
                print(f"dedup sidecar: discarding snapshot built with "
                      f"cdc_policy {policy} (engine runs policy "
                      f"{self.engine.config.cdc_policy})", flush=True)
                return
            self.files = files
            self.by_file = {v: k for k, v in self.files.items()}
        elif os.path.exists(exact_p) or os.path.exists(near_p):
            # Index snapshots without the files/spec record: unknown
            # chunker spec — same discard rule.
            print("dedup sidecar: discarding index snapshots with no "
                  "chunker-spec record", flush=True)
            return
        if os.path.exists(exact_p) and os.path.exists(near_p):
            try:
                self.engine = DedupEngine.load(exact_p, near_p,
                                               self.engine.config)
            except Exception as e:
                # A stale-spec, truncated, or otherwise unreadable
                # snapshot must not brick the sidecar (which would
                # fail-open EVERY upload to flat storage): keep whatever
                # exact state loads, restart the near index.
                print(f"dedup sidecar: dropping near-dup snapshot ({e}); "
                      "exact dedup state retained", flush=True)
                from fastdfs_tpu.dedup.index import ExactDigestIndex
                fresh = DedupEngine(self.engine.config)
                try:
                    fresh.exact = ExactDigestIndex.load(exact_p)
                except Exception:
                    pass
                self.engine = fresh

    def save_state(self) -> None:
        from fastdfs_tpu.ops.gear_cdc import CDC_SPEC_VERSION

        if not self.state_dir:
            return
        files_p, exact_p, near_p = self._state_paths()
        with self._lock:
            tmp = files_p + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"cdc_spec": CDC_SPEC_VERSION,
                           "cdc_policy": self.engine.config.cdc_policy,
                           "files": self.files}, fh)
            os.replace(tmp, files_p)
            self.engine.save(exact_p, near_p)

    # -- request handlers --------------------------------------------------

    def _fingerprint(self, body: bytes, with_cuts: bool = False
                     ) -> tuple[int, bytes]:
        if len(body) < 16:
            return 22, b""
        session_id = _I64.unpack_from(body)[0]
        base_offset = _I64.unpack_from(body, 8)[0]
        cuts = None
        if with_cuts:
            # DEDUP_FINGERPRINT_CUTS: the daemon already ran the
            # (identical) native CDC; body carries the cut offsets.
            if len(body) < 24:
                return 22, b""
            n_cuts = _I64.unpack_from(body, 16)[0]
            if n_cuts < 0 or 24 + 8 * n_cuts > len(body):
                return 22, b""
            cuts = [_I64.unpack_from(body, 24 + 8 * i)[0]
                    for i in range(n_cuts)]
            data = body[24 + 8 * n_cuts:]
            # Cuts must exactly cover the payload: an empty cut list
            # with data would "succeed" with zero chunks and a recipe
            # covering none of the bytes.
            if data:
                if (not cuts or cuts[-1] != len(data)
                        or any(c <= p for p, c in zip([0] + cuts, cuts))):
                    return 22, b""
            elif cuts:
                return 22, b""
        else:
            data = body[16:]
        # Pure compute OUTSIDE the lock: engine.fingerprint touches no
        # index state (its docstring is the contract), and JAX dispatch
        # is thread-safe — so concurrent daemon uploads overlap their
        # device round-trips instead of queueing behind one global lock.
        # Only session/stats/index mutation is serialized.
        t_start = time.monotonic()
        spans, digests, sigs = self.engine.fingerprint(data, cuts=cuts)
        t_wait = time.monotonic()
        with self._lock:
            t_held = time.monotonic()
            self.stats["lock_wait_us"] += int((t_held - t_wait) * 1e6)
            self.stats["engine_us"] += int((t_wait - t_start) * 1e6)
            sess = self._sessions.setdefault(session_id, _Session())
            sess.touched = time.monotonic()
            raw = np.asarray(digests, dtype=">u4").tobytes()
            out = [_I64.pack(len(spans))]
            for i, (off, ln) in enumerate(spans):
                out.append(_I64.pack(base_offset + off))
                out.append(_I64.pack(ln))
                # Digest attribution (which file first carried a chunk,
                # for near-dup reporting) stays buffered in the session
                # until commit binds the real file id — the index never
                # sees provisional entries.
                dig = raw[i * 20:(i + 1) * 20]
                out.append(dig)
                sess.digests.append((dig, base_offset + off))
            if len(spans):
                sig = np.asarray(sigs).min(axis=0)
                sess.sig = sig if sess.sig is None else np.minimum(sess.sig, sig)
            self.stats["fingerprint_bytes"] += len(data)
            self.stats["chunks"] += len(spans)
        return 0, b"".join(out)

    def _query(self, body: bytes) -> tuple[int, bytes]:
        sha1_hex = body.decode("ascii", "replace").strip()
        with self._lock:
            fid = self.files.get(sha1_hex)
        return 0, fid.encode() if fid else b""

    def _commit(self, body: bytes) -> tuple[int, bytes]:
        parts = body.decode("utf-8", "replace").split()
        if not parts:
            return 22, b""
        with self._lock:
            if parts[0] == "commitfile" and len(parts) == 3:
                self.files.setdefault(parts[1], parts[2])
                self.by_file[parts[2]] = parts[1]
                return 0, b""
            if parts[0] == "commitchunks" and len(parts) == 3:
                sess = self._sessions.pop(_parse_session(parts[1]), None)
                if sess is not None:
                    file_id = parts[2]
                    for dig, off in sess.digests:
                        self.engine.exact.insert(dig, [file_id, off])
                    if sess.sig is not None:
                        self.engine.near.add(sess.sig, file_id)
                return 0, b""
            if parts[0] == "stats" and len(parts) == 1:
                return 0, json.dumps(self.stats).encode()
            if parts[0] == "abort" and len(parts) == 2:
                self._sessions.pop(_parse_session(parts[1]), None)
                return 0, b""
            if parts[0] == "forget" and len(parts) == 2:
                sha1 = self.by_file.pop(parts[1], None)
                if sha1 is not None and self.files.get(sha1) == parts[1]:
                    del self.files[sha1]
                self.engine.near.remove(parts[1])
                # Exact attributions for the deleted file leave the index
                # too (they would otherwise accumulate in RAM + snapshots
                # forever).  The daemon's ChunkStore owns true chunk
                # refcounts; this index only answers "who first carried
                # it", so dropping the tombstoned carrier is safe — a
                # later upload of the same chunk re-attributes it.  One
                # vectorized pass over the index's carrier column — no
                # per-file digest-list side table in RAM.
                self.engine.exact.remove_by_carrier(parts[1])
                return 0, b""
        return 22, b""

    def _verify(self, body: bytes) -> tuple[int, bytes]:
        """DEDUP_VERIFY (136): batched chunk-integrity check for the
        storage scrubber.  Body = 8B count + count x (8B length + 20B
        expected raw SHA1) + payloads concatenated; response = count
        bytes (0 = match, 1 = mismatch).

        Pure compute — no index or session state — so it runs entirely
        outside the engine lock, on the accelerator via
        ``ops/sha1.sha1_batch`` (one padded (N, L) batch per request)
        with a hashlib fallback if the device path fails for any
        reason: a verify answer must never be wrong, only slower.
        """
        if len(body) < 8:
            return 22, b""
        count = _I64.unpack_from(body)[0]
        if count < 0 or 8 + count * 28 > len(body):
            return 22, b""
        lengths = []
        digests = []
        for i in range(count):
            off = 8 + i * 28
            ln = _I64.unpack_from(body, off)[0]
            if ln < 0:
                return 22, b""
            lengths.append(ln)
            digests.append(body[off + 8:off + 28])
        payloads = body[8 + count * 28:]
        if sum(lengths) != len(payloads):
            return 22, b""
        chunks = []
        off = 0
        for ln in lengths:
            chunks.append(payloads[off:off + ln])
            off += ln
        got: list[bytes] = []
        try:
            got = self._batch_sha1(chunks)
        except Exception as e:  # noqa: BLE001 — fall back to the host
            print(f"dedup sidecar: batched verify fell back to hashlib "
                  f"({type(e).__name__}: {e})", flush=True)
        if len(got) != count:
            import hashlib
            got = [hashlib.sha1(c).digest() for c in chunks]
        mask = bytes(0 if g == d else 1 for g, d in zip(got, digests))
        return 0, mask

    @staticmethod
    def _batch_sha1(chunks: list[bytes]) -> list[bytes]:
        """One sha1_batch dispatch over zero-padded rows (device path)."""
        if not chunks:
            return []
        from fastdfs_tpu.ops.sha1 import digest_bytes, sha1_batch
        max_len = max(len(c) for c in chunks)
        batch = np.zeros((len(chunks), max(max_len, 1)), dtype=np.uint8)
        lens = np.zeros((len(chunks),), dtype=np.int32)
        for i, c in enumerate(chunks):
            batch[i, :len(c)] = np.frombuffer(c, dtype=np.uint8)
            lens[i] = len(c)
        raw = digest_bytes(sha1_batch(batch, lens))
        return [raw[i * 20:(i + 1) * 20] for i in range(len(chunks))]

    def _neardups(self, body: bytes) -> tuple[int, bytes]:
        """Ranked near-dup report for a stored file id (the production
        query surface for the LSH index; without it the index is
        write-only).  Status 61 (ENODATA) when the file is unknown to the
        near index — flat, whole-file-deduped, or never committed."""
        file_id = body.decode("utf-8", "replace").strip()
        if not file_id:
            return 22, b""
        with self._lock:
            sig = self.engine.near.signature_of(file_id)
            if sig is None:
                return 61, b""
            cfg = self.engine.config
            pairs = self.engine.near.query(
                sig, top_k=cfg.near_dup_top_k * 2 + 1,
                min_similarity=cfg.near_dup_threshold)
        lines = [f"{ref} {score:.4f}" for ref, score in pairs
                 if ref != file_id][:self.engine.config.near_dup_top_k * 2]
        return 0, "\n".join(lines).encode()

    def _reap_stale_sessions(self) -> None:
        cutoff = time.monotonic() - _SESSION_TTL
        with self._lock:
            stale = [s for s, sess in self._sessions.items()
                     if sess.touched < cutoff]
            for s in stale:
                del self._sessions[s]
        if stale:
            print(f"dedup sidecar: reaped {len(stale)} stale sessions",
                  flush=True)

    # -- server loop -------------------------------------------------------

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                hdr = self._recv_exact(conn, HEADER_SIZE)
                if hdr is None:
                    return
                h = unpack_header(hdr)
                if h.pkg_len < 0 or h.pkg_len > (1 << 31):
                    return
                body = self._recv_exact(conn, h.pkg_len) if h.pkg_len else b""
                if body is None:
                    return
                self.stats["requests"] += 1
                if h.cmd == StorageCmd.DEDUP_FINGERPRINT:
                    status, resp = self._fingerprint(body)
                elif h.cmd == StorageCmd.DEDUP_FINGERPRINT_CUTS:
                    status, resp = self._fingerprint(body, with_cuts=True)
                elif h.cmd == StorageCmd.DEDUP_QUERY:
                    status, resp = self._query(body)
                elif h.cmd == StorageCmd.DEDUP_COMMIT:
                    status, resp = self._commit(body)
                elif h.cmd == StorageCmd.DEDUP_NEARDUPS:
                    status, resp = self._neardups(body)
                elif h.cmd == StorageCmd.DEDUP_VERIFY:
                    status, resp = self._verify(body)
                elif h.cmd == StorageCmd.ACTIVE_TEST:
                    status, resp = 0, b""
                else:
                    status, resp = 22, b""
                conn.sendall(_pack_header(len(resp),
                                          StorageCmd.RESP, status) + resp)
        except OSError:
            pass
        finally:
            conn.close()

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
        buf = bytearray()
        while len(buf) < n:
            got = conn.recv(n - len(buf))
            if not got:
                return None
            buf.extend(got)
        return bytes(buf)

    @staticmethod
    def _rss_mb() -> float:
        try:
            with open("/proc/self/status") as fh:
                for line in fh:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1]) / 1024.0
        except OSError:
            pass
        return 0.0

    def _housekeeping_loop(self, snapshot_interval: float) -> None:
        """Snapshot + stale-session reaping on a dedicated timer thread:
        a steadily-busy listener must not defer them (the accept-timeout
        scheduling they used to ride starves under sustained traffic,
        making crash loss unbounded instead of one snapshot interval).

        Also the RSS watchdog: the experimental axon jax client strands
        an unreleasable host copy of every device transfer (measured ~1x
        bytes shipped; see tools/PROFILE_r05.md), so a long-lived
        sidecar would eventually OOM the box.  Over the limit, the loop
        snapshots state and re-execs the process in place — the daemon
        side fails open and retries on fresh connections, so service
        degrades to flat storage for the ~warmup window instead of
        dying."""
        while not self._stop.wait(snapshot_interval):
            # Catch EVERYTHING: one bad snapshot attempt (OSError, but
            # also numpy/json errors from racing state) must not kill the
            # thread and silently disable snapshots + session reaping.
            snap_ok = True
            try:
                self.save_state()
            except Exception as e:
                snap_ok = False
                print(f"dedup sidecar: snapshot failed: {e}", flush=True)
            try:
                self._reap_stale_sessions()
            except Exception as e:
                print(f"dedup sidecar: session reap failed: {e}", flush=True)
            # Re-exec ONLY on the back of a successful snapshot — losing
            # everything since the last good one would make crash loss
            # unbounded, the exact failure bound this loop guarantees.
            if snap_ok and self.max_rss_mb > 0 and self.restart_argv:
                rss = self._rss_mb()
                if rss > self.max_rss_mb:
                    # A trip EARLY in the process's life means the limit
                    # sits below the natural baseline (misconfiguration:
                    # restarting cannot help — that's what the consecutive
                    # counter and its disable guard catch).  A trip after
                    # a long healthy run is the leak doing what leaks do;
                    # resetting the counter keeps the watchdog alive for
                    # the service's whole lifetime.
                    uptime = time.monotonic() - self._started
                    if uptime < 600.0:
                        os.environ["FDFS_SIDECAR_RESTARTS"] = str(
                            int(os.environ.get("FDFS_SIDECAR_RESTARTS",
                                               "0")) + 1)
                    else:
                        os.environ["FDFS_SIDECAR_RESTARTS"] = "0"
                    print(f"dedup sidecar: rss {rss:.0f} MB > limit "
                          f"{self.max_rss_mb} MB after {uptime:.0f}s — "
                          "re-exec (state saved)", flush=True)
                    os.execv(sys.executable,
                             [sys.executable, "-m", "fastdfs_tpu.sidecar",
                              *self.restart_argv])

    def serve_forever(self, ready_event: threading.Event | None = None,
                      snapshot_interval: float = 60.0) -> None:
        try:
            os.unlink(self.socket_path)
        except FileNotFoundError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(16)
        self._listener.settimeout(0.5)
        if ready_event is not None:
            ready_event.set()
        housekeeper = threading.Thread(
            target=self._housekeeping_loop, args=(snapshot_interval,),
            daemon=True)
        housekeeper.start()
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve_conn,
                             args=(conn,), daemon=True).start()
        self._stop.set()
        housekeeper.join(timeout=5.0)
        self.save_state()
        self._listener.close()
        try:
            os.unlink(self.socket_path)
        except FileNotFoundError:
            pass

    def stop(self) -> None:
        self._stop.set()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="fastdfs_tpu dedup sidecar")
    ap.add_argument("--socket", required=True, help="unix socket path")
    ap.add_argument("--state-dir", default=None,
                    help="snapshot dir (checkpoint/resume)")
    ap.add_argument("--snapshot-interval", type=float, default=60.0)
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu for tests; this "
                         "image pins JAX_PLATFORMS=axon via sitecustomize, "
                         "so only jax.config.update overrides reliably)")
    ap.add_argument("--max-rss-mb", type=int, default=24576,
                    help="RSS watchdog: snapshot state and re-exec the "
                         "process above this resident size (0 disables). "
                         "Guards against client-side transfer leaks on "
                         "experimental backends; the daemon fails open "
                         "during the restart window.")
    ap.add_argument("--cdc-policy", type=int, default=1, choices=(1, 2),
                    help="cut-selection policy: 1 = default (frozen, "
                         "ref-identical cuts), 2 = skip-min "
                         "(arXiv:2508.05797; different boundaries — new "
                         "groups only, see OPERATIONS.md).  Snapshots "
                         "built under another policy are discarded at "
                         "load.")
    ap.add_argument("--fan-out", type=int, default=None,
                    help="shard each fingerprint batch's rows over this "
                         "many local devices (default: auto — all local "
                         "devices on a multi-chip TPU host, else 1)")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    config = DedupConfig(cdc_policy=args.cdc_policy, fan_out=args.fan_out)
    sidecar = DedupSidecar(args.socket, state_dir=args.state_dir,
                           config=config)
    # Restart-loop guard: a limit below the process's natural baseline
    # (misconfiguration) would otherwise re-exec every tick forever,
    # each cycle costing a warmup of degraded-to-flat service.  After
    # two watchdog restarts the guard disables itself and stays up.
    restarts = int(os.environ.get("FDFS_SIDECAR_RESTARTS", "0"))
    if restarts >= 2 and args.max_rss_mb > 0:
        print(f"dedup sidecar: {restarts} watchdog restarts — limit "
              f"{args.max_rss_mb} MB looks below baseline; watchdog "
              "DISABLED for this process", flush=True)
        sidecar.max_rss_mb = 0
    else:
        sidecar.max_rss_mb = args.max_rss_mb
    sidecar.restart_argv = list(argv) if argv is not None else sys.argv[1:]
    signal.signal(signal.SIGTERM, lambda *_: sidecar.stop())
    signal.signal(signal.SIGINT, lambda *_: sidecar.stop())
    t0 = time.monotonic()
    sidecar.engine.warmup()  # compile all shapes BEFORE accepting traffic
    print(f"dedup sidecar warmed in {time.monotonic() - t0:.1f}s, "
          f"listening on {args.socket}", flush=True)
    sidecar.serve_forever(snapshot_interval=args.snapshot_interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
