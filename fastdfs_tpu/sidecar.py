"""Dedup sidecar: the TPU fingerprint engine behind the storage daemon.

This is the server half of the daemon's ``dedup_mode = sidecar`` plugin
(C++ client: ``native/storage/dedup.cc:SidecarDedup``): a unix-socket
service speaking the standard 10-byte framing with the DEDUP_* opcodes.
The storage daemon streams each chunk-eligible upload through cmd 120 and
writes only the chunks its content-addressed store has never seen — this
process supplies the cut-points and digests, computed by the JAX/TPU
pipeline (position-parallel gear CDC + batched SHA1; the replacement for
the scalar CRC32 loop in the reference's
``storage/storage_dio.c:dio_write_file()``).

Opcodes
-------
* ``DEDUP_FINGERPRINT`` (120): body = 8B BE base_offset + raw segment
  bytes.  Response: 8B BE chunk count, then per chunk 8B BE offset +
  8B BE length + 20B raw SHA1.  Also feeds the MinHash near-dup index
  with the segment's file signature (pending until commit).
* ``DEDUP_QUERY`` (121): body = 40-hex whole-file SHA1.  Response: the
  canonical file id if known (whole-file dedup for sub-threshold files).
* ``DEDUP_COMMIT`` (122): text body, one of
  ``commitfile <sha1hex> <file_id>`` | ``commitchunks <file_id>`` |
  ``forget <file_id>``.

State: whole-file digest map + the DedupEngine's exact/LSH indexes;
snapshotted to ``<state_dir>/sidecar_*.json`` on SIGTERM and every
``--snapshot-interval`` seconds.

Run: ``python -m fastdfs_tpu.sidecar --socket /path/dedup.sock``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import struct
import threading
import time

import numpy as np

from fastdfs_tpu.common.protocol import HEADER_SIZE, StorageCmd, unpack_header
from fastdfs_tpu.dedup.engine import DedupConfig, DedupEngine

_I64 = struct.Struct(">q")


def _pack_header(pkg_len: int, cmd: int, status: int = 0) -> bytes:
    return struct.pack(">qBB", pkg_len, cmd, status)


class DedupSidecar:
    """Unix-socket dedup service around a :class:`DedupEngine`.

    One engine (and one TPU context) serves every daemon connection;
    engine calls are serialized under a lock — batching happens inside
    the engine's bucketed jit calls, not across requests.
    """

    def __init__(self, socket_path: str, state_dir: str | None = None,
                 config: DedupConfig | None = None) -> None:
        self.socket_path = socket_path
        self.state_dir = state_dir
        self.engine = DedupEngine(config)
        self.files: dict[str, str] = {}       # whole-file sha1 -> file id
        self.by_file: dict[str, str] = {}     # file id -> sha1
        self._pending_sigs: dict[int, np.ndarray] = {}  # conn id -> file sig
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener: socket.socket | None = None
        self.stats = {"fingerprint_bytes": 0, "chunks": 0, "requests": 0}
        if state_dir:
            self._load_state()

    # -- state -------------------------------------------------------------

    def _state_paths(self) -> tuple[str, str, str]:
        d = self.state_dir or "."
        return (os.path.join(d, "sidecar_files.json"),
                os.path.join(d, "sidecar_exact.npz"),
                os.path.join(d, "sidecar_near.npz"))

    def _load_state(self) -> None:
        files_p, exact_p, near_p = self._state_paths()
        if os.path.exists(files_p):
            with open(files_p) as fh:
                self.files = json.load(fh)
            self.by_file = {v: k for k, v in self.files.items()}
        if os.path.exists(exact_p) and os.path.exists(near_p):
            self.engine = DedupEngine.load(exact_p, near_p,
                                           self.engine.config)

    def save_state(self) -> None:
        if not self.state_dir:
            return
        files_p, exact_p, near_p = self._state_paths()
        with self._lock:
            tmp = files_p + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(self.files, fh)
            os.replace(tmp, files_p)
            self.engine.save(exact_p, near_p)

    # -- request handlers --------------------------------------------------

    def _fingerprint(self, conn_id: int, body: bytes) -> tuple[int, bytes]:
        if len(body) < 8:
            return 22, b""
        base_offset = _I64.unpack_from(body)[0]
        data = body[8:]
        with self._lock:
            spans, digests, sigs = self.engine.fingerprint(data)
            raw = np.asarray(digests, dtype=">u4").tobytes()
            out = [_I64.pack(len(spans))]
            for i, (off, ln) in enumerate(spans):
                out.append(_I64.pack(base_offset + off))
                out.append(_I64.pack(ln))
                out.append(raw[i * 20:(i + 1) * 20])
                # Exact chunk index: remembers which file first carried a
                # digest (near-dup attribution; the byte-level dedup
                # decision lives in the daemon's content-addressed store).
                dig = raw[i * 20:(i + 1) * 20]
                if self.engine.exact.lookup(dig) is None:
                    self.engine.exact.insert(dig, ["(pending)", off])
            if len(spans):
                sig = np.asarray(sigs).min(axis=0)
                prev = self._pending_sigs.get(conn_id)
                self._pending_sigs[conn_id] = (
                    sig if prev is None else np.minimum(prev, sig))
            self.stats["fingerprint_bytes"] += len(data)
            self.stats["chunks"] += len(spans)
        return 0, b"".join(out)

    def _query(self, body: bytes) -> tuple[int, bytes]:
        sha1_hex = body.decode("ascii", "replace").strip()
        with self._lock:
            fid = self.files.get(sha1_hex)
        return 0, fid.encode() if fid else b""

    def _commit(self, conn_id: int, body: bytes) -> tuple[int, bytes]:
        parts = body.decode("utf-8", "replace").split()
        if not parts:
            return 22, b""
        with self._lock:
            if parts[0] == "commitfile" and len(parts) == 3:
                self.files.setdefault(parts[1], parts[2])
                self.by_file[parts[2]] = parts[1]
                return 0, b""
            if parts[0] == "commitchunks" and len(parts) == 2:
                sig = self._pending_sigs.pop(conn_id, None)
                if sig is not None:
                    self.engine.near.add(sig, parts[1])
                return 0, b""
            if parts[0] == "forget" and len(parts) == 2:
                sha1 = self.by_file.pop(parts[1], None)
                if sha1 is not None and self.files.get(sha1) == parts[1]:
                    del self.files[sha1]
                self.engine.near.remove(parts[1])
                return 0, b""
        return 22, b""

    # -- server loop -------------------------------------------------------

    def _serve_conn(self, conn: socket.socket, conn_id: int) -> None:
        try:
            while not self._stop.is_set():
                hdr = self._recv_exact(conn, HEADER_SIZE)
                if hdr is None:
                    return
                h = unpack_header(hdr)
                if h.pkg_len < 0 or h.pkg_len > (1 << 31):
                    return
                body = self._recv_exact(conn, h.pkg_len) if h.pkg_len else b""
                if body is None:
                    return
                self.stats["requests"] += 1
                if h.cmd == StorageCmd.DEDUP_FINGERPRINT:
                    status, resp = self._fingerprint(conn_id, body)
                elif h.cmd == StorageCmd.DEDUP_QUERY:
                    status, resp = self._query(body)
                elif h.cmd == StorageCmd.DEDUP_COMMIT:
                    status, resp = self._commit(conn_id, body)
                elif h.cmd == StorageCmd.ACTIVE_TEST:
                    status, resp = 0, b""
                else:
                    status, resp = 22, b""
                conn.sendall(_pack_header(len(resp),
                                          StorageCmd.RESP, status) + resp)
        except OSError:
            pass
        finally:
            with self._lock:
                self._pending_sigs.pop(conn_id, None)
            conn.close()

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
        buf = bytearray()
        while len(buf) < n:
            got = conn.recv(n - len(buf))
            if not got:
                return None
            buf.extend(got)
        return bytes(buf)

    def serve_forever(self, ready_event: threading.Event | None = None,
                      snapshot_interval: float = 60.0) -> None:
        try:
            os.unlink(self.socket_path)
        except FileNotFoundError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(16)
        self._listener.settimeout(0.5)
        if ready_event is not None:
            ready_event.set()
        next_snap = time.monotonic() + snapshot_interval
        conn_seq = 0
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                if time.monotonic() >= next_snap:
                    self.save_state()
                    next_snap = time.monotonic() + snapshot_interval
                continue
            except OSError:
                break
            conn_seq += 1
            threading.Thread(target=self._serve_conn,
                             args=(conn, conn_seq), daemon=True).start()
        self.save_state()
        self._listener.close()
        try:
            os.unlink(self.socket_path)
        except FileNotFoundError:
            pass

    def stop(self) -> None:
        self._stop.set()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="fastdfs_tpu dedup sidecar")
    ap.add_argument("--socket", required=True, help="unix socket path")
    ap.add_argument("--state-dir", default=None,
                    help="snapshot dir (checkpoint/resume)")
    ap.add_argument("--snapshot-interval", type=float, default=60.0)
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu for tests; this "
                         "image pins JAX_PLATFORMS=axon via sitecustomize, "
                         "so only jax.config.update overrides reliably)")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    sidecar = DedupSidecar(args.socket, state_dir=args.state_dir)
    signal.signal(signal.SIGTERM, lambda *_: sidecar.stop())
    signal.signal(signal.SIGINT, lambda *_: sidecar.stop())
    t0 = time.monotonic()
    sidecar.engine.warmup()  # compile all shapes BEFORE accepting traffic
    print(f"dedup sidecar warmed in {time.monotonic() - t0:.1f}s, "
          f"listening on {args.socket}", flush=True)
    sidecar.serve_forever(snapshot_interval=args.snapshot_interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
