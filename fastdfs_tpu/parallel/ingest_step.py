"""The distributed ingest step: the framework's "full training step".

One jitted ``shard_map`` over a (dp, sp, tp) mesh runs the whole upload
fingerprint pipeline with real shardings and collectives:

1. **CDC, sequence-parallel (sp)** — each device holds one contiguous block
   of every stream; the gear hash's 31-byte window straddles block seams,
   so each device ``ppermute``-sends its trailing window to the next
   device (ring halo exchange) and computes exact per-position hashes for
   its block.  Cut candidates come out bit-identical to the single-device
   path (tested).
2. **Fingerprints, data-parallel (dp)** — the chunk batch is row-sharded;
   each device runs batched SHA1 on its rows, then the digests are
   ``all_gather``-ed (the "cross-node digest all-gather" of BASELINE
   config 5).
3. **MinHash, tensor-parallel (tp)** — the permutation axis is sharded;
   each device computes ``P/tp`` signature lanes, reassembled with
   ``all_gather``.
4. **Index query (dp + pmax)** — the signature index is row-sharded over
   dp; every device scores the (gathered) query signatures against its
   shard and the global best similarity is reduced with ``pmax``.

There is no SGD here — a storage system's "step" is ingest — but the
sharding roles are the real ones: dp=batch, sp=sequence(byte stream),
tp=feature(hash lanes).  Pipeline parallelism is intentionally absent: the
reference's 5-stage upload pipeline (SURVEY.md §2.8) is an *async host*
pipeline (nio→dio→binlog→sync), which maps to overlapping host↔device
streams, not to device-staged layers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from fastdfs_tpu.ops.gear_cdc import GEAR_TABLE, WINDOW
from fastdfs_tpu.ops.minhash import (EMPTY, _perm_constants, minhash_batch,
                                     survivor_segmin)
from fastdfs_tpu.ops.sha1 import _sha1_padded

HALO = WINDOW - 1


def _shard_mapped(fn, **specs):
    """``shard_map`` across the jax API move (>=0.6 top-level / check_vma,
    older experimental module / check_rep)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, **specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(fn, **specs, check_rep=False)


def _gear_from_g(g: jax.Array) -> jax.Array:
    """Windowed gear hash over pre-gathered table values ``g`` (n,)."""
    h = g
    for k in range(1, WINDOW):
        shifted = jnp.roll(g, k).at[:k].set(0)
        h = h + (shifted << np.uint32(k))
    return h


def make_ingest_step(mesh: Mesh, num_perms: int = 64, avg_bits: int = 13,
                     shingle: int = 5):
    """Build the jitted distributed ingest step for ``mesh``.

    Returns ``step(stream, chunk_batch, chunk_lens, index_sigs)`` where

    - ``stream``: uint8 ``(B, sp, block_len)`` — B byte streams, each split
      into ``sp`` contiguous blocks (global stream = concat along axis 1);
    - ``chunk_batch``: uint8 ``(N, L)``; ``chunk_lens``: int32 ``(N,)``;
    - ``index_sigs``: uint32 ``(M, num_perms)`` — the near-dup index shard
      rows (M across dp);

    and returns ``(cand_mask (B, sp, block_len) bool, digests (N, 5),
    sigs (N, num_perms), best_sim (N,))``.
    """
    dp = mesh.shape["dp"]
    sp = mesh.shape["sp"]
    tp = mesh.shape["tp"]
    if num_perms % tp:
        raise ValueError(f"num_perms {num_perms} must divide by tp {tp}")
    p_local = num_perms // tp
    a_full, b_full = _perm_constants(num_perms)
    mask_val = np.uint32((1 << avg_bits) - 1)
    table = jnp.asarray(GEAR_TABLE)

    def step_local(stream, chunk_batch, chunk_lens, index_sigs):
        # ---- stage 1: sequence-parallel CDC with ring halo exchange -----
        # local stream: (B_loc, 1, block_len) — the sp axis is fully split.
        blk = stream[:, 0, :]                       # (B_loc, L_blk) uint8
        g = table[blk.astype(jnp.int32)]            # gear values
        tail = g[:, -HALO:]                         # my trailing window
        sp_idx = jax.lax.axis_index("sp")
        # ring: device i sends tail to i+1 (its successor holds the next block)
        prev_tail = jax.lax.ppermute(
            tail, "sp", [(i, (i + 1) % sp) for i in range(sp)])
        # first block has no predecessor: zero its halo contributions
        prev_tail = jnp.where(sp_idx == 0, jnp.uint32(0), prev_tail)
        g_ext = jnp.concatenate([prev_tail, g], axis=1)
        h = jax.vmap(_gear_from_g)(g_ext)[:, HALO:]  # (B_loc, L_blk)
        cand = ((h & mask_val) == 0)[:, None, :]     # restore the sp axis

        # ---- stage 2: data-parallel SHA1 + digest all-gather ------------
        digests_loc = _sha1_padded(chunk_batch, chunk_lens,
                                   int(chunk_batch.shape[1]))  # (N_loc, 5)
        digests = jax.lax.all_gather(digests_loc, "dp", axis=0, tiled=True)

        # ---- stage 3: tensor-parallel MinHash (v2 survivor sketch) ------
        tp_idx = jax.lax.axis_index("tp")
        a = jax.lax.dynamic_slice(jnp.asarray(a_full), (tp_idx * p_local,), (p_local,))
        b = jax.lax.dynamic_slice(jnp.asarray(b_full), (tp_idx * p_local,), (p_local,))

        z = survivor_segmin(chunk_batch, chunk_lens, shingle)  # (N_loc, S)

        def one_sig(zr):
            hv = zr[None, :] * a[:, None] + b[:, None]
            hv = jnp.where((zr != EMPTY)[None, :], hv, EMPTY)
            return hv.min(axis=1)                    # (p_local,)

        sigs_loc = jax.vmap(one_sig)(z)              # (N_loc, p_local)
        sigs_full = jax.lax.all_gather(sigs_loc, "tp", axis=1, tiled=True)
        sigs = jax.lax.all_gather(sigs_full, "dp", axis=0, tiled=True)  # (N, P)

        # ---- stage 4: dp-sharded index query + global pmax --------------
        # index_sigs local: (M_loc, P); score all N queries vs my shard.
        eq = sigs[:, None, :] == index_sigs[None, :, :]          # (N, M_loc, P)
        scores = eq.mean(axis=2, dtype=jnp.float32)
        local_best = jnp.max(scores, axis=1, initial=0.0)        # 0.0 if M_loc==0
        best = jax.lax.pmax(local_best, "dp")                    # (N,)
        return cand, digests, sigs, best

    sharded = _shard_mapped(
        step_local,
        mesh=mesh,
        in_specs=(P("dp", "sp", None), P("dp", None), P("dp"), P("dp", None)),
        out_specs=(P("dp", "sp", None), P(), P(), P()),
    )
    return jax.jit(sharded)


def fingerprint_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D ``dp`` mesh over the local devices, for the fingerprint
    fan-out (chunk rows are the abundant parallelism; no collectives are
    needed, so one axis is the whole story)."""
    devs = jax.local_devices()
    n = len(devs) if n_devices is None else n_devices
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), ("dp",))


def make_fingerprint_step(mesh: Mesh, num_perms: int = 64, shingle: int = 5):
    """Build the jitted multi-chip fingerprint step for a 1-D ``dp`` mesh.

    Returns ``step(chunk_batch (N, L) uint8, chunk_lens (N,) int32) ->
    (digests (N, 5) uint32, sigs (N, num_perms) uint32)``.  ``N`` must
    divide by ``mesh.shape['dp']``.

    This is the ingest hot loop's scale-out: rows shard across every
    local device and each chip runs batched SHA1 (``_sha1_padded``) plus
    the survivor-sketch MinHash (``minhash_batch``) on its slice — pure
    map parallelism, zero collectives, so aggregate throughput is
    ``n_devices x`` the per-chip rate minus transfer overlap.  Outputs
    stay sharded (``P('dp', None)``); fetching reassembles them.  Both
    kernels are the XLA references that the Pallas twins are pinned
    bit-identical to (tests/test_pallas_kernels.py), so the fan-out path
    produces byte-for-byte the digests/signatures of the single-chip
    paths — verified across mesh sizes in tests/test_cdc_kernels.py.
    """
    def fp_local(chunk_batch, chunk_lens):
        digests = _sha1_padded(chunk_batch, chunk_lens,
                               int(chunk_batch.shape[1]))
        sigs = minhash_batch(chunk_batch, chunk_lens, num_perms, shingle)
        return digests, sigs

    sharded = _shard_mapped(
        fp_local,
        mesh=mesh,
        in_specs=(P("dp", None), P("dp")),
        out_specs=(P("dp", None), P("dp", None)),
    )
    return jax.jit(sharded)


@functools.cache
def _cached_fingerprint_step(mesh_key, num_perms, shingle):
    mesh, _ = mesh_key
    return make_fingerprint_step(mesh, num_perms, shingle)


def distributed_fingerprint(mesh: Mesh, chunk_batch, chunk_lens,
                            num_perms: int = 64, shingle: int = 5):
    """Convenience wrapper: build (cached) and run the fan-out step."""
    step = _cached_fingerprint_step(
        (mesh, str(mesh.devices.tolist())), num_perms, shingle)
    return step(jnp.asarray(chunk_batch, dtype=jnp.uint8),
                jnp.asarray(chunk_lens, dtype=jnp.int32))


@functools.cache
def _cached_step(mesh_key, num_perms, avg_bits, shingle):
    mesh, _ = mesh_key
    return make_ingest_step(mesh, num_perms, avg_bits, shingle)


def distributed_ingest_step(mesh: Mesh, stream, chunk_batch, chunk_lens,
                            index_sigs, num_perms: int = 64,
                            avg_bits: int = 13, shingle: int = 5):
    """Convenience wrapper: build (cached) and run the step on ``mesh``."""
    step = _cached_step((mesh, str(mesh.devices.tolist())), num_perms,
                        avg_bits, shingle)
    return step(jnp.asarray(stream, dtype=jnp.uint8),
                jnp.asarray(chunk_batch, dtype=jnp.uint8),
                jnp.asarray(chunk_lens, dtype=jnp.int32),
                jnp.asarray(index_sigs, dtype=jnp.uint32))
