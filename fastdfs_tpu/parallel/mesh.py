"""Mesh construction: factor a device count into (dp, sp, tp) axes."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def factorize_devices(n: int, num_axes: int = 3) -> tuple[int, ...]:
    """Split ``n`` devices into ``num_axes`` near-equal power factors.

    8 → (2, 2, 2); 4 → (2, 2, 1); 2 → (2, 1, 1); 1 → (1, 1, 1);
    6 → (3, 2, 1); 12 → (3, 2, 2).  Earlier axes get the larger factors
    (dp first: chunk batches are the abundant parallelism).
    """
    factors = []
    rem = n
    for d in range(2, rem + 1):
        while rem % d == 0:
            factors.append(d)
            rem //= d
    factors.sort(reverse=True)
    axes = [1] * num_axes
    for f in factors:
        axes[int(np.argmin(axes))] *= f
    return tuple(sorted(axes, reverse=True))


def make_mesh(n_devices: int | None = None,
              axis_names: tuple[str, ...] = ("dp", "sp", "tp")) -> Mesh:
    """Build a Mesh over the first ``n_devices`` jax devices."""
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    shape = factorize_devices(n, len(axis_names))
    arr = np.array(devs[:n]).reshape(shape)
    return Mesh(arr, axis_names)
