"""Device-mesh parallelism for the dedup data plane.

The reference's parallelism is threads + point-to-point TCP (SURVEY.md
§2.8); the TPU-native equivalents here are XLA collectives over a
``jax.sharding.Mesh``:

- **sp** (sequence parallel): a long byte stream is split into contiguous
  blocks, one per device; the gear rolling hash needs a 31-byte halo from
  the previous block, exchanged with ``ppermute`` (the ring-attention
  analogue for CDC — SURVEY.md §5 "long-context").
- **dp** (data parallel): chunk batches sharded across devices; digest
  all-gather builds the replicated exact index view.
- **tp** (tensor parallel): the MinHash permutation axis sharded across
  devices; ``all_gather`` reassembles full signatures.

Control plane (tracker protocol, client data path) stays TCP — it is a
storage wire protocol, not a tensor exchange.
"""

from fastdfs_tpu.parallel.mesh import make_mesh, factorize_devices  # noqa: F401
from fastdfs_tpu.parallel.ingest_step import (  # noqa: F401
    distributed_fingerprint,
    distributed_ingest_step,
    fingerprint_mesh,
    make_fingerprint_step,
    make_ingest_step,
)
