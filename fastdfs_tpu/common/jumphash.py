"""Jump consistent hash (Lamping & Veach, "A Fast, Minimal Memory,
Consistent Hash Algorithm", arXiv:1406.2294).

The parallel download path uses it to pick WHICH replica serves WHICH
byte range of a file: the function is stateless and consistent, so every
client maps (file id, range index) to the same replica — per-replica
hot-chunk read caches (storage.conf:read_cache_mb) accumulate hits
instead of each client spraying every replica's cache with every range.
When the replica set grows by one, only ~1/n of the range assignments
move (the consistent-hash property), so cache warmth largely survives
membership changes.
"""

from __future__ import annotations

import hashlib

_MASK64 = (1 << 64) - 1
_K = 2862933555777941757  # the paper's 64-bit LCG multiplier


def jump_hash(key: int, num_buckets: int) -> int:
    """Bucket in [0, num_buckets) for a 64-bit key — the paper's
    ch(key, num_buckets), bit-for-bit."""
    if num_buckets <= 0:
        raise ValueError(f"num_buckets must be positive, got {num_buckets}")
    key &= _MASK64
    b, j = -1, 0
    while j < num_buckets:
        b = j
        key = (key * _K + 1) & _MASK64
        j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


def placement_key(key: str) -> int:
    """64-bit jump-hash key for upload placement (store_lookup = 3): the
    first 8 bytes (big-endian) of SHA1(client key).  Mirrored bit-exactly
    by native/common/jumphash.h PlacementKey (fdfs_codec placement-wire
    golden)."""
    h = hashlib.sha1(key.encode()).digest()
    return int.from_bytes(h[:8], "big")


def group_for_key(key: str, num_active_groups: int) -> int:
    """Index into the placement epoch's ordered ACTIVE-group list for one
    client key — the pick the tracker, the rebalance migrator, and a
    placement-routing client all agree on."""
    return jump_hash(placement_key(key), num_active_groups)


def range_key(file_id: str, range_index: int) -> int:
    """64-bit jump-hash key for one byte range of one file: the first 8
    bytes (big-endian) of SHA1("<file_id>#<range_index>")."""
    h = hashlib.sha1(f"{file_id}#{range_index}".encode()).digest()
    return int.from_bytes(h[:8], "big")


def replica_for_range(file_id: str, range_index: int,
                      num_replicas: int) -> int:
    """Which replica (index into the tracker's query_fetch_all list)
    serves this range — the cache-affinity pick every client agrees on."""
    return jump_hash(range_key(file_id, range_index), num_replicas)
