"""Binary wire protocol: header framing, opcodes, field widths.

Reference: ``common/fdfs_proto.h`` in xigui2013/fastdfs — the 10-byte
``TrackerHeader { char pkg_len[8]; char cmd; char status; }`` with a
big-endian int64 body length, plus the ``TRACKER_PROTO_CMD_*`` /
``STORAGE_PROTO_CMD_*`` opcode tables.

Provenance note (SURVEY.md §2.5): the reference mount was empty at survey
time, so opcode *values* follow the documented upstream layout
(high-confidence reconstruction) and the protocol is FastDFS-*shaped*
rather than certified byte-compatible.  Within this framework the values
below ARE the contract: the C++ daemons in ``native/`` generate their
opcode table from this module (see ``native/gen_protocol_header.py``).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass

# ---------------------------------------------------------------------------
# Field widths (reference: common/fdfs_proto.h constants)
# ---------------------------------------------------------------------------

GROUP_NAME_MAX_LEN = 16          # FDFS_GROUP_NAME_MAX_LEN
IP_ADDRESS_SIZE = 16             # IP_ADDRESS_SIZE (dotted-quad + NUL)
FILE_EXT_NAME_MAX_LEN = 6        # FDFS_FILE_EXT_NAME_MAX_LEN
FILE_PREFIX_MAX_LEN = 16         # FDFS_FILE_PREFIX_MAX_LEN (slave names)
FILENAME_BASE64_LENGTH = 27      # FDFS_FILENAME_BASE64_LENGTH (20 raw bytes)
STORAGE_ID_MAX_SIZE = 16
PROTO_PKG_LEN_SIZE = 8
MAX_META_NAME_LEN = 64
MAX_META_VALUE_LEN = 256

# Metadata wire separators (reference: fdfs_proto.h FDFS_RECORD_SEPARATOR /
# FDFS_FIELD_SEPARATOR).
RECORD_SEPARATOR = b"\x01"
FIELD_SEPARATOR = b"\x02"

HEADER_SIZE = PROTO_PKG_LEN_SIZE + 2  # 8B len + 1B cmd + 1B status

# ---------------------------------------------------------------------------
# Storage-beat stat blob (reference: FDFSStorageStat in tracker_types.h,
# shipped to the tracker on every TRACKER_PROTO_CMD_STORAGE_BEAT).
#
# The beat body carries BEAT_STAT_COUNT big-endian int64 slots after the
# identity prefix; slot i is named BEAT_STAT_FIELDS[i].  The C++ daemons
# compile against the generated mirror (protocol_gen.h kBeatStatNames),
# so the tracker's JSON stat feed and the Python monitor agree on every
# field by construction.  Slots 0-18 are the storage's restart-persisted
# op counters (storage_stat.dat); 19+ are live values sampled at beat
# time.  Append-only: new fields go at the end (the tracker accepts
# shorter blobs from older storages, missing slots read 0).
# ---------------------------------------------------------------------------

BEAT_STAT_FIELDS = (
    "total_upload", "success_upload",
    "total_download", "success_download",
    "total_delete", "success_delete",
    "total_append", "success_append",
    "total_set_meta", "success_set_meta",
    "total_get_meta", "success_get_meta",
    "total_query", "success_query",
    "bytes_uploaded", "bytes_downloaded",
    "dedup_hits", "dedup_bytes_saved",
    "last_source_update",
    "connections",
    "refused_connections",
    "sync_lag_s",
    "sync_bytes_saved_wire",
    "recovery_chunks_fetched",
    "recovery_chunks_local",
    "recovery_files",
    "fetch_chunk_batches",
    "dedup_chunk_misses",
    "rebalance_files_moved",
    "rebalance_bytes_moved",
    "rebalance_files_pending",
    "rebalance_errors",
    "rebalance_done",
)
BEAT_STAT_COUNT = len(BEAT_STAT_FIELDS)

# ---------------------------------------------------------------------------
# Integrity-engine status blob (fastdfs_tpu extension; no reference
# equivalent — upstream FastDFS never re-reads stored bytes).
#
# The ``StorageCmd.SCRUB_STATUS`` response body carries SCRUB_STAT_COUNT
# big-endian int64 slots; slot i is named SCRUB_STAT_FIELDS[i].  The C++
# daemon compiles against the generated mirror (protocol_gen.h
# kScrubStatNames), and the layout is pinned by the ``fdfs_codec
# scrub-status`` cross-language golden.  Append-only like the beat blob:
# new fields go at the end, decoders read missing tail slots as 0.
# ---------------------------------------------------------------------------

SCRUB_STAT_FIELDS = (
    "running",               # a verify/GC pass is in flight right now
    "passes",                # completed passes since start
    "pass_chunks_done",      # progress within the current pass
    "pass_chunks_total",
    "chunks_verified",       # cumulative re-hashed chunks
    "bytes_verified",
    "chunks_corrupt",        # digest mismatches found (incl. truncations)
    "chunks_repaired",       # quarantined chunks restored from a replica
    "corrupt_unrepairable",  # repair attempts with no replica serving it
    "quarantined",           # currently quarantined (live refs, bytes aside)
    "skipped_pinned",        # corrupt but pinned by an in-flight stream
    "gc_pending_chunks",     # zero-ref chunks inside the grace window
    "gc_pending_bytes",
    "chunks_reclaimed",      # zero-ref chunks unlinked by GC sweeps
    "bytes_reclaimed",       # chunk + recipe-sidecar bytes reclaimed
    "recipes_reclaimed",     # recipe sidecar files deleted with their file
    "last_pass_unix",
    "last_pass_duration_us",
)
SCRUB_STAT_COUNT = len(SCRUB_STAT_FIELDS)


def pack_scrub_stats(stats: dict[str, int]) -> bytes:
    """SCRUB_STATUS response body from named values (tests/goldens; the
    production encoder is the C++ daemon)."""
    return b"".join(long2buff(int(stats.get(name, 0)))
                    for name in SCRUB_STAT_FIELDS)


def unpack_scrub_stats(buf: bytes) -> dict[str, int]:
    """Name a SCRUB_STATUS blob; missing tail slots read 0 (the wire
    contract is append-only, so an older daemon's shorter blob decodes)."""
    n = len(buf) // 8
    vals = [buff2long(buf, i * 8) for i in range(min(n, SCRUB_STAT_COUNT))]
    vals += [0] * (SCRUB_STAT_COUNT - len(vals))
    return dict(zip(SCRUB_STAT_FIELDS, vals))


# ---------------------------------------------------------------------------
# Erasure-coding status blob (fastdfs_tpu extension; no reference
# equivalent — upstream FastDFS only replicates).
#
# The ``StorageCmd.EC_STATUS`` response body carries EC_STAT_COUNT
# big-endian int64 slots; slot i is named EC_STAT_FIELDS[i].  The C++
# daemon compiles against the generated mirror (protocol_gen.h
# kEcStatNames), and the layout is pinned by the ``fdfs_codec
# ec-status`` cross-language golden.  Append-only like the beat and
# scrub blobs: new fields go at the end, decoders read missing tail
# slots as 0.
# ---------------------------------------------------------------------------

EC_STAT_FIELDS = (
    "enabled",                 # ec_k > 0 on this daemon
    "k",                       # data shards per stripe
    "m",                       # parity shards per stripe
    "stripes",                 # live stripes in this node's EC store
    "stripe_chunks",           # live chunks resident in those stripes
    "data_bytes",              # logical chunk bytes inside live stripes
    "parity_bytes",            # parity + padding overhead bytes on disk
    "demoted_chunks",          # cumulative chunks encoded into stripes
    "demoted_bytes",
    "released_chunks",         # replica copies dropped after EC handover
    "released_bytes",
    "reconstructed_shards",    # shards rebuilt from parity by scrub
    "reconstructed_bytes",
    "repair_fallback_chunks",  # stripes past parity, refilled via FETCH_CHUNK
    "remote_reads",            # released-chunk reads served via a peer fetch
    "last_demote_unix",
)
EC_STAT_COUNT = len(EC_STAT_FIELDS)


def pack_ec_stats(stats: dict[str, int]) -> bytes:
    """EC_STATUS response body from named values (tests/goldens; the
    production encoder is the C++ daemon)."""
    return b"".join(long2buff(int(stats.get(name, 0)))
                    for name in EC_STAT_FIELDS)


def unpack_ec_stats(buf: bytes) -> dict[str, int]:
    """Name an EC_STATUS blob; missing tail slots read 0 (append-only
    wire contract, same discipline as the scrub blob)."""
    n = len(buf) // 8
    vals = [buff2long(buf, i * 8) for i in range(min(n, EC_STAT_COUNT))]
    vals += [0] * (EC_STAT_COUNT - len(vals))
    return dict(zip(EC_STAT_FIELDS, vals))


PROFILE_CTL_LEN = 17


def pack_profile_ctl(start: bool, hz: int = 0, duration_s: int = 0) -> bytes:
    """PROFILE_CTL request body: 1B action (1 = start, 0 = stop) + 8B BE
    hz + 8B BE duration seconds.  Stop ignores the numbers but still
    carries the full 17-byte shape (fixed-size bodies keep the daemon's
    recv path branch-free; pinned by the fdfs_codec profile-ctl golden)."""
    return bytes([1 if start else 0]) + long2buff(hz) + long2buff(duration_s)

# Largest request body a daemon will buffer in memory (larger bodies
# stream to disk, or the connection is closed).  A WIRE contract, not a
# tuning knob: senders of inline-only commands (e.g. the chunk-aware
# replication query) must size against it or their requests are
# unparseable at the peer.
MAX_INLINE_BODY = 64 << 20

# ---------------------------------------------------------------------------
# Trace context (fastdfs_tpu extension; no reference equivalent).
#
# A traced request is prefixed by one TRACE_CTX frame: a normal 10-byte
# header with cmd=TRACE_CTX and pkg_len=TRACE_CTX_LEN, whose body is the
# 16-byte context (8B trace_id + 4B parent span_id + 4B flags, all
# big-endian).  The frame elicits NO response; the daemon stashes the
# context on the connection and applies it to the NEXT request, whose
# spans then stitch cross-node by trace_id.  Append-only wire contract:
# an untraced request is byte-identical to the pre-trace protocol, so
# old daemons and old clients interoperate untraced.
# ---------------------------------------------------------------------------

TRACE_CTX_LEN = 16
TRACE_FLAG_SAMPLED = 1      # context carried an explicit client sample
TRACE_FLAG_SLOW = 2         # span force-retained by the slow-request gate

_TRACE_CTX_STRUCT = struct.Struct(">QII")


def pack_trace_ctx(trace_id: int, span_id: int, flags: int = TRACE_FLAG_SAMPLED) -> bytes:
    """16-byte TRACE_CTX frame body (big-endian, like every wire int)."""
    return _TRACE_CTX_STRUCT.pack(trace_id & (2**64 - 1),
                                  span_id & (2**32 - 1),
                                  flags & (2**32 - 1))


def unpack_trace_ctx(buf: bytes) -> tuple[int, int, int]:
    """(trace_id, parent_span_id, flags) from a TRACE_CTX frame body."""
    if len(buf) < TRACE_CTX_LEN:
        raise ValueError(f"short trace ctx: {len(buf)} < {TRACE_CTX_LEN}")
    return _TRACE_CTX_STRUCT.unpack_from(buf)

# ---------------------------------------------------------------------------
# Request QoS / admission control (fastdfs_tpu extension; no reference
# equivalent — upstream FastDFS queues past saturation unboundedly).
#
# Every request has a priority class.  A tagged request is prefixed by
# one PRIORITY frame: a normal 10-byte header with cmd=PRIORITY and
# pkg_len=PRIORITY_FRAME_LEN whose body is the single class byte.  Like
# TRACE_CTX the frame elicits NO response; the daemon stashes the class
# on the connection and applies it to the NEXT request.  Untagged
# requests default by opcode class (DefaultPriorityClass below —
# scrub/rebalance/sync traffic is born BACKGROUND), so an un-upgraded
# client is byte-identical to the pre-QoS protocol and still gets sane
# shedding behavior.
#
# The admission ladder (native/storage/admission.h AdmissionController):
#   level 0  admit everything
#   level 1  shed BACKGROUND
#   level 2  shed BULK + BACKGROUND
#   level 3  shed everything but CONTROL + INTERACTIVE (reads)
# i.e. a class is admitted at level L iff  class + L <= 4.  A shed
# request is answered EBUSY with an 8-byte big-endian retry-after hint
# in milliseconds as the response body; the client backs off (with
# jitter) instead of hammering a saturated daemon.
# ---------------------------------------------------------------------------

PRIORITY_FRAME_LEN = 1


class PriorityClass(enum.IntEnum):
    """Request priority classes, best (never shed) first."""

    CONTROL = 0      # stats/health/admin plane — how operators see in
    INTERACTIVE = 1  # client reads: downloads, metadata, file info
    NORMAL = 2       # client writes: uploads, appends, deletes
    BULK = 3         # negotiated bulk ingest (recipe/chunk uploads)
    BACKGROUND = 4   # replication, recovery fetches, EC release


def admitted_at_level(priority_class: int, level: int) -> bool:
    """The ladder contract: class c is admitted at level L iff c + L <= 4
    (level 0 admits all; level 3 admits only control + reads).  Mirrors
    AdmissionController::Admit — pinned by the fdfs_codec
    admission-ladder golden."""
    return level <= 0 or priority_class + level <= PriorityClass.BACKGROUND


def pack_priority(priority_class: int) -> bytes:
    """1-byte PRIORITY frame body."""
    if not 0 <= priority_class <= 0xFF:
        raise ValueError(f"bad priority class: {priority_class}")
    return bytes([priority_class])


def unpack_priority(buf: bytes) -> int:
    if len(buf) < PRIORITY_FRAME_LEN:
        raise ValueError("short priority frame")
    return buf[0]


def priority_frame(priority_class: int) -> bytes:
    """The full prefix frame (header + class byte) sent before a tagged
    request; elicits no response."""
    return pack_header(PRIORITY_FRAME_LEN, StorageCmd.PRIORITY) \
        + pack_priority(priority_class)


def pack_retry_after(retry_after_ms: int) -> bytes:
    """EBUSY shed-response body: the daemon's backoff hint."""
    return long2buff(int(retry_after_ms))


def unpack_retry_after(buf: bytes) -> int:
    """Retry-after ms from an EBUSY body; 0 when the body carries none
    (older daemons and non-admission EBUSYs answer status-only)."""
    if len(buf) < 8:
        return 0
    return max(buff2long(buf), 0)


# Untagged requests default by opcode (the C++ mirror is
# DefaultPriorityClass in native/storage/admission.cc; the two tables
# are pinned against each other by the fdfs_codec priority-frame
# golden).  Keyed by raw cmd value; anything unlisted is NORMAL.
_STORAGE_PRIORITY_DEFAULTS: dict[int, int] = {}


def default_priority_class(cmd: int) -> int:
    """Born-priority of an untagged storage-port request."""
    if not _STORAGE_PRIORITY_DEFAULTS:
        S, P = StorageCmd, PriorityClass
        for c in (S.STAT, S.TRACE_DUMP, S.EVENT_DUMP, S.METRICS_HISTORY,
                  S.HEAT_TOP, S.SCRUB_STATUS, S.SCRUB_KICK, S.EC_STATUS,
                  S.EC_KICK, S.HEALTH_STATUS, S.ADMISSION_STATUS,
                  S.PROFILE_CTL, S.PROFILE_DUMP, S.ACTIVE_TEST,
                  S.QUERY_FILE_INFO):
            _STORAGE_PRIORITY_DEFAULTS[int(c)] = int(P.CONTROL)
        for c in (S.DOWNLOAD_FILE, S.GET_METADATA, S.NEAR_DUPS):
            _STORAGE_PRIORITY_DEFAULTS[int(c)] = int(P.INTERACTIVE)
        for c in (S.UPLOAD_RECIPE, S.UPLOAD_CHUNKS):
            _STORAGE_PRIORITY_DEFAULTS[int(c)] = int(P.BULK)
        for c in (S.SYNC_CREATE_FILE, S.SYNC_DELETE_FILE,
                  S.SYNC_UPDATE_FILE, S.SYNC_CREATE_LINK,
                  S.SYNC_APPEND_FILE, S.SYNC_MODIFY_FILE,
                  S.SYNC_TRUNCATE_FILE, S.SYNC_QUERY_CHUNKS,
                  S.SYNC_CREATE_RECIPE, S.FETCH_ONE_PATH_BINLOG,
                  S.FETCH_RECIPE, S.FETCH_CHUNK, S.EC_RELEASE):
            _STORAGE_PRIORITY_DEFAULTS[int(c)] = int(P.BACKGROUND)
    return _STORAGE_PRIORITY_DEFAULTS.get(int(cmd), int(PriorityClass.NORMAL))


_HEADER_STRUCT = struct.Struct(">qBB")


class TrackerCmd(enum.IntEnum):
    """Tracker-port opcodes (reference: fdfs_proto.h TRACKER_PROTO_CMD_*)."""

    # storage -> tracker (cluster management)
    STORAGE_JOIN = 81
    QUIT = 82
    STORAGE_BEAT = 83
    STORAGE_REPORT_DISK_USAGE = 84
    STORAGE_REPLICA_CHG = 85
    STORAGE_SYNC_SRC_REQ = 86
    STORAGE_SYNC_DEST_REQ = 87
    STORAGE_SYNC_NOTIFY = 88
    STORAGE_SYNC_REPORT = 89
    STORAGE_SYNC_DEST_QUERY = 79
    STORAGE_REPORT_IP_CHANGED = 78
    STORAGE_CHANGELOG_REQ = 77
    STORAGE_PARAMETER_REQ = 76

    # client -> tracker (ops / listing)
    SERVER_LIST_ONE_GROUP = 90
    SERVER_LIST_ALL_GROUPS = 91
    SERVER_LIST_STORAGE = 92
    SERVER_DELETE_STORAGE = 93
    SERVER_SET_TRUNK_SERVER = 94
    # fastdfs_tpu extension: one-RPC cluster observability dump — tracker
    # role/leader plus every group and storage with the full named
    # last-beat stat payload (JSON body; optional 16B group filter).
    # Upstream's fdfs_monitor stitches this from LIST_ALL_GROUPS +
    # LIST_STORAGE binary structs instead.
    SERVER_CLUSTER_STAT = 95
    # fastdfs_tpu extension: dump the tracker's span ring buffer (empty
    # body -> JSON; shape per fastdfs_tpu.trace.decode_dump, covered by
    # the fdfs_codec trace-json cross-language golden).
    TRACE_DUMP = 96
    # fastdfs_tpu extension: the tracker's own stats-registry snapshot
    # (empty body -> the same {"counters","gauges","histograms"} JSON
    # contract as StorageCmd.STAT) — event-loop lag, dispatched ops,
    # request accounting.  `fdfs_top` polls this for the tracker row.
    STAT = 97
    # fastdfs_tpu extension: flight-recorder dump (empty body -> JSON
    # {"role","port","events":[...]}; shape per
    # fastdfs_tpu.monitor.decode_events, pinned by the fdfs_codec
    # event-json cross-language golden).
    EVENT_DUMP = 98
    # fastdfs_tpu extension: metrics-journal window dump (the tracker's
    # durable telemetry history; native/common/metrog.h).  Body = empty
    # or 8B BE since-ts (epoch µs; 0 = everything retained) -> JSON
    # {"role","port","snapshots":[{"ts_us",counters,gauges,histograms}]}
    # per fastdfs_tpu.monitor.decode_metrics_history; pinned by the
    # fdfs_codec metrics-history cross-language golden.  ENOTSUP when
    # journaling is off (metrics_journal_mb = 0).  Same contract as
    # StorageCmd.METRICS_HISTORY.
    METRICS_HISTORY = 99

    # client -> tracker (service queries; reference: tracker_deal_service_query_*)
    SERVICE_QUERY_STORE_WITHOUT_GROUP_ONE = 101
    SERVICE_QUERY_FETCH_ONE = 102
    SERVICE_QUERY_UPDATE = 103
    SERVICE_QUERY_STORE_WITH_GROUP_ONE = 104
    SERVICE_QUERY_FETCH_ALL = 105
    SERVICE_QUERY_STORE_WITHOUT_GROUP_ALL = 106
    SERVICE_QUERY_STORE_WITH_GROUP_ALL = 107

    RESP = 100
    ACTIVE_TEST = 111

    # tracker <-> tracker (leader election; reference: tracker_relationship.c)
    TRACKER_GET_STATUS = 70
    TRACKER_GET_SYS_FILES_START = 61
    TRACKER_GET_SYS_FILES_END = 62
    TRACKER_GET_ONE_SYS_FILE = 63
    TRACKER_PING_LEADER = 71
    TRACKER_NOTIFY_NEXT_LEADER = 72
    TRACKER_COMMIT_NEXT_LEADER = 73
    # fastdfs_tpu extension: followers fetch the per-group trunk-server
    # decision from the elected tracker leader instead of electing locally
    # (upstream: only the leader calls tracker_mem_find_trunk_server).
    TRACKER_GET_TRUNK_SERVER = 74

    # fastdfs_tpu extension: consistent-placement epoch fetch (the
    # store_lookup = 3 subsystem; arXiv:1406.2294 jump hash over the
    # ordered group list).  Empty request body -> response = 8B BE
    # placement version + 8B BE entry count + per entry (16B group name +
    # 1B state [0 active / 1 draining / 2 retired] + 8B BE member count +
    # per member (16B ip + 8B BE port)), members being the group's ACTIVE
    # storages.  Clients cache the table and compute
    # jump_hash(sha1(key)[:8], n_active) locally to route uploads without
    # a tracker round-trip; any routing failure or EBUSY refresh-and-
    # falls-back to the classic QUERY_STORE path.  Entry order is the
    # epoch contract: groups append on first join and NEVER reorder, so
    # adding group N+1 remaps only ~1/(N+1) of keys.  Followers serve
    # their last table adopted from the leader.  Pinned by the fdfs_codec
    # placement-wire cross-language golden.
    QUERY_PLACEMENT = 64
    # fastdfs_tpu extension: group lifecycle admin (leader-only; EBUSY
    # from a follower, like SERVER_SET_TRUNK_SERVER).  Request body =
    # 16B group name; OK response body = 8B BE new placement version.
    # DRAIN moves active -> draining (no new writes placed there; reads
    # and replication continue; storages start the paced rebalance
    # migrator), REACTIVATE moves draining -> active.  Idempotent; ENOENT
    # for an unknown group.  Pinned by the fdfs_codec group-admin
    # cross-language golden.
    GROUP_DRAIN = 65
    GROUP_REACTIVATE = 66
    # fastdfs_tpu extension: in-daemon sampling profiler + thread ledger
    # (OPERATIONS.md "Profiling & the thread ledger").  CTL body = 1B
    # action (1 = start, 0 = stop) + 8B BE hz + 8B BE duration seconds
    # (stop ignores the numbers; the 17-byte shape is pinned by the
    # fdfs_codec profile-ctl cross-language golden).  Start is
    # idempotent (re-arming restarts the capture window) and the daemon
    # auto-stops at the duration so a vanished client cannot leave the
    # timer armed.  ENOTSUP unless profile_max_hz > 0.  NOTE: the design
    # doc assigned the tracker 100/101, but 100 is RESP and 101 is
    # SERVICE_QUERY_STORE_WITHOUT_GROUP_ONE (both upstream-fixed), so
    # the tracker pair lives at 67/68 next to the other fastdfs_tpu
    # admin extensions; the storage pair keeps its planned 141/142.
    PROFILE_CTL = 67
    # Folded-stack dump: empty body -> JSON per
    # fastdfs_tpu.monitor.decode_profile (pinned by the fdfs_codec
    # profile-json golden).  ENOTSUP while a capture was never started.
    PROFILE_DUMP = 68
    # fastdfs_tpu extension: N x N differential gray-failure matrix
    # (OPERATIONS.md "Health, probes & gray failure").  Every storage
    # appends a health trailer to its beat (self gray score + its EWMA
    # scores ABOUT each group peer, append-only past the pinned stat
    # slots); the tracker folds those into per-node rows so a node most
    # *peers* report slow is flagged gray even while it self-reports
    # healthy.  Empty body -> JSON {"role","port","gray_threshold",
    # "nodes":[{"group","addr","self","peer_avg","reports","verdict",
    # "age_s","peers":{addr:score}}]} with verdict one of ok | gray |
    # sick | unknown.  Shape per fastdfs_tpu.monitor.decode_health_matrix;
    # pinned by the fdfs_codec health-matrix cross-language golden.
    HEALTH_MATRIX = 69

    # fastdfs_tpu extension: the elastic hot-replication map
    # (OPERATIONS.md "Elastic hot replication").  The tracker leader's
    # heat policy merges the per-node heat trailers riding each storage
    # beat (append-only past the health trailer: 1B version=2 + 8B BE
    # entry count + per entry (8B BE key_len + key + 8B BE cumulative
    # read hits + 8B BE cumulative read bytes)), promotes file-ids whose
    # windowed cluster-wide read EWMA crosses hot_promote_threshold to
    # extra replica groups, and serves the epoch-versioned map here.
    # Request body = empty (full map) or 8B BE since_version (delta).
    # Response = 8B BE map version + 1B full flag (1 = full snapshot;
    # 0 = delta relative to the requested since_version) + 8B BE entry
    # count + per entry (8B BE key_len + key + 8B BE extra-group count +
    # per group 16B group name).  A delta entry with ZERO extra groups is
    # a tombstone: the key was demoted — drop it from the cache.  Full
    # snapshots carry only live (published) entries.  Clients route hot
    # reads across home + extra replicas by
    # jump_hash(sha1("<file_id>#<range_index>")[:8], n_replicas) — the
    # established cache-affinity pick — and fall back to the classic
    # tracker path on any failure.  Pinned by the fdfs_codec hot-map
    # cross-language golden.
    QUERY_HOT_MAP = 75
    # fastdfs_tpu extension: storage -> tracker ack completing a hot
    # fan-out task (the tracker tasks the home group's elected member
    # via a beat-response trailer; the member pushes + byte-verifies,
    # then acks here, and ONLY then does the tracker publish the map
    # entry — verify-then-publish, so a routed read can never miss).
    # Body = 16B home group + 1B task type (1 = replicate, 2 = drop) +
    # 8B BE key_len + key + 8B BE verified-group count + per group 16B
    # group name.  OK response body = empty.  Pinned by the fdfs_codec
    # hot-map cross-language golden.
    HOT_FANOUT_DONE = 80

    # fastdfs_tpu extension: distributed-tracing context prefix frame
    # (see TRACE_CTX_LEN above).  Deliberately the SAME value on both
    # ports (StorageCmd.TRACE_CTX) so framing code is shared.
    TRACE_CTX = 140
    # fastdfs_tpu extension: request-priority prefix frame (see
    # PRIORITY_FRAME_LEN above).  Same value on both ports
    # (StorageCmd.PRIORITY) so framing code is shared.  On the tracker
    # the class gates the EXPENSIVE observability dumps (cluster stat,
    # metrics history, trace/event/profile dumps are born BULK) while
    # beats, joins, and service queries stay CONTROL — a lagging
    # single-loop tracker sheds dashboards before it sheds the cluster.
    PRIORITY = 147
    # fastdfs_tpu extension: admission-controller snapshot.  Empty body
    # -> JSON {"role","port","enabled","level","level_name","pressure",
    # "ewma","tighten_threshold","relax_threshold","tightens","relaxes",
    # "retry_after_ms","admitted","shed","shed_by_class":{...}} per
    # fastdfs_tpu.monitor.decode_admission; pinned by the fdfs_codec
    # admission-json cross-language golden.  Same contract as
    # StorageCmd.ADMISSION_STATUS.
    ADMISSION_STATUS = 148


class StorageCmd(enum.IntEnum):
    """Storage-port opcodes (reference: fdfs_proto.h STORAGE_PROTO_CMD_*)."""

    UPLOAD_FILE = 11
    DELETE_FILE = 12
    SET_METADATA = 13
    DOWNLOAD_FILE = 14
    GET_METADATA = 15
    SYNC_CREATE_FILE = 16
    SYNC_DELETE_FILE = 17
    SYNC_UPDATE_FILE = 18
    SYNC_CREATE_LINK = 19
    CREATE_LINK = 20
    UPLOAD_SLAVE_FILE = 21
    QUERY_FILE_INFO = 22
    UPLOAD_APPENDER_FILE = 23
    APPEND_FILE = 24
    SYNC_APPEND_FILE = 25
    FETCH_ONE_PATH_BINLOG = 26

    # trunk subsystem (reference: storage/trunk_mgr/).  Opcodes 30-33
    # (upstream's trunk_sync.c binlog-shipping protocol) are deliberately
    # ABSENT: this rebuild replicates trunk slot writes through the main
    # binlog (op 'c'/'d' with trunk file-IDs, tests/test_trunk.py), so a
    # second replication channel would be dead surface.  The values stay
    # reserved for wire compatibility.
    TRUNK_ALLOC_SPACE = 27
    TRUNK_ALLOC_CONFIRM = 28
    TRUNK_FREE_SPACE = 29

    MODIFY_FILE = 34
    SYNC_MODIFY_FILE = 35
    TRUNCATE_FILE = 36
    SYNC_TRUNCATE_FILE = 37

    # fastdfs_tpu extension: dedup-engine sidecar RPCs (no reference
    # equivalent; carried on the same framing so the C++ daemons reuse one
    # codec).  Values chosen clear of the upstream table — later upstream
    # releases keep assigning the 38+ range (e.g. 38 becomes
    # REGENERATE_APPENDER_FILENAME), so ALL extensions live at 120+.
    DEDUP_FINGERPRINT = 120
    DEDUP_QUERY = 121
    DEDUP_COMMIT = 122
    DEDUP_NEARDUPS = 123
    # Like DEDUP_FINGERPRINT, but the caller already ran CDC (the C++
    # daemon's AVX2 gear chunker — same table, identical cut points) and
    # ships the cut offsets with the bytes: body = 8B session + 8B
    # base_offset + 8B n_cuts + n_cuts x 8B relative exclusive ends +
    # raw segment.  The engine then skips its own chunking pass — on a
    # host-limited link that halves the bytes the accelerator round-trip
    # has to move (CDC is branchy scalar work the CPU does at GB/s; the
    # hashing is the FLOP-heavy part that belongs on the TPU).
    DEDUP_FINGERPRINT_CUTS = 125

    # Chunk-aware replication (fastdfs_tpu extension; the reference ships
    # every logical byte for every replica, storage_sync.c).  A sender
    # whose file is stored as a recipe first asks the peer which chunks
    # it lacks, then ships the recipe plus ONLY the missing chunk bytes:
    #   SYNC_QUERY_CHUNKS: 16B group + 8B name_len + name + N x 20B raw
    #     digests -> response body N bytes (0 = present, 1 = needed);
    #     ENOTSUP when the peer has no chunk store (sender falls back to
    #     the full-copy SYNC_CREATE_FILE).
    #   SYNC_CREATE_RECIPE: 16B group + 8B name_len + 8B logical_size +
    #     8B chunk_count + 8B payload_len + name + per chunk (20B digest
    #     + 8B length + 1B needed) + concatenated needed chunk payloads.
    SYNC_QUERY_CHUNKS = 126
    SYNC_CREATE_RECIPE = 127

    # Chunk-aware disk recovery (fastdfs_tpu extension): the rebuilding
    # node PULLS recipes and only the chunk bytes its store lacks,
    # instead of re-downloading every logical byte (the reference's
    # storage_disk_recovery.c fetches full files).
    #   FETCH_RECIPE: 16B group + remote name -> 8B logical_size + 8B
    #     chunk_count + per chunk (20B raw digest + 8B length); ENOENT
    #     when the file is stored flat (caller downloads normally).
    #   FETCH_CHUNK: 16B group + 8B name_len + name + 8B count +
    #     count x (20B raw digest + 8B expect_len) -> the payloads
    #     concatenated in request order (lengths are known from the
    #     recipe).  BATCHED so a rebuild pays one round-trip per ~8 MB
    #     of missing bytes, not one per ~8 KB chunk.  ENOENT when any
    #     requested chunk is gone (caller falls back to a full download
    #     of that file).
    FETCH_RECIPE = 128
    FETCH_CHUNK = 129
    # Stats dump (fastdfs_tpu extension): empty body -> JSON snapshot of
    # the daemon's stats registry (per-opcode counters and latency
    # histograms, dedup hits/misses and bytes-saved-on-wire, per-peer
    # binlog sync lag, recovery chunk accounting).  The shape is the
    # registry contract: {"counters":{},"gauges":{},"histograms":{}} —
    # decoded by fastdfs_tpu.monitor and covered by a cross-language
    # golden test.
    STAT = 130
    # Span ring-buffer dump (fastdfs_tpu extension): empty body -> JSON
    # {"role","port","spans":[...]} per fastdfs_tpu.trace.decode_dump
    # (cross-language golden: fdfs_codec trace-json).
    TRACE_DUMP = 131
    # Dedup-aware negotiated upload (fastdfs_tpu extension; no reference
    # equivalent — upstream ships every byte of every upload).  The
    # client chunks + fingerprints locally (the same gear CDC + SHA1 the
    # daemons run, so cut points agree cluster-wide) and only ships
    # chunk bytes the storage's content-addressed ChunkStore lacks:
    #   UPLOAD_RECIPE: 1B store_path_index (0xFF = server picks) + 6B
    #     ext + 8B crc32 + 8B logical_size + 8B chunk_count + per chunk
    #     (20B raw digest + 8B length) -> response 8B session_id +
    #     chunk_count bytes (0 = present, 1 = needed), with the present
    #     chunks PINNED server-side (PinRecipe discipline) until the
    #     session commits, aborts, or times out.  ENOTSUP when the
    #     daemon has no chunk store (client falls back to UPLOAD_FILE;
    #     an OLDER daemon answers the unknown opcode with EINVAL, which
    #     the client treats the same way).
    #   UPLOAD_CHUNKS: 8B session_id + 8B payload_len + the needed
    #     chunks' payloads concatenated in recipe order.  The daemon
    #     verifies SHA1(payload) == digest per chunk (the replication
    #     receiver's check), assembles the file via PutAndRef + refs +
    #     recipe write, logs the binlog record, and answers exactly
    #     like UPLOAD_FILE (16B group + remote filename).  ENOENT when
    #     the session is unknown/expired (client falls back to a plain
    #     upload).
    UPLOAD_RECIPE = 132
    UPLOAD_CHUNKS = 133
    # Integrity engine (fastdfs_tpu extension; see native/storage/scrub.*).
    #   SCRUB_STATUS: empty body -> SCRUB_STAT_COUNT big-endian int64
    #     slots named by SCRUB_STAT_FIELDS (append-only; cross-language
    #     golden: fdfs_codec scrub-status).  ENOTSUP when the daemon has
    #     no chunk store (dedup off — nothing to scrub).
    #   SCRUB_KICK: empty body -> status 0 once a verify+GC pass has been
    #     scheduled (runs even when scrub_interval_s = 0, so operators
    #     and tests can drive passes deterministically).
    SCRUB_STATUS = 134
    SCRUB_KICK = 135
    # Sidecar RPC: batched chunk-integrity verify on the accelerator
    # (ops/sha1.sha1_batch) for the storage scrubber.  Body = 8B count +
    # count x (8B length + 20B expected raw SHA1) + the payloads
    # concatenated; response = count bytes (0 = digest matches,
    # 1 = mismatch).  The daemon falls back to its serial host SHA1 when
    # the sidecar is unreachable — scrubbing never blocks on the TPU.
    DEDUP_VERIFY = 136
    # Flight-recorder dump (fastdfs_tpu extension): empty body -> JSON
    # {"role","port","events":[{"seq","ts_us","severity","type","key",
    # "detail"}]} — the daemon's bounded ring of structured cluster
    # events (chunk quarantined/repaired/healed, GC sweeps, upload-
    # session expiry, dedup fallbacks, replication stalls, slow
    # requests, config anomalies).  Shape per
    # fastdfs_tpu.monitor.decode_events; pinned by the fdfs_codec
    # event-json cross-language golden.  Same contract as
    # TrackerCmd.EVENT_DUMP.
    EVENT_DUMP = 137
    # Metrics-journal window dump (fastdfs_tpu extension; see
    # native/common/metrog.h): every daemon appends a delta-encoded,
    # CRC-framed snapshot of its stats registry to a size-capped on-disk
    # ring each SLO tick, so rate/quantile time-series survive a crash
    # or restart.  Body = empty or 8B BE since-ts (epoch µs; 0 = all
    # retained history) -> JSON {"role","port","snapshots":[{"ts_us",
    # "counters","gauges","histograms"}]} — each snapshot is the full
    # absolute registry view (the on-disk delta encoding is a storage
    # detail, never on the wire).  Shape per
    # fastdfs_tpu.monitor.decode_metrics_history; pinned by the
    # fdfs_codec metrics-history cross-language golden.  ENOTSUP when
    # journaling is off (metrics_journal_mb = 0).
    METRICS_HISTORY = 138
    # Hot-key heat telemetry (fastdfs_tpu extension; see
    # native/common/heatsketch.h): a lock-striped space-saving top-K
    # sketch fed from the request-accounting choke point, keyed by
    # file-id for DOWNLOAD_FILE / uploads / FETCH_CHUNK, with per-op
    # request and byte counts.  Body = empty or 8B BE k (0 = the
    # daemon's heat_top_k default) -> JSON {"role","port","k","tracked",
    # "touches","entries":[{"key","hits","err_bound","bytes","ops":
    # {"download":{"count","bytes"},...}}]} sorted by hits descending.
    # Shape per fastdfs_tpu.monitor.decode_heat; pinned by the
    # fdfs_codec heat-top cross-language golden.  ENOTSUP when the
    # sketch is off (heat_top_k = 0).
    HEAT_TOP = 139
    # Trace-context prefix frame (same value as TrackerCmd.TRACE_CTX).
    TRACE_CTX = 140
    # Ranked near-dup report for a stored file, answered from the
    # sidecar's MinHash/LSH index.  Body = 16B group + remote filename;
    # response = text lines "<file_id> <score>".  ENOTSUP when the dedup
    # mode has no near index.
    NEAR_DUPS = 124
    # Sampling profiler + thread ledger, same contract as the tracker
    # pair (TrackerCmd.PROFILE_CTL / PROFILE_DUMP — CTL semantics and
    # body shape documented there; both pinned by the profile-ctl /
    # profile-json cross-language goldens).
    PROFILE_CTL = 141
    PROFILE_DUMP = 142
    # Erasure-coded cold tier (fastdfs_tpu extension; see
    # native/storage/ecstore.*).  Cold chunks past ec_demote_age_s are
    # encoded into RS(k+m) stripes by scrub stage 5, then the replicated
    # copies are released group-wide via a verify-then-release handover.
    #   EC_STATUS: empty body -> EC_STAT_COUNT big-endian int64 slots
    #     named by EC_STAT_FIELDS (append-only; cross-language golden:
    #     fdfs_codec ec-status).  ENOTSUP when EC is off (ec_k = 0) or
    #     the daemon has no chunk store.
    #   EC_KICK: empty body -> status 0 once an EC demote sweep has been
    #     scheduled with the next scrub pass (runs even when
    #     scrub_interval_s = 0, so operators and tests can drive
    #     demotion deterministically).  ENOTSUP when ec_k = 0.
    #   EC_RELEASE: the stripe owner tells a replica peer that a batch
    #     of chunk digests is now parity-protected on the owner, so the
    #     peer may drop its replicated payload bytes (refs and recipe
    #     metadata are retained; reads re-fetch via FETCH_CHUNK).  Body
    #     = 16B group + 8B BE count + count x (20B raw digest + 8B BE
    #     length); response = count bytes (0 = released, 1 = kept —
    #     e.g. pinned by an in-flight upload session or unknown here).
    #     Sent only AFTER the owner verified the stripe decodes
    #     byte-identical (rebalance.map discipline: release.map is
    #     fsynced before the first peer sees the batch).  Pinned by the
    #     fdfs_codec ec-stripe-layout cross-language golden alongside
    #     the on-disk stripe framing it protects.
    EC_STATUS = 143
    EC_KICK = 144
    EC_RELEASE = 145
    # Gray-failure health snapshot (fastdfs_tpu extension; see
    # native/common/healthmon.*).  The daemon's local view: the per-peer
    # EWMA RPC health table (fed passively from every outbound NetRpc
    # plus an active ACTIVE_TEST probe loop), the per-store-path disk
    # probe latencies, and the thread-watchdog state.  Empty body ->
    # JSON {"role","port","score","stalled_threads","probe":
    # {"read_us","write_us","threshold_ms"},"peers":[{"addr","op",
    # "score","rpc_ewma_us","error_pct","timeout_pct","ops","errors",
    # "timeouts","age_s"}]}.  Shape per
    # fastdfs_tpu.monitor.decode_health_status; pinned by the fdfs_codec
    # health-status cross-language golden.
    HEALTH_STATUS = 146
    # Request-priority prefix frame (same value as TrackerCmd.PRIORITY;
    # body = the single class byte, no response — see the admission
    # section above).  The class applies to the NEXT request on the
    # connection; untagged requests default by opcode
    # (default_priority_class), so sync/recovery/EC traffic is born
    # BACKGROUND and shed first when the admission ladder tightens.
    PRIORITY = 147
    # Admission-controller snapshot (contract documented on
    # TrackerCmd.ADMISSION_STATUS; pinned by the fdfs_codec
    # admission-json cross-language golden).  Always answers, even
    # while shedding — it is CONTROL class by construction.
    ADMISSION_STATUS = 148

    RESP = 100
    ACTIVE_TEST = 111


# ---------------------------------------------------------------------------
# Wire-contract annotations, consumed by native/gen_protocol.py when it
# emits native/protocol_manifest.json (the machine-readable contract
# tools/fdfs_lint.py checks the tree against).
#
# NO_WIRE_BODY names opcodes whose request AND response bodies are empty
# or pure status — nothing to pin with a golden.  Every other opcode
# carries a structured body; WIRE_GOLDENS maps those covered by an
# `fdfs_codec <name>` cross-language golden fixture.  An opcode with a
# wire body and no golden must be allowlisted (with a reason) in
# tools/fdfs_lint.py's golden-coverage check — adding an opcode without
# deciding its golden story fails the linter by design.
# ---------------------------------------------------------------------------

NO_WIRE_BODY = frozenset({
    "TrackerCmd.QUIT",            # empty body, no response
    "TrackerCmd.RESP",            # pseudo-opcode: the response header itself
    "TrackerCmd.ACTIVE_TEST",     # empty ping, status-only answer
    "StorageCmd.RESP",
    "StorageCmd.ACTIVE_TEST",
    "StorageCmd.EC_KICK",         # empty body, status-only answer
})

WIRE_GOLDENS = {
    "TrackerCmd.SERVER_CLUSTER_STAT": "stats-json",  # embeds beat-stat names
    "TrackerCmd.TRACE_DUMP": "trace-json",
    "TrackerCmd.STAT": "stats-json",
    "TrackerCmd.EVENT_DUMP": "event-json",
    "TrackerCmd.METRICS_HISTORY": "metrics-history",
    "TrackerCmd.TRACE_CTX": "trace-ctx",
    "StorageCmd.STAT": "stats-json",
    "StorageCmd.TRACE_DUMP": "trace-json",
    "StorageCmd.EVENT_DUMP": "event-json",
    "StorageCmd.METRICS_HISTORY": "metrics-history",
    "StorageCmd.HEAT_TOP": "heat-top",
    "StorageCmd.TRACE_CTX": "trace-ctx",
    "StorageCmd.SCRUB_STATUS": "scrub-status",
    "StorageCmd.UPLOAD_RECIPE": "ingest-wire",
    "StorageCmd.UPLOAD_CHUNKS": "ingest-wire",
    "TrackerCmd.QUERY_PLACEMENT": "placement-wire",
    "TrackerCmd.GROUP_DRAIN": "group-admin",
    "TrackerCmd.GROUP_REACTIVATE": "group-admin",
    "TrackerCmd.PROFILE_CTL": "profile-ctl",
    "TrackerCmd.PROFILE_DUMP": "profile-json",
    "StorageCmd.PROFILE_CTL": "profile-ctl",
    "StorageCmd.PROFILE_DUMP": "profile-json",
    "StorageCmd.EC_STATUS": "ec-status",
    "StorageCmd.EC_RELEASE": "ec-stripe-layout",
    "TrackerCmd.HEALTH_MATRIX": "health-matrix",
    "TrackerCmd.QUERY_HOT_MAP": "hot-map",
    "TrackerCmd.HOT_FANOUT_DONE": "hot-map",
    "StorageCmd.HEALTH_STATUS": "health-status",
    "StorageCmd.PRIORITY": "priority-frame",
    "TrackerCmd.PRIORITY": "priority-frame",
    "StorageCmd.ADMISSION_STATUS": "admission-json",
    "TrackerCmd.ADMISSION_STATUS": "admission-json",
}


class Status(enum.IntEnum):
    """Header status byte: 0 = OK, otherwise an errno-style code."""

    OK = 0
    ENOENT = 2
    EIO = 5
    EBUSY = 16
    EEXIST = 17
    EINVAL = 22
    ENOSPC = 28
    ENODATA = 61
    ENOTSUP = 95
    ECONNREFUSED = 111
    EALREADY = 114


class StorageStatus(enum.IntEnum):
    """Storage-server lifecycle states held by the tracker.

    Reference: ``tracker/tracker_types.h`` FDFS_STORAGE_STATUS_* (values
    flagged "verify" in SURVEY.md §3.4).
    """

    INIT = 0
    WAIT_SYNC = 1
    SYNCING = 2
    IP_CHANGED = 3
    DELETED = 4
    OFFLINE = 5
    ONLINE = 6
    ACTIVE = 7
    RECOVERY = 9
    NONE = 99


class StoreLookup(enum.IntEnum):
    """Upload group-selection policy (reference: tracker.conf store_lookup).

    JUMP_CONSISTENT is a fastdfs_tpu extension (no upstream equivalent):
    uploads place by jump_hash(sha1(client_key)) over the ordered list of
    ACTIVE groups in the placement epoch (TrackerCmd.QUERY_PLACEMENT), so
    adding group N+1 remaps only ~1/(N+1) of keys and draining a group
    has a deterministic re-placement target for every file.
    """

    ROUND_ROBIN = 0
    SPECIFIED_GROUP = 1
    LOAD_BALANCE = 2
    JUMP_CONSISTENT = 3


class StorePathPolicy(enum.IntEnum):
    """Store-path selection inside one server (reference: storage.conf
    store_path_mode? — upstream ``tracker.conf store_path`` 0=rr, 2=load
    balance)."""

    ROUND_ROBIN = 0
    LOAD_BALANCE = 2


class DownloadServer(enum.IntEnum):
    """Replica-selection policy for reads (reference: tracker.conf
    download_server)."""

    ROUND_ROBIN = 0
    SOURCE_FIRST = 1


@dataclass(frozen=True)
class Header:
    """Decoded wire header (reference: fdfs_proto.h TrackerHeader)."""

    pkg_len: int
    cmd: int
    status: int = 0

    def pack(self) -> bytes:
        return _HEADER_STRUCT.pack(self.pkg_len, self.cmd, self.status)


def pack_header(pkg_len: int, cmd: int, status: int = 0) -> bytes:
    """Encode the 10-byte header: int64-BE body length, cmd, status.

    Reference: ``fdfs_proto.c`` fills TrackerHeader via ``long2buff``.
    """
    return _HEADER_STRUCT.pack(pkg_len, cmd, status)


def unpack_header(buf: bytes) -> Header:
    if len(buf) < HEADER_SIZE:
        raise ValueError(f"short header: {len(buf)} < {HEADER_SIZE}")
    pkg_len, cmd, status = _HEADER_STRUCT.unpack_from(buf)
    if pkg_len < 0:
        raise ValueError(f"negative pkg_len {pkg_len}")
    return Header(pkg_len=pkg_len, cmd=cmd, status=status)


def long2buff(n: int) -> bytes:
    """Encode an int64 big-endian (reference: shared_func.c long2buff())."""
    return struct.pack(">q", n)


def buff2long(buf: bytes, offset: int = 0) -> int:
    """Decode a big-endian int64 (reference: shared_func.c buff2long())."""
    return struct.unpack_from(">q", buf, offset)[0]


def pack_group_name(group: str) -> bytes:
    """Fixed-width group-name field: NUL-padded to 16 bytes."""
    raw = group.encode("utf-8")
    if len(raw) > GROUP_NAME_MAX_LEN:
        raise ValueError(f"group name too long: {group!r}")
    return raw.ljust(GROUP_NAME_MAX_LEN, b"\x00")


def unpack_group_name(buf: bytes) -> str:
    return buf[:GROUP_NAME_MAX_LEN].rstrip(b"\x00").decode("utf-8")


def pack_ext_name(ext: str) -> bytes:
    """Fixed-width file-extension field (6 bytes, NUL-padded)."""
    raw = ext.encode("utf-8")
    if len(raw) > FILE_EXT_NAME_MAX_LEN:
        raise ValueError(f"ext name too long: {ext!r}")
    return raw.ljust(FILE_EXT_NAME_MAX_LEN, b"\x00")


def pack_prefix_name(prefix: str) -> bytes:
    """Fixed-width slave-file prefix field (16 bytes, NUL-padded).

    Character rules mirror the C++ codec's IsSlavePrefix (fileid.cc): no
    separators, dots, whitespace, or control bytes — the prefix lands in
    filesystem paths, so reject client-side what the server would refuse.
    """
    raw = prefix.encode("utf-8")
    if not raw or len(raw) > FILE_PREFIX_MAX_LEN or any(
            b <= 0x20 or b == 0x7F or b in b"/." for b in raw):
        raise ValueError(f"bad slave prefix: {prefix!r}")
    return raw.ljust(FILE_PREFIX_MAX_LEN, b"\x00")


def unpack_ext_name(buf: bytes) -> str:
    return buf[:FILE_EXT_NAME_MAX_LEN].rstrip(b"\x00").decode("utf-8")


def pack_metadata(meta: dict[str, str]) -> bytes:
    """Serialize metadata key/values with \\x02 field and \\x01 record
    separators (reference: fdfs_proto.h FDFS_FIELD/RECORD_SEPARATOR,
    client/storage_client.c fdfs_pack_metadata())."""
    if not meta:
        return b""
    recs = []
    for k, v in sorted(meta.items()):
        kb, vb = k.encode("utf-8"), v.encode("utf-8")
        if FIELD_SEPARATOR in kb or RECORD_SEPARATOR in kb:
            raise ValueError(f"metadata key contains separator: {k!r}")
        if FIELD_SEPARATOR in vb or RECORD_SEPARATOR in vb:
            raise ValueError(f"metadata value contains separator: {v!r}")
        recs.append(kb + FIELD_SEPARATOR + vb)
    return RECORD_SEPARATOR.join(recs)


def unpack_metadata(buf: bytes) -> dict[str, str]:
    if not buf:
        return {}
    meta: dict[str, str] = {}
    for rec in buf.split(RECORD_SEPARATOR):
        if not rec:
            continue
        k, _, v = rec.partition(FIELD_SEPARATOR)
        meta[k.decode("utf-8")] = v.decode("utf-8")
    return meta
