"""mime.types parser.

Reference: ``common/mime_file_parser.c`` — load_mime_types_from_file()
loads nginx-style ``conf/mime.types`` (``type ext1 ext2 ...;`` entries,
optionally wrapped in a ``types { ... }`` block) into an extension → type
map for the (legacy) HTTP serving path.
"""

from __future__ import annotations

DEFAULT_MIME_TYPE = "application/octet-stream"


def parse_mime_types(text: str) -> dict[str, str]:
    """ext (lowercase, no dot) -> mime type."""
    out: dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip().rstrip(";").strip()
        if not line or line in ("types {", "types{", "{", "}"):
            continue
        parts = line.split()
        if len(parts) < 2 or "/" not in parts[0]:
            continue
        for ext in parts[1:]:
            out[ext.lower().lstrip(".")] = parts[0]
    return out


def load_mime_types(path: str) -> dict[str, str]:
    with open(path, encoding="utf-8") as fh:
        return parse_mime_types(fh.read())


def mime_type_for(filename: str, table: dict[str, str]) -> str:
    ext = filename.rsplit(".", 1)[-1].lower() if "." in filename else ""
    return table.get(ext, DEFAULT_MIME_TYPE)
