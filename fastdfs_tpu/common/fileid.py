"""Self-describing file-ID codec.

Reference: FastDFS file IDs (``group1/M00/02/44/<base64>.ext``) encode
everything needed to locate and validate a file with **no metadata
database**: group name, store-path index, two-level subdirectory, and a
base64 blob packing source-storage IP, create timestamp, file size (with
flag bits) and CRC32.  Reference anchors:
``storage/storage_service.c:storage_gen_filename()``,
``common/fdfs_global.c:fdfs_check_data_filename()``,
``client/storage_client.c:fdfs_get_file_info()``.

Blob layout (20 bytes, big-endian, mirrors the upstream field order):

    [0:4]   source storage IPv4 (packed)
    [4:8]   create timestamp (uint32 unix seconds)
    [8:16]  file-size field: flags | uniquifier | true size (see below)
    [16:20] CRC32 of the file content

File-size field (int64):
    bit 62          appender-file flag   (upstream: FDFS_APPENDER_FILE_SIZE)
    bit 61          trunk-file flag      (upstream: FDFS_TRUNK_FILE_MARK_SIZE)
    bit 60          slave-file flag
    bits 48..59     12-bit uniquifier (per-server upload counter slice; keeps
                    IDs unique when ip+ts+crc collide)
    bits 0..47      true file size (256 TiB max)

Base64 uses the URL-safe alphabet (``-``/``_``) without padding: 20 bytes →
exactly 27 chars (= upstream FDFS_FILENAME_BASE64_LENGTH).  The alphabet is
FastDFS-*shaped*, not guaranteed bit-compatible (reference mount was empty
at survey time — SURVEY.md provenance warning).
"""

from __future__ import annotations

import base64
import binascii
import posixpath
import re
import struct
from dataclasses import dataclass

from fastdfs_tpu.common.protocol import FILENAME_BASE64_LENGTH

STORAGE_DATA_DIR_FORMAT = "%02X"
DEFAULT_SUBDIR_COUNT = 256

_SIZE_MASK = (1 << 48) - 1
_UNIQ_SHIFT = 48
_UNIQ_MASK = 0xFFF
FLAG_SLAVE = 1 << 60
FLAG_TRUNK = 1 << 61
FLAG_APPENDER = 1 << 62

_BLOB_STRUCT = struct.Struct(">IIqI")
# \Z (not $) so trailing newlines never sneak past; whitespace and control
# characters are excluded from group/ext classes — these strings arrive over
# the wire and end up in filesystem paths and logs.
# Prefix/ext character class mirrors the C++ codec (IsExt/IsSlavePrefix in
# fileid.cc): excludes '/', '.', whitespace AND all control bytes ≤ 0x20
# plus 0x7F, so both languages accept exactly the same IDs.
# Byte-class mirror of native/common/fileid.cc (IsSlavePrefix/ext check):
# reject '/', '.', control bytes, space, DEL — and nothing else.  A Unicode
# class like \s would also reject U+00A0/U+3000 etc., splitting the codec
# from the C++ side, which compares raw bytes only.
_SAFE = r"[^/.\x00-\x20\x7f]"
# Prefix cap is 2x the slave max: trunk IDs carry a 16-char slot-location
# segment first, optionally followed by a slave prefix (slave-of-trunk-
# master names).  Non-trunk IDs are re-checked against the 16 cap after
# the blob decode.
_FILE_ID_RE = re.compile(
    r"^(?P<group>[^\s/]{1,16})/M(?P<path>[0-9A-F]{2})/"
    r"(?P<sub1>[0-9A-F]{2})/(?P<sub2>[0-9A-F]{2})/"
    r"(?P<b64>[A-Za-z0-9_-]{27})(?P<prefix>" + _SAFE + r"{1,32})?"
    r"(?P<ext>\." + _SAFE + r"{1,6})?\Z"
)
_REMOTE_NAME_RE = re.compile(
    r"^M[0-9A-F]{2}/[0-9A-F]{2}/[0-9A-F]{2}/"
    r"[A-Za-z0-9_-]{27}(" + _SAFE + r"{1,32})?(\." + _SAFE + r"{1,6})?\Z"
)


@dataclass(frozen=True)
class TrunkLocation:
    """Slot location inside a trunk file (reference: FDFSTrunkFullInfo in
    storage/trunk_mgr/trunk_shared.h).  Carried in trunk file IDs as an
    extra 16-char base64 segment after the 27-char stem — the analogue of
    upstream's longer trunk logic filenames."""

    trunk_id: int    # trunk file number within the store path
    offset: int      # slot start (its 24-byte header) in the trunk file
    alloc_size: int  # whole slot size including the header


TRUNK_SUFFIX_LENGTH = 16  # base64(12 bytes)
_TRUNK_STRUCT = struct.Struct(">III")


def encode_trunk_suffix(loc: TrunkLocation) -> str:
    return _b64encode(_TRUNK_STRUCT.pack(loc.trunk_id, loc.offset,
                                         loc.alloc_size))


def decode_trunk_suffix(suffix: str) -> TrunkLocation:
    if len(suffix) != TRUNK_SUFFIX_LENGTH:
        raise ValueError(f"bad trunk suffix length: {len(suffix)}")
    raw = _b64decode(suffix)
    return TrunkLocation(*_TRUNK_STRUCT.unpack(raw))


@dataclass(frozen=True)
class FileInfo:
    """Decoded identity facts carried inside a file ID."""

    source_ip: str
    create_timestamp: int
    file_size: int
    crc32: int
    uniquifier: int = 0
    appender: bool = False
    trunk: bool = False
    slave: bool = False
    trunk_loc: TrunkLocation | None = None


@dataclass(frozen=True)
class FileId:
    """Parsed structural parts of a file ID string."""

    group: str
    store_path_index: int
    subdir1: int
    subdir2: int
    filename: str  # "<27 b64 chars>[.ext]"

    @property
    def remote_filename(self) -> str:
        """The part after the group name (what the storage protocol carries)."""
        return posixpath.join(
            f"M{self.store_path_index:02X}",
            STORAGE_DATA_DIR_FORMAT % self.subdir1,
            STORAGE_DATA_DIR_FORMAT % self.subdir2,
            self.filename,
        )

    def __str__(self) -> str:
        return f"{self.group}/{self.remote_filename}"


def pack_ip(ip: str) -> int:
    a, b, c, d = (int(x) for x in ip.split("."))
    for part in (a, b, c, d):
        if not 0 <= part <= 255:
            raise ValueError(f"bad IPv4 address {ip!r}")
    return (a << 24) | (b << 16) | (c << 8) | d


def unpack_ip(n: int) -> str:
    return f"{(n >> 24) & 0xFF}.{(n >> 16) & 0xFF}.{(n >> 8) & 0xFF}.{n & 0xFF}"


def _b64encode(blob: bytes) -> str:
    return base64.urlsafe_b64encode(blob).rstrip(b"=").decode("ascii")


def _b64decode(s: str) -> bytes:
    pad = (-len(s)) % 4
    return base64.urlsafe_b64decode(s + "=" * pad)


def subdirs_for_blob(blob: bytes, subdir_count: int = DEFAULT_SUBDIR_COUNT) -> tuple[int, int]:
    """Deterministic two-level subdirectory spread from the packed blob.

    Reference: upstream spreads files over ``subdir_count_per_path²``
    directories (``storage/storage_func.c:storage_make_data_dirs()``); the
    chosen pair is a pure function of the blob so any party holding the ID
    can compute the on-disk path.
    """
    h = binascii.crc32(blob)
    return ((h >> 16) & 0xFF) % subdir_count, (h & 0xFF) % subdir_count


def encode_file_id(
    group: str,
    store_path_index: int,
    source_ip: str,
    create_timestamp: int,
    file_size: int,
    crc32: int,
    ext: str = "",
    uniquifier: int = 0,
    appender: bool = False,
    trunk: bool = False,
    slave: bool = False,
    trunk_loc: TrunkLocation | None = None,
    subdir_count: int = DEFAULT_SUBDIR_COUNT,
) -> str:
    """Build a file-ID string (reference: storage_gen_filename())."""
    # Byte-length limits match the fixed-width wire fields
    # (protocol.pack_group_name / pack_ext_name) so every minted ID is
    # transmittable.
    if (not group or len(group.encode("utf-8")) > 16
            or any(c == "/" or c.isspace() or ord(c) < 0x20 for c in group)):
        raise ValueError(f"bad group name: {group!r}")
    ext = ext.lstrip(".")
    if ext and (len(ext.encode("utf-8")) > 6 or any(
            c in "/." or c.isspace() or ord(c) < 0x20 for c in ext)):
        raise ValueError(f"bad ext name: {ext!r}")
    if not 0 <= store_path_index <= 0xFF:
        raise ValueError(f"store_path_index out of range: {store_path_index}")
    if not 0 <= file_size <= _SIZE_MASK:
        raise ValueError(f"file_size out of range: {file_size}")
    if not 0 <= uniquifier <= _UNIQ_MASK:
        raise ValueError(f"uniquifier out of range: {uniquifier}")
    size_field = file_size | (uniquifier << _UNIQ_SHIFT)
    if appender:
        size_field |= FLAG_APPENDER
    if trunk:
        size_field |= FLAG_TRUNK
    if slave:
        size_field |= FLAG_SLAVE
    blob = _BLOB_STRUCT.pack(
        pack_ip(source_ip), create_timestamp & 0xFFFFFFFF, size_field, crc32 & 0xFFFFFFFF
    )
    if trunk != (trunk_loc is not None):
        raise ValueError("trunk flag requires trunk_loc (and vice versa)")
    sub1, sub2 = subdirs_for_blob(blob, subdir_count)
    name = _b64encode(blob)
    assert len(name) == FILENAME_BASE64_LENGTH
    if trunk_loc is not None:
        name += encode_trunk_suffix(trunk_loc)
    if ext:
        name += "." + ext
    return (
        f"{group}/M{store_path_index:02X}/"
        f"{STORAGE_DATA_DIR_FORMAT % sub1}/{STORAGE_DATA_DIR_FORMAT % sub2}/{name}"
    )


def decode_file_id(
    file_id: str, subdir_count: int = DEFAULT_SUBDIR_COUNT
) -> tuple[FileId, FileInfo]:
    """Parse and validate a file-ID string; inverse of :func:`encode_file_id`.

    Reference: ``fdfs_check_data_filename()`` + client-side
    ``fdfs_get_file_info()`` — download needs no index lookup because the ID
    itself names the group, path, and content facts.
    """
    m = _FILE_ID_RE.match(file_id)
    if m is None:
        raise ValueError(f"malformed file id: {file_id!r}")
    b64 = m.group("b64")
    blob = _b64decode(b64)
    ip_n, ts, size_field, crc = _BLOB_STRUCT.unpack(blob)
    prefix = m.group("prefix") or ""
    fid = FileId(
        group=m.group("group"),
        store_path_index=int(m.group("path"), 16),
        subdir1=int(m.group("sub1"), 16),
        subdir2=int(m.group("sub2"), 16),
        filename=b64 + prefix + (m.group("ext") or ""),
    )
    expect = subdirs_for_blob(blob, subdir_count)
    if expect != (fid.subdir1, fid.subdir2):
        raise ValueError(
            f"file id subdirs {fid.subdir1:02X}/{fid.subdir2:02X} do not match "
            f"blob hash {expect[0]:02X}/{expect[1]:02X}"
        )
    trunk = bool(size_field & FLAG_TRUNK)
    trunk_loc = None
    if trunk:
        # Trunk IDs: first 16 post-stem chars are the slot location
        # (disambiguated by the blob flag, as upstream does by the longer
        # trunk filename length); any remainder is a slave prefix — such a
        # slave is stored FLAT, so its trunk_loc stays None (the location
        # names the master's slot, not this file).
        if len(prefix) < TRUNK_SUFFIX_LENGTH:
            raise ValueError(f"trunk id missing location: {file_id!r}")
        try:
            loc = decode_trunk_suffix(prefix[:TRUNK_SUFFIX_LENGTH])
        except (ValueError, binascii.Error) as e:
            raise ValueError(f"bad trunk suffix in {file_id!r}") from e
        prefix = prefix[TRUNK_SUFFIX_LENGTH:]
        if not prefix:
            trunk_loc = loc
    elif len(prefix) > 16:
        raise ValueError(f"slave prefix too long: {file_id!r}")
    info = FileInfo(
        source_ip=unpack_ip(ip_n),
        create_timestamp=ts,
        file_size=size_field & _SIZE_MASK,
        crc32=crc,
        uniquifier=(size_field >> _UNIQ_SHIFT) & _UNIQ_MASK,
        appender=bool(size_field & FLAG_APPENDER),
        trunk=trunk,
        # A non-empty prefix after the base64 stem IS the slave marker
        # (reference: slave names are "<master stem><prefix>.<ext>").
        slave=bool(prefix) or (not trunk and bool(size_field & FLAG_SLAVE)),
        trunk_loc=trunk_loc,
    )
    return fid, info


def local_path(base_path: str, remote_filename: str) -> str:
    """Map a remote filename (``M00/XX/YY/name``) to the on-disk path
    ``<base_path>/data/XX/YY/name`` for the store path it names.

    Reference: storage daemons keep each store path's payload under
    ``<store_path>/data/`` (storage_func.c:storage_make_data_dirs()).
    """
    # Strict grammar — remote filenames arrive over the wire, so anything
    # loose here is a path traversal (``M00/../../etc`` must not escape).
    m = _REMOTE_NAME_RE.match(remote_filename)
    if m is None:
        raise ValueError(f"malformed remote filename: {remote_filename!r}")
    parts = remote_filename.split("/")
    return posixpath.join(base_path, "data", parts[1], parts[2], parts[3])
