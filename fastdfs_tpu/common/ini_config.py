"""FastDFS-style INI config reader.

Reference: libfastcommon ``ini_file_reader.c`` — a flat ``key = value``
format (no mandatory sections) with ``#`` comments, repeated keys (e.g.
multiple ``tracker_server`` lines), and an ``#include other.conf``
directive resolved relative to the including file.  The daemons' conf files
(``conf/tracker.conf``, ``conf/storage.conf``, ``conf/client.conf``) are
the de-facto documentation of every tunable, so keeping the syntax
compatible lets users carry their configs over.
"""

from __future__ import annotations

import os
import re
from typing import Iterable

_SIZE_SUFFIX = {"": 1, "B": 1, "K": 1 << 10, "KB": 1 << 10, "M": 1 << 20,
                "MB": 1 << 20, "G": 1 << 30, "GB": 1 << 30, "T": 1 << 40,
                "TB": 1 << 40}
_TIME_SUFFIX = {"": 1, "s": 1, "m": 60, "h": 3600, "d": 86400}
_TRUE = {"1", "yes", "true", "on"}
_FALSE = {"0", "no", "false", "off"}


class IniConfig:
    """Parsed config: every key maps to a list of values in file order."""

    def __init__(self) -> None:
        self._items: dict[str, list[str]] = {}

    # -- construction -----------------------------------------------------

    @classmethod
    def load(cls, path: str) -> "IniConfig":
        cfg = cls()
        cfg._load_file(path, seen=set())
        return cfg

    @classmethod
    def loads(cls, text: str, base_dir: str | None = None) -> "IniConfig":
        """Parse from a string.  ``#include`` directives are rejected unless
        ``base_dir`` says where to resolve them (a bare string has no
        containing file to be relative to)."""
        cfg = cls()
        cfg._parse_lines(text.splitlines(), base_dir=base_dir, seen=set())
        return cfg

    def _load_file(self, path: str, seen: set[str]) -> None:
        # `seen` is the *active include stack*, not all files ever loaded:
        # entries are removed on return so diamond includes are legal and
        # only true cycles are rejected.
        real = os.path.realpath(path)
        if real in seen:
            raise ValueError(f"#include cycle at {path}")
        seen.add(real)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                self._parse_lines(fh, base_dir=os.path.dirname(real), seen=seen)
        finally:
            seen.discard(real)

    def _parse_lines(self, lines: Iterable[str], base_dir: str | None,
                     seen: set[str]) -> None:
        for raw in lines:
            line = raw.strip()
            if not line or line.startswith(("#", ";")):
                m = re.match(r"#include\s+(\S.*)$", line)
                if m:
                    if base_dir is None:
                        raise ValueError(
                            "#include in a string config: pass base_dir to loads()")
                    self._load_file(os.path.join(base_dir, m.group(1).strip()), seen)
                continue
            if re.fullmatch(r"\[[^\]]*\]", line):
                continue  # section headers tolerated, flattened (upstream-compatible)
            key, sep, value = line.partition("=")
            if not sep:
                continue
            key = key.strip()
            value = value.strip()
            self._items.setdefault(key, []).append(value)

    # -- accessors ---------------------------------------------------------

    def get(self, key: str, default: str | None = None) -> str | None:
        vals = self._items.get(key)
        return vals[-1] if vals else default

    def get_all(self, key: str) -> list[str]:
        return list(self._items.get(key, []))

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key)
        return default if v is None or v == "" else int(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v is None or v == "":
            return default
        lv = v.lower()
        if lv in _TRUE:
            return True
        if lv in _FALSE:
            return False
        raise ValueError(f"bad boolean for {key}: {v!r}")

    def get_bytes(self, key: str, default: int = 0) -> int:
        """Parse sizes like ``256KB``, ``64MB``, ``4G`` (reference:
        ini_file_reader's iniGetByteValue used for buff_size etc.)."""
        v = self.get(key)
        if v is None or v == "":
            return default
        m = re.fullmatch(r"(\d+)\s*([A-Za-z]*)", v)
        if not m or m.group(2).upper() not in _SIZE_SUFFIX:
            raise ValueError(f"bad size for {key}: {v!r}")
        return int(m.group(1)) * _SIZE_SUFFIX[m.group(2).upper()]

    def get_seconds(self, key: str, default: int = 0) -> int:
        """Parse durations like ``30``, ``5m``, ``1h``, ``1d``."""
        v = self.get(key)
        if v is None or v == "":
            return default
        m = re.fullmatch(r"(\d+)\s*([smhdSMHD]?)", v)
        if not m:
            raise ValueError(f"bad duration for {key}: {v!r}")
        return int(m.group(1)) * _TIME_SUFFIX[m.group(2).lower()]

    def keys(self) -> list[str]:
        return list(self._items.keys())

    def __contains__(self, key: str) -> bool:
        return key in self._items
