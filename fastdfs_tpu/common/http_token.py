"""Anti-leech HTTP token.

Reference: ``common/fdfs_http_shared.c`` — fdfs_http_gen_token() /
fdfs_http_check_token(): ``token = md5(file_uri + secret_key + ts)`` as a
32-char lowercase hex string, carried as ``?token=...&ts=...`` by the web
edge; valid while |now - ts| is within the configured ttl.  Bit-compatible
with native/common/http_token.cc (cross-checked by golden tests).
"""

from __future__ import annotations

import hashlib
import hmac


def http_gen_token(file_uri: str, secret: str, ts: int) -> str:
    payload = file_uri.encode() + secret.encode() + str(ts).encode()
    return hashlib.md5(payload).hexdigest()


def http_check_token(token: str, file_uri: str, secret: str, ts: int,
                     now: int, ttl_seconds: int) -> bool:
    if ttl_seconds > 0 and abs(now - ts) > ttl_seconds:
        return False
    return hmac.compare_digest(token, http_gen_token(file_uri, secret, ts))
