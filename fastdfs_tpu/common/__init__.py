"""L1 shared layer: wire protocol, file-ID codec, config parsing.

Reference analogue: ``common/`` (``fdfs_proto.h``, ``fdfs_global.c``,
``fdfs_shared_func.c``) in xigui2013/fastdfs.
"""

from fastdfs_tpu.common.protocol import (  # noqa: F401
    Header,
    HEADER_SIZE,
    TrackerCmd,
    StorageCmd,
    Status,
    pack_header,
    unpack_header,
    long2buff,
    buff2long,
)
from fastdfs_tpu.common.fileid import (  # noqa: F401
    FileId,
    FileInfo,
    encode_file_id,
    decode_file_id,
)
