"""MinHash near-duplicate fingerprints on TPU.

The tracker-side near-dup index (north star: "tracker's file-id index
backed by a jax.numpy cosine/MinHash similarity search") needs a compact
per-chunk signature whose agreement rate estimates Jaccard similarity of
the underlying shingle sets.  Pipeline:

1. byte shingles of size ``k`` hashed with a polynomial hash (vectorized
   as ``k`` shifted multiply-adds — same trick as the gear window);
2. ``P`` universal-hash permutations ``h_j(x) = a_j * x + b_j`` over
   uint32 (odd ``a_j``; multiply-shift family), min-reduced over shingle
   positions → signature ``(P,)`` uint32;
3. signature agreement fraction ≈ Jaccard(J) of shingle sets.

No reference equivalent — upstream FastDFS has only exact CRC32 (SURVEY.md
§0 north-star note).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_SHINGLE = 5
DEFAULT_PERMS = 64

_MINHASH_SEED = 0x5F3759DF
_POLY_B = np.uint32(0x01000193)  # FNV-32 prime as shingle-hash base


def _perm_constants(num_perms: int) -> tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(_MINHASH_SEED & 0x7FFFFFFF)
    a = (rng.randint(0, 1 << 31, size=num_perms, dtype=np.uint64) * 2 + 1).astype(np.uint32)
    b = rng.randint(0, 1 << 32, size=num_perms, dtype=np.uint64).astype(np.uint32)
    return a, b


@functools.partial(jax.jit, static_argnames=("k",))
def shingle_hashes(data: jax.Array, k: int = DEFAULT_SHINGLE) -> jax.Array:
    """Polynomial hashes of all ``k``-byte shingles of uint8 ``(n,)`` data.

    Returns uint32 ``(n,)``; entry ``i`` hashes ``data[i : i+k]`` and the
    trailing ``k-1`` entries (incomplete windows) are masked to the hash of
    the shorter suffix — callers slice ``[: n-k+1]`` for exact semantics.
    """
    d = data.astype(jnp.uint32)
    h = jnp.zeros_like(d)
    for j in range(k):
        shifted = jnp.roll(d, -j).at[-j:].set(0) if j else d
        h = h * _POLY_B + shifted
    return h


_MIN_BLOCK = 512  # positions per scan step: keeps the (P, block)
                  # permuted-hash tile resident instead of an O(P*L) array


@functools.partial(jax.jit, static_argnames=("num_perms",))
def minhash_signature(hashes: jax.Array, num_perms: int = DEFAULT_PERMS,
                      valid: jax.Array | None = None) -> jax.Array:
    """MinHash signature of a set of shingle hashes.

    ``hashes``: uint32 ``(m,)``.  ``valid``: optional bool ``(m,)`` mask
    (padded positions excluded).  Returns uint32 ``(num_perms,)``.

    Computed as a running min over position blocks (lax.scan): the
    naive ``(P, m)`` permuted matrix is never materialized, so memory is
    O(P * block) regardless of chunk length.
    """
    a, b = _perm_constants(num_perms)
    av = jnp.asarray(a)[:, None]
    bv = jnp.asarray(b)[:, None]
    m = hashes.shape[0]
    pad = (-m) % _MIN_BLOCK
    h = jnp.pad(hashes, (0, pad))
    v = (jnp.pad(valid, (0, pad)) if valid is not None
         else jnp.pad(jnp.ones((m,), dtype=bool), (0, pad)))
    h_blocks = h.reshape(-1, _MIN_BLOCK)
    v_blocks = v.reshape(-1, _MIN_BLOCK)

    def body(carry, hv_block):
        hb, vb = hv_block
        perm = hb[None, :] * av + bv                      # (P, block)
        perm = jnp.where(vb[None, :], perm, jnp.uint32(0xFFFFFFFF))
        return jnp.minimum(carry, perm.min(axis=1)), None

    init = jnp.full((num_perms,), 0xFFFFFFFF, dtype=jnp.uint32)
    sig, _ = jax.lax.scan(body, init, (h_blocks, v_blocks))
    return sig


@functools.partial(jax.jit, static_argnames=("num_perms", "k"))
def minhash_batch(data: jax.Array, lengths: jax.Array,
                  num_perms: int = DEFAULT_PERMS,
                  k: int = DEFAULT_SHINGLE) -> jax.Array:
    """Signatures for a batch of chunks: uint8 ``(N, L)`` + lengths ``(N,)``
    → uint32 ``(N, num_perms)``."""

    def one(row, ln):
        h = shingle_hashes(row, k)
        pos = jnp.arange(row.shape[0], dtype=jnp.int32)
        valid = pos <= (ln - k)  # complete shingles only
        # Degenerate chunks shorter than k hash their zero-padded window.
        valid = jnp.where(ln >= k, valid, pos < jnp.maximum(ln, 1))
        return minhash_signature(h, num_perms, valid)

    return jax.vmap(one)(jnp.asarray(data, dtype=jnp.uint8),
                         jnp.asarray(lengths, dtype=jnp.int32))


def estimate_jaccard(sig_a: jax.Array, sig_b: jax.Array) -> jax.Array:
    """Agreement fraction of two signatures ≈ Jaccard similarity.

    Broadcasts: ``(…, P)`` vs ``(…, P)`` → ``(…,)`` float32.
    """
    return (sig_a == sig_b).mean(axis=-1).astype(jnp.float32)
