"""MinHash near-duplicate fingerprints on TPU (v2 "survivor sketch" spec).

The tracker-side near-dup index (north star: "tracker's file-id index
backed by a jax.numpy cosine/MinHash similarity search") needs a compact
per-chunk signature whose agreement rate estimates Jaccard similarity of
the underlying shingle sets.  The v1 spec permuted EVERY shingle hash
through all ``P`` universal hashes — ``P`` multiply-add-min triples per
byte, ~192 vector ops/byte, which capped the whole ingest pipeline at
~2.9 GB/s on a v5e chip (see tools/PROFILE_r03.md).  The v2 spec is a
TPU-first two-stage sketch with identical set semantics:

1. **Shingle hashes** — polynomial hash of every ``k``-byte window
   (unchanged from v1);
2. **Survivor sampling** — keep only hashes with ``h & SAMPLE_MASK == 0``
   (rate 1/256).  Sampling is keyed on the VALUE, so it is invariant to
   where content sits in the stream: two near-duplicate chunks sample
   (almost exactly) the same elements.  Jaccard of the sampled sets is an
   unbiased estimate of Jaccard of the full sets;
3. **Segment-min compaction** — the sparse survivors are compacted to a
   dense ``NUM_SEGMENTS``-wide vector ``z`` by taking the min surviving
   hash per segment (``segment = word_index mod NUM_SEGMENTS``; empty
   segments hold ``EMPTY``).  When two survivors share a segment the
   larger is dropped (~1-11% of survivors depending on chunk size) —
   a small position-dependent thinning that both the CPU reference and
   the TPU kernel apply identically;
4. **Permutation MinHash over survivors** — ``P`` universal-hash
   permutations ``h_j(x) = a_j * x + b_j`` min-reduced over the ~256
   survivors instead of all ~65k positions.  Signature agreement
   fraction ≈ Jaccard of the survivor (≈ shingle) sets.

Why this is the TPU shape of the problem: stage 2+3 are one cheap pass
(compare + select + min) that shrinks the element count 64-256x, so the
expensive ``P``-way permutation work runs on 1/64th of the data and the
whole sketch drops from ~192 to ~25 vector ops per ingested byte.

A chunk with no survivors signs as all-``EMPTY``; ``EMPTY`` is neutral
in element-wise mins, so file-level signatures (min over chunk
signatures) remain "MinHash of the union of the chunks' survivor sets".

No reference equivalent — upstream FastDFS has only exact CRC32
(SURVEY.md §0 north-star note).  Bit-exactness of the Pallas twin
(``ops/pallas_minhash.py``) against this reference is enforced by
``tests/test_pallas_kernels.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_SHINGLE = 5
DEFAULT_PERMS = 64

SAMPLE_MASK = np.uint32(0xFF)   # keep h iff (h & SAMPLE_MASK) == 0: rate 1/256
NUM_SEGMENTS = 1024             # z width; segment = word_index % NUM_SEGMENTS
EMPTY = np.uint32(0xFFFFFFFF)   # empty-segment sentinel, neutral under min

_MINHASH_SEED = 0x5F3759DF
_POLY_B = np.uint32(0x01000193)  # FNV-32 prime as shingle-hash base


def _perm_constants(num_perms: int) -> tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(_MINHASH_SEED & 0x7FFFFFFF)
    a = (rng.randint(0, 1 << 31, size=num_perms, dtype=np.uint64) * 2 + 1).astype(np.uint32)
    b = rng.randint(0, 1 << 32, size=num_perms, dtype=np.uint64).astype(np.uint32)
    return a, b


@functools.partial(jax.jit, static_argnames=("k",))
def shingle_hashes(data: jax.Array, k: int = DEFAULT_SHINGLE) -> jax.Array:
    """Polynomial hashes of all ``k``-byte shingles of uint8 ``(n,)`` data.

    Returns uint32 ``(n,)``; entry ``i`` hashes ``data[i : i+k]`` and the
    trailing ``k-1`` entries (incomplete windows) are masked to the hash of
    the shorter suffix — callers slice ``[: n-k+1]`` for exact semantics.
    """
    d = data.astype(jnp.uint32)
    h = jnp.zeros_like(d)
    for j in range(k):
        shifted = jnp.roll(d, -j).at[-j:].set(0) if j else d
        h = h * _POLY_B + shifted
    return h


def _valid_mask(n: int, lengths: jax.Array, k: int) -> jax.Array:
    """(N, n) bool: complete-shingle positions (degenerate chunks shorter
    than ``k`` hash their zero-padded window at positions < max(len, 1))."""
    pos = jnp.arange(n, dtype=jnp.int32)[None, :]
    lens = lengths.astype(jnp.int32)[:, None]
    valid = pos <= (lens - k)
    return jnp.where(lens >= k, valid, pos < jnp.maximum(lens, 1))


@functools.partial(jax.jit, static_argnames=("k",))
def survivor_segmin(data: jax.Array, lengths: jax.Array,
                    k: int = DEFAULT_SHINGLE) -> jax.Array:
    """Stages 1-3 of the sketch: uint8 ``(N, L)`` + lengths ``(N,)`` →
    uint32 ``(N, NUM_SEGMENTS)`` survivor vector ``z``.

    ``z[s]`` is the smallest surviving shingle hash whose byte position
    ``p`` satisfies ``(p // 4) % NUM_SEGMENTS == s`` (word-granular
    striding, so ``z`` is independent of the padded container length),
    or ``EMPTY`` when no survivor maps to ``s``.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    lengths = jnp.asarray(lengths, dtype=jnp.int32)
    n, L = data.shape
    block = 4 * NUM_SEGMENTS
    pad = (-L) % block
    h = jax.vmap(lambda row: shingle_hashes(row, k))(
        jnp.pad(data, ((0, 0), (0, pad))))
    surv = _valid_mask(L + pad, lengths, k) & ((h & SAMPLE_MASK) == 0)
    hm = jnp.where(surv, h, EMPTY)
    # position p = block*b + 4*s + r  →  word p//4 = NUM_SEGMENTS*b + s,
    # so a plain reshape groups positions by segment.
    return hm.reshape(n, (L + pad) // block, NUM_SEGMENTS, 4).min(axis=(1, 3))


_MIN_BLOCK = 512  # positions per scan step: keeps the (P, block)
                  # permuted-hash tile resident instead of an O(P*L) array


@functools.partial(jax.jit, static_argnames=("num_perms",))
def minhash_signature(hashes: jax.Array, num_perms: int = DEFAULT_PERMS,
                      valid: jax.Array | None = None) -> jax.Array:
    """MinHash signature of a set of element hashes (stage 4).

    ``hashes``: uint32 ``(m,)``.  ``valid``: optional bool ``(m,)`` mask
    (excluded positions contribute nothing).  Returns uint32
    ``(num_perms,)``; all-invalid input signs as all-``EMPTY``.

    Computed as a running min over position blocks (lax.scan): the
    naive ``(P, m)`` permuted matrix is never materialized, so memory is
    O(P * block) regardless of input length.
    """
    a, b = _perm_constants(num_perms)
    av = jnp.asarray(a)[:, None]
    bv = jnp.asarray(b)[:, None]
    m = hashes.shape[0]
    pad = (-m) % _MIN_BLOCK
    h = jnp.pad(hashes, (0, pad))
    v = (jnp.pad(valid, (0, pad)) if valid is not None
         else jnp.pad(jnp.ones((m,), dtype=bool), (0, pad)))
    h_blocks = h.reshape(-1, _MIN_BLOCK)
    v_blocks = v.reshape(-1, _MIN_BLOCK)

    def body(carry, hv_block):
        hb, vb = hv_block
        perm = hb[None, :] * av + bv                      # (P, block)
        perm = jnp.where(vb[None, :], perm, EMPTY)
        return jnp.minimum(carry, perm.min(axis=1)), None

    init = jnp.full((num_perms,), EMPTY, dtype=jnp.uint32)
    sig, _ = jax.lax.scan(body, init, (h_blocks, v_blocks))
    return sig


@functools.partial(jax.jit, static_argnames=("num_perms", "k"))
def minhash_batch(data: jax.Array, lengths: jax.Array,
                  num_perms: int = DEFAULT_PERMS,
                  k: int = DEFAULT_SHINGLE) -> jax.Array:
    """Signatures for a batch of chunks: uint8 ``(N, L)`` + lengths ``(N,)``
    → uint32 ``(N, num_perms)``.

    CONTRACT: rows must be zero past their length (shared with
    ``sha1_batch``); the survivor stage hashes padded windows and relies
    on the validity mask to exclude them.
    """
    z = survivor_segmin(data, lengths, k)
    return jax.vmap(
        lambda zr: minhash_signature(zr, num_perms, zr != EMPTY))(z)


def estimate_jaccard(sig_a: jax.Array, sig_b: jax.Array) -> jax.Array:
    """Agreement fraction of two signatures ≈ Jaccard similarity.

    Broadcasts: ``(…, P)`` vs ``(…, P)`` → ``(…,)`` float32.
    """
    return (sig_a == sig_b).mean(axis=-1).astype(jnp.float32)
