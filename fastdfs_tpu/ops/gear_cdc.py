"""Content-defined chunking via a gear rolling hash, position-parallel.

Replaces the sequential chunk loop of the reference upload path
(``storage/storage_dio.c:dio_write_file()`` — ``buff_size`` chunks with a
CRC32 carried across iterations) with TPU-parallel chunking.

The serial gear hash is ``h = (h << 1) + gear[b[i]]`` with a cut candidate
wherever ``h & mask == 0``.  Because ``<< 1`` pushes a byte's contribution
out of a 32-bit register after 32 steps, ``h`` at position ``i`` depends
only on the trailing 32-byte window:

    h[i] = sum_{k=0..31} gear[b[i-k]] << k        (mod 2^32)

which is computable *independently per position* — 32 shifted adds over the
whole buffer, fully vectorized on TPU lanes.  No seam reconciliation is
needed for the hash itself; the only sequential part is greedy cut
*selection* under min/max chunk-size constraints, which runs over the
sparse candidate list on the host.

Cut-point equality with the canonical serial algorithm (which resets the
hash at each chunk start) holds whenever ``min_size >= 32``: every position
eligible for a cut is at least ``min_size`` bytes past the previous cut, so
the 32-byte window never straddles a chunk boundary.  This is the
"blockwise CDC with seam fixup" design from SURVEY.md §5, validated
property-based in ``tests/test_gear_cdc.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Deterministic 256-entry gear table; fixed seed so every node in a cluster
# (and the CPU reference path) chunks identically.
_GEAR_SEED = 0x9E3779B9
GEAR_TABLE = np.random.RandomState(_GEAR_SEED & 0x7FFFFFFF).randint(
    0, 1 << 32, size=256, dtype=np.uint64
).astype(np.uint32)

WINDOW = 32

# Default chunking geometry (bytes).  avg 8 KiB => 13 mask bits.
DEFAULT_MIN_SIZE = 2048
DEFAULT_AVG_BITS = 13
DEFAULT_MAX_SIZE = 65536


def gear_hashes_ref(data: bytes | np.ndarray) -> np.ndarray:
    """Serial CPU reference: windowed gear hash at every position."""
    buf = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else np.asarray(data, dtype=np.uint8)
    out = np.zeros(len(buf), dtype=np.uint32)
    h = np.uint32(0)
    with np.errstate(over="ignore"):
        for i, b in enumerate(buf):
            h = np.uint32(h << np.uint32(1)) + GEAR_TABLE[b]
            out[i] = h
    return out


@functools.partial(jax.jit, static_argnames=())
def gear_hashes(data: jax.Array) -> jax.Array:
    """Position-parallel gear hashes: ``h[i]`` for every byte position.

    ``data`` is uint8 of shape ``(n,)``; returns uint32 ``(n,)`` equal to the
    serial rolling value at each position (exactly, for all positions).
    """
    g = jnp.asarray(GEAR_TABLE)[data.astype(jnp.int32)]  # (n,) uint32
    h = g
    for k in range(1, WINDOW):
        shifted = jnp.roll(g, k).at[:k].set(0)  # g[i-k], zero for i<k
        h = h + (shifted << np.uint32(k))
    return h


def candidate_mask(hashes: jax.Array, avg_bits: int = DEFAULT_AVG_BITS) -> jax.Array:
    """Boolean cut-candidate mask: positions where the low ``avg_bits`` of
    the gear hash are zero (expected chunk size ``2**avg_bits``)."""
    mask = np.uint32((1 << avg_bits) - 1)
    return (hashes & mask) == 0


def select_cuts(
    candidates: np.ndarray,
    n: int,
    min_size: int = DEFAULT_MIN_SIZE,
    max_size: int = DEFAULT_MAX_SIZE,
) -> list[int]:
    """Greedy cut selection under min/max chunk-size constraints.

    ``candidates`` are sorted candidate positions (cut *after* byte ``i``,
    i.e. chunk end ``i + 1``).  Returns exclusive end offsets of every chunk
    (final offset is ``n``).  Sequential but sparse — O(#cuts log #cands) on
    the host.
    """
    if min_size < WINDOW:
        raise ValueError(f"min_size must be >= {WINDOW} for cut-point "
                         f"equality with the serial reference")
    cuts: list[int] = []
    cand = np.asarray(candidates, dtype=np.int64)
    last = 0
    while n - last > max_size or (n - last >= min_size and len(cand)):
        lo = np.searchsorted(cand, last + min_size - 1, side="left")
        hi = np.searchsorted(cand, last + max_size - 1, side="right")
        if lo < hi:
            cut = int(cand[lo]) + 1
        elif n - last > max_size:
            cut = last + max_size
        else:
            break
        cuts.append(cut)
        last = cut
    if last < n:
        cuts.append(n)
    return cuts


def chunk_stream(
    data: bytes,
    min_size: int = DEFAULT_MIN_SIZE,
    avg_bits: int = DEFAULT_AVG_BITS,
    max_size: int = DEFAULT_MAX_SIZE,
) -> list[int]:
    """TPU-parallel CDC: returns exclusive chunk end offsets for ``data``.

    The buffer is zero-padded to the next power of two before the jitted
    hash pass: XLA compiles once per pow2 shape instead of once per file
    size, and trailing padding cannot affect ``h[i]`` for real positions
    (each depends only on the 32 bytes ending at ``i``).
    """
    if not data:
        return []
    n = len(data)
    padded = 1 << max(12, (n - 1).bit_length())  # >= 4 KiB, pow2
    buf = np.zeros(padded, dtype=np.uint8)
    buf[:n] = np.frombuffer(data, dtype=np.uint8)
    hashes = np.asarray(gear_hashes(jnp.asarray(buf)))[:n]
    cand = np.flatnonzero(np.asarray(candidate_mask(hashes, avg_bits)))
    return select_cuts(cand, n, min_size, max_size)


def chunk_stream_ref(
    data: bytes,
    min_size: int = DEFAULT_MIN_SIZE,
    avg_bits: int = DEFAULT_AVG_BITS,
    max_size: int = DEFAULT_MAX_SIZE,
) -> list[int]:
    """Canonical serial CDC (hash reset at each chunk start) — the CPU
    referee for cut-point equality tests."""
    if min_size < WINDOW:
        raise ValueError(f"min_size must be >= {WINDOW}")
    mask = np.uint32((1 << avg_bits) - 1)
    table = GEAR_TABLE
    buf = np.frombuffer(data, dtype=np.uint8)
    n = len(buf)
    cuts: list[int] = []
    last = 0
    h = np.uint32(0)
    pos = 0
    with np.errstate(over="ignore"):
        while pos < n:
            h = np.uint32(h << np.uint32(1)) + table[buf[pos]]
            size = pos - last + 1
            if (size >= min_size and (h & mask) == 0) or size >= max_size:
                cuts.append(pos + 1)
                last = pos + 1
                h = np.uint32(0)
            pos += 1
    if last < n:
        cuts.append(n)
    return cuts
