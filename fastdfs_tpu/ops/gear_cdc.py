"""Content-defined chunking via a gear rolling hash, position-parallel.

Replaces the sequential chunk loop of the reference upload path
(``storage/storage_dio.c:dio_write_file()`` — ``buff_size`` chunks with a
CRC32 carried across iterations) with TPU-parallel chunking.

The serial gear hash is ``h = (h << 1) + gear[b[i]]`` with a cut candidate
wherever ``h & mask == 0``.  Because ``<< 1`` pushes a byte's contribution
out of a 32-bit register after 32 steps, ``h`` at position ``i`` depends
only on the trailing 32-byte window:

    h[i] = sum_{k=0..31} gear[b[i-k]] << k        (mod 2^32)

which is computable *independently per position* — 32 shifted adds over the
whole buffer, fully vectorized on TPU lanes.  No seam reconciliation is
needed for the hash itself; the only sequential part is greedy cut
*selection* under min/max chunk-size constraints, which runs over the
sparse candidate list on the host.

Cut-point equality with the canonical serial algorithm (which resets the
hash at each chunk start) holds whenever ``min_size >= 32``: every position
eligible for a cut is at least ``min_size`` bytes past the previous cut, so
the 32-byte window never straddles a chunk boundary.  This is the
"blockwise CDC with seam fixup" design from SURVEY.md §5, validated
property-based in ``tests/test_gear_cdc.py`` / ``tests/test_cdc_kernels.py``.

Two throughput refinements from the vector-chunking literature (round 13):

- **Lane-parallel hashing** (arXiv:2505.21194): the jax path folds the
  byte stream into a ``(LANES, cols)`` grid with a 31-byte halo carried
  from the previous row, so the windowed sum vectorizes across both the
  TPU sublane and lane axes instead of one long roll chain.  Bit-identical
  to the 1-D formulation (the halo makes every kept window complete).
- **Skip-min evaluation** (arXiv:2508.05797): hash evaluation *skips* the
  ``min_size`` bytes after every accepted cut instead of rolling through
  them, restarting the hash at the first eligible position.  This moves
  boundaries relative to the default policy — cuts are content addresses —
  so it ships strictly as opt-in ``cdc_policy=CDC_POLICY_SKIPMIN`` with
  its own serial referee (``chunk_stream_skipmin_ref``), never as a
  default.  See OPERATIONS.md "Ingest kernels & chunking policies".
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np


def _fmix32(x: np.ndarray) -> np.ndarray:
    """murmur3 finalizer: the gear table's generator."""
    x = np.asarray(x, dtype=np.uint32).copy()
    x ^= x >> np.uint32(16)
    x *= np.uint32(0x85EBCA6B)
    x ^= x >> np.uint32(13)
    x *= np.uint32(0xC2B2AE35)
    x ^= x >> np.uint32(16)
    return x


# Chunker spec version: bumped whenever cut-point behavior changes (the
# table, window, or selection rule).  v2 = the fmix32 table (round 5).
# Dedup state built under another spec chunks the same content at
# different offsets, so exact-dedup hits would silently drop to ~0; the
# sidecar discards stale-spec state at load (reads/recipes are
# unaffected — chunk stores are content-addressed).
CDC_SPEC_VERSION = 2

# Cut-selection policies.  Policy is orthogonal to the spec version: the
# DEFAULT policy under spec v2 is frozen (golden-pinned), and SKIPMIN is
# a distinct, explicitly-chosen policy with different boundaries — state
# built under one policy must never be queried under the other (the
# sidecar discards snapshots on policy mismatch, like spec mismatch).
CDC_POLICY_DEFAULT = 1   # serial-equivalent rolling evaluation (frozen)
CDC_POLICY_SKIPMIN = 2   # skip min_size bytes after each cut (arXiv:2508.05797)

# Deterministic 256-entry gear table, defined as fmix32(byte+1) so it is
# COMPUTABLE, not just storable: a 256-entry gather lowers to a slow
# scalar loop on TPU (~45 MB/s measured on this chip), while the same
# lookup as inline fmix32 arithmetic runs at vector speed.  The C++
# chunker and the CPU reference paths keep using the materialized table
# (native/gen_gear.py regenerates gear_gen.h from this array), so every
# node still chunks identically.
GEAR_TABLE = _fmix32(np.arange(1, 257, dtype=np.uint32))

WINDOW = 32
_HALO = WINDOW - 1

# Lane-parallel fold geometry: 256 rows keeps the row length >= the halo
# for every pow2 buffer >= 8 KiB while giving XLA a (256, cols) grid that
# tiles the 8x128 VPU cleanly (sublane axis full, lane axis contiguous).
_LANES = 256
_LANE_MIN_BYTES = _LANES * WINDOW  # smallest fold where cols >= WINDOW > halo

# Reusable host staging buffers for device_put: on a remote-accelerator
# link, transferring a FRESH host allocation pays per-buffer setup
# (~30 MB/s observed) while a reused buffer streams at ~1.7 GB/s.
# Thread-local: concurrent fingerprint calls must not share staging.
# (device_put snapshots the buffer synchronously, so reuse right after
# dispatch is safe.)
_staging = threading.local()


def staging_buffer(size: int, slot: int = 0) -> np.ndarray:
    """Reusable host staging buffer, keyed by (size, slot).

    ``slot`` lets callers double-buffer: PJRT host-buffer donation
    semantics are backend-dependent (some clients hold the host buffer
    zero-copy until the transfer completes), so a caller that dispatches
    tile N+1 before fetching tile N must rotate >= 2 slots per size or
    risk overwriting bytes still in flight (ADVICE r5,
    dedup/engine.py fingerprint()).
    """
    bufs = getattr(_staging, "bufs", None)
    if bufs is None:
        bufs = _staging.bufs = {}
    key = (size, slot)
    buf = bufs.get(key)
    if buf is None:
        buf = bufs[key] = np.zeros(size, dtype=np.uint8)
    return buf


def staging_buffer_stats() -> dict:
    """Introspection for the growth audit: count + total bytes of live
    staging buffers on THIS thread (tests assert reuse, not realloc)."""
    bufs = getattr(_staging, "bufs", None) or {}
    return {
        "buffers": len(bufs),
        "bytes": int(sum(b.nbytes for b in bufs.values())),
        "keys": sorted(bufs.keys()),
    }

# Default chunking geometry (bytes).  avg 8 KiB => 13 mask bits.
DEFAULT_MIN_SIZE = 2048
DEFAULT_AVG_BITS = 13
DEFAULT_MAX_SIZE = 65536


def gear_hashes_ref(data: bytes | np.ndarray) -> np.ndarray:
    """Serial CPU reference: windowed gear hash at every position."""
    buf = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else np.asarray(data, dtype=np.uint8)
    out = np.zeros(len(buf), dtype=np.uint32)
    h = np.uint32(0)
    with np.errstate(over="ignore"):
        for i, b in enumerate(buf):
            h = np.uint32(h << np.uint32(1)) + GEAR_TABLE[b]
            out[i] = h
    return out


def _inline_gear(data: jax.Array) -> jax.Array:
    """Gear table values as inline fmix32 arithmetic (no gather)."""
    x = data.astype(jnp.uint32) + jnp.uint32(1)
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> jnp.uint32(16))


def _windowed_sum_1d(g: jax.Array) -> jax.Array:
    # Prefix doubling: S_w[i] = sum_{k<w} g[i-k] << k satisfies
    # S_2w[i] = S_w[i] + (S_w[i-w] << w), so the 32-term window needs
    # log2(32) = 5 shifted adds, not 31.
    h = g
    w = 1
    while w < WINDOW:
        shifted = jnp.roll(h, w).at[:w].set(0)  # S_w[i-w], zero for i<w
        h = h + (shifted << np.uint32(w))
        w <<= 1
    return h


def _windowed_sum_rows(g_ext: jax.Array) -> jax.Array:
    """Row-wise prefix-doubling windowed sum over ``(rows, cols)``."""
    h = g_ext
    w = 1
    while w < WINDOW:
        shifted = jnp.pad(h, ((0, 0), (w, 0)))[:, :-w]
        h = h + (shifted << np.uint32(w))
        w <<= 1
    return h


@functools.partial(jax.jit, static_argnames=())
def gear_hashes(data: jax.Array) -> jax.Array:
    """Position-parallel gear hashes: ``h[i]`` for every byte position.

    ``data`` is uint8 of shape ``(n,)``; returns uint32 ``(n,)`` equal to the
    serial rolling value at each position (exactly, for all positions).

    The table lookup is computed as inline fmix32 arithmetic (see
    ``GEAR_TABLE``) — pure vector ops, no gather.  Buffers large enough to
    fold are hashed lane-parallel: the stream reshapes to ``(_LANES,
    cols)`` and each row carries a 31-value halo from its predecessor, so
    every kept window is complete and the result is bit-identical to the
    1-D chain while the shifted adds vectorize across both grid axes
    (arXiv:2505.21194's row-folded formulation).
    """
    g = _inline_gear(data)
    n = data.shape[0]
    if n >= _LANE_MIN_BYTES and n % _LANES == 0:
        cols = n // _LANES
        g2 = g.reshape(_LANES, cols)
        # Row r's halo = the 31 trailing values of row r-1 (zeros for r=0):
        # exactly the bytes a 32-wide window at the row head reaches back to.
        halo = jnp.pad(g2[:-1, -_HALO:], ((1, 0), (0, 0)))
        g_ext = jnp.concatenate([halo, g2], axis=1)
        h = _windowed_sum_rows(g_ext)[:, _HALO:]
        return h.reshape(n)
    return _windowed_sum_1d(g)


def candidate_mask(hashes: jax.Array, avg_bits: int = DEFAULT_AVG_BITS) -> jax.Array:
    """Boolean cut-candidate mask: positions where the low ``avg_bits`` of
    the gear hash are zero (expected chunk size ``2**avg_bits``)."""
    mask = np.uint32((1 << avg_bits) - 1)
    return (hashes & mask) == 0


@functools.partial(jax.jit, static_argnames=("avg_bits", "k"))
def gear_candidates(data: jax.Array, n: jax.Array, avg_bits: int,
                    k: int) -> jax.Array:
    """Candidate positions, computed AND compacted on device.

    Returns the first ``k`` candidate positions within the first ``n``
    bytes (sorted, padded with ``len(data)``) as ONE array — on a
    remote-accelerator link every fetched array pays fixed latency, and
    the full per-position hash array (4 B/input byte) would cost more to
    fetch than the hashing itself.  The dense mask is never needed: cut
    selection only consumes the sparse candidates.  A full last slot
    signals possible overflow (caller falls back to the dense path).
    """
    h = gear_hashes(data)
    m = candidate_mask(h, avg_bits) & (jnp.arange(data.shape[0]) < n)
    return jnp.nonzero(m, size=k, fill_value=data.shape[0])[0]


def select_cuts(
    candidates: np.ndarray,
    n: int,
    min_size: int = DEFAULT_MIN_SIZE,
    max_size: int = DEFAULT_MAX_SIZE,
) -> list[int]:
    """Greedy cut selection under min/max chunk-size constraints.

    ``candidates`` are sorted candidate positions (cut *after* byte ``i``,
    i.e. chunk end ``i + 1``).  Returns exclusive end offsets of every chunk
    (final offset is ``n``).  Sequential but sparse — O(#cuts log #cands) on
    the host.
    """
    if min_size < WINDOW:
        raise ValueError(f"min_size must be >= {WINDOW} for cut-point "
                         f"equality with the serial reference")
    cuts: list[int] = []
    cand = np.asarray(candidates, dtype=np.int64)
    last = 0
    while n - last > max_size or (n - last >= min_size and len(cand)):
        lo = np.searchsorted(cand, last + min_size - 1, side="left")
        hi = np.searchsorted(cand, last + max_size - 1, side="right")
        if lo < hi:
            cut = int(cand[lo]) + 1
        elif n - last > max_size:
            cut = last + max_size
        else:
            break
        cuts.append(cut)
        last = cut
    if last < n:
        cuts.append(n)
    return cuts


def select_cuts_skipmin(
    data: bytes | np.ndarray,
    candidates: np.ndarray,
    n: int,
    min_size: int = DEFAULT_MIN_SIZE,
    avg_bits: int = DEFAULT_AVG_BITS,
    max_size: int = DEFAULT_MAX_SIZE,
) -> list[int]:
    """Skip-min cut selection from precomputed *windowed* candidates.

    Skip-min restarts the hash at the first eligible position after each
    cut (``last + min_size - 1``), so the hash at position ``p`` covers
    ``[start, p]`` clamped to the 32-byte window.  For
    ``p >= start + WINDOW - 1`` the window is full and the restart hash
    EQUALS the continuous windowed hash — the global candidate list
    applies verbatim.  Only the ``<= 31`` warm-up positions per chunk
    (partial windows) need fresh hashing, done vectorized on the slice.

    Needs the byte buffer (for warm-up hashing) in addition to the
    candidate list.  ``candidates`` must cover ``[0, n)`` densely (every
    windowed-hash candidate), as produced by ``gear_candidates`` /
    ``gear_candidates_np``.
    """
    if min_size < 1:
        raise ValueError("min_size must be >= 1")
    if max_size < min_size:
        raise ValueError("max_size must be >= min_size")
    buf = (np.frombuffer(bytes(data), dtype=np.uint8)
           if isinstance(data, (bytes, bytearray, memoryview))
           else np.asarray(data, dtype=np.uint8))
    mask = np.uint32((1 << avg_bits) - 1)
    cand = np.asarray(candidates, dtype=np.int64)
    cuts: list[int] = []
    last = 0
    while n - last > 0:
        if n - last < min_size:
            cuts.append(n)
            break
        start = last + min_size - 1       # first position a cut may land on
        forced = last + max_size - 1      # reaching this position always cuts
        cutpos = -1
        # Warm-up region: partial-window restart hashes, <= 31 positions.
        warm_end = min(start + WINDOW - 2, forced, n - 1)
        if warm_end >= start:
            wh = gear_hashes_np(buf[start:warm_end + 1])
            hits = np.nonzero((wh & mask) == 0)[0]
            if len(hits):
                cutpos = start + int(hits[0])
        if cutpos < 0:
            # Full-window region: reuse the global windowed candidates.
            lo = np.searchsorted(cand, start + WINDOW - 1, side="left")
            hi = np.searchsorted(cand, min(forced, n - 1), side="right")
            if lo < hi:
                cutpos = int(cand[lo])
        if cutpos >= 0:
            cuts.append(cutpos + 1)
            last = cutpos + 1
        elif n - last >= max_size:
            cuts.append(last + max_size)
            last = last + max_size
        else:
            cuts.append(n)
            break
    return cuts


def chunk_stream(
    data: bytes,
    min_size: int = DEFAULT_MIN_SIZE,
    avg_bits: int = DEFAULT_AVG_BITS,
    max_size: int = DEFAULT_MAX_SIZE,
    cdc_policy: int = CDC_POLICY_DEFAULT,
    _k_override: int | None = None,
) -> list[int]:
    """TPU-parallel CDC: returns exclusive chunk end offsets for ``data``.

    The buffer is zero-padded to the next power of two before the jitted
    hash pass: XLA compiles once per pow2 shape instead of once per file
    size, and trailing padding cannot affect ``h[i]`` for real positions
    (each depends only on the 32 bytes ending at ``i``).

    Only the sparse candidate list leaves the device (expected density
    ``2**-avg_bits``, fetched with 4x headroom); if a pathological input
    exceeds the headroom, the dense mask path recovers exactly.
    ``_k_override`` exists so tests can force that fallback.

    ``cdc_policy`` selects the boundary rule: ``CDC_POLICY_DEFAULT`` is
    cut-identical to ``chunk_stream_ref`` (the frozen content-address
    contract); ``CDC_POLICY_SKIPMIN`` is the opt-in skip-min rule checked
    against ``chunk_stream_skipmin_ref``.  Both share one hash pass.
    """
    if cdc_policy not in (CDC_POLICY_DEFAULT, CDC_POLICY_SKIPMIN):
        raise ValueError(f"unknown cdc_policy {cdc_policy}")
    if not data:
        return []
    n = len(data)
    padded = 1 << max(12, (n - 1).bit_length())  # >= 4 KiB, pow2
    buf = staging_buffer(padded)
    buf[:n] = np.frombuffer(data, dtype=np.uint8)
    buf[n:] = 0
    k = _k_override if _k_override is not None else max(
        padded >> max(avg_bits - 2, 0), 256)
    # device_put (NOT jnp.asarray, which re-wraps the buffer and misses
    # the reused-staging fast path) + ONE fetched array.
    dev = jax.device_put(buf)
    idx = np.asarray(jax.device_get(
        gear_candidates(dev, np.int32(n), avg_bits, k)))
    if idx[-1] >= padded:  # last slot unused => no overflow
        cand = idx[idx < padded].astype(np.int64)
    else:
        # Candidate buffer possibly overflowed (>4x the expected
        # density): fetch the dense mask once (exact, just slower)
        # rather than risk missed cut points.
        hashes = np.asarray(gear_hashes(dev))[:n]
        cand = np.flatnonzero(np.asarray(candidate_mask(hashes, avg_bits)))
    if cdc_policy == CDC_POLICY_SKIPMIN:
        return select_cuts_skipmin(buf[:n], cand, n, min_size, avg_bits,
                                   max_size)
    return select_cuts(cand, n, min_size, max_size)


def gear_hashes_np(data: bytes | np.ndarray) -> np.ndarray:
    """Vectorized NumPy twin of :func:`gear_hashes` (same prefix-doubling
    windowed sum, uint32 wraparound) — for hosts without an accelerator:
    the client-side fingerprint path must not pay a per-byte Python loop
    (``gear_hashes_ref``) or drag JAX into thin client processes."""
    buf = (np.frombuffer(bytes(data), dtype=np.uint8)
           if isinstance(data, (bytes, bytearray, memoryview))
           else np.asarray(data, dtype=np.uint8))
    with np.errstate(over="ignore"):
        x = buf.astype(np.uint32) + np.uint32(1)
        x ^= x >> np.uint32(16)
        x *= np.uint32(0x85EBCA6B)
        x ^= x >> np.uint32(13)
        x *= np.uint32(0xC2B2AE35)
        h = x ^ (x >> np.uint32(16))
        w = 1
        while w < WINDOW:
            shifted = np.zeros_like(h)
            shifted[w:] = h[:-w]
            h = h + (shifted << np.uint32(w))
            w <<= 1
    return h


# Host-path scan tile: large enough to amortize the 5 shifted-add passes,
# small enough that the working set (2 uint32 work buffers per byte) stays
# near L2 instead of streaming 4 B/byte of hashes through main memory.
_NP_TILE = 1 << 20

# Staging slots for the tiled host scan's two uint32 work buffers (hash
# accumulator + shift temporary).  Slots 0/1 are the engine's
# double-buffered device staging; keep these disjoint so a client that
# chunks AND fingerprints on one thread never aliases them.
_NP_WORK_SLOTS = (16, 17)


def _gear_hashes_np_into(buf_slice: np.ndarray, work_h: np.ndarray,
                         work_t: np.ndarray) -> np.ndarray:
    """``gear_hashes_np`` computed in-place inside caller-owned uint32
    work buffers (no per-call temporaries) — the tiled scan's inner loop.
    Returns a view of ``work_h``."""
    m = len(buf_slice)
    h = work_h[:m]
    tmp = work_t[:m]
    with np.errstate(over="ignore"):
        np.copyto(h, buf_slice)          # uint8 widens into the uint32 buffer
        h += np.uint32(1)
        h ^= h >> np.uint32(16)
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> np.uint32(13)
        h *= np.uint32(0xC2B2AE35)
        h ^= h >> np.uint32(16)
        w = 1
        while w < WINDOW:
            tmp[w:] = h[:-w]
            tmp[:w] = 0
            tmp <<= np.uint32(w)
            h += tmp
            w <<= 1
    return h


def gear_candidates_np(data: bytes | np.ndarray,
                       avg_bits: int = DEFAULT_AVG_BITS) -> np.ndarray:
    """Windowed-hash candidate positions, scanned in cache-sized tiles.

    Equal to ``np.nonzero(candidate_mask(gear_hashes_np(data)))[0]`` but
    never materializes the full 4-bytes-per-input-byte hash array: each
    1 MiB tile is hashed with a 31-byte halo from its predecessor (so
    every emitted position sees a full window) and only the sparse
    candidate indices survive.  This is the host-path analogue of the
    lane fold — same math, tiled for cache instead of lanes.  The two
    uint32 work buffers come from the thread-local staging pool, so
    repeated calls at any input size reuse ONE fixed allocation
    (asserted by tests/test_cdc_kernels.py's growth audit).
    """
    buf = (np.frombuffer(bytes(data), dtype=np.uint8)
           if isinstance(data, (bytes, bytearray, memoryview))
           else np.asarray(data, dtype=np.uint8))
    n = len(buf)
    mask = np.uint32((1 << avg_bits) - 1)
    if n <= 4096:
        # Tiny inputs: a per-call temporary beats pinning the ~8 MB
        # work pair for a client that only ever chunks small buffers.
        h = gear_hashes_np(buf)
        return np.nonzero((h & mask) == 0)[0]
    span = min(n, _NP_TILE + _HALO)
    work_h = staging_buffer(4 * (_NP_TILE + _HALO),
                            slot=_NP_WORK_SLOTS[0]).view(np.uint32)[:span]
    work_t = staging_buffer(4 * (_NP_TILE + _HALO),
                            slot=_NP_WORK_SLOTS[1]).view(np.uint32)[:span]
    out: list[np.ndarray] = []
    for t in range(0, n, _NP_TILE):
        lo = max(0, t - _HALO)
        h = _gear_hashes_np_into(buf[lo:t + _NP_TILE], work_h, work_t)
        seg = h[t - lo:]
        idx = np.nonzero((seg & mask) == 0)[0]
        if len(idx):
            out.append(idx.astype(np.int64) + t)
    if not out:
        return np.zeros(0, dtype=np.int64)
    return np.concatenate(out)


def chunk_stream_np(
    data: bytes,
    min_size: int = DEFAULT_MIN_SIZE,
    avg_bits: int = DEFAULT_AVG_BITS,
    max_size: int = DEFAULT_MAX_SIZE,
    cdc_policy: int = CDC_POLICY_DEFAULT,
) -> list[int]:
    """CPU-vectorized CDC with the exact cut points of ``chunk_stream`` /
    ``chunk_stream_ref`` (same table, window, and selection rule), or of
    ``chunk_stream_skipmin_ref`` under ``cdc_policy=CDC_POLICY_SKIPMIN``."""
    if cdc_policy not in (CDC_POLICY_DEFAULT, CDC_POLICY_SKIPMIN):
        raise ValueError(f"unknown cdc_policy {cdc_policy}")
    n = len(data)
    if n == 0:
        return []
    candidates = gear_candidates_np(data, avg_bits)
    if cdc_policy == CDC_POLICY_SKIPMIN:
        return select_cuts_skipmin(data, candidates, n, min_size, avg_bits,
                                   max_size)
    return select_cuts(candidates, n, min_size, max_size)


def chunk_stream_ref(
    data: bytes,
    min_size: int = DEFAULT_MIN_SIZE,
    avg_bits: int = DEFAULT_AVG_BITS,
    max_size: int = DEFAULT_MAX_SIZE,
) -> list[int]:
    """Canonical serial CDC (hash reset at each chunk start) — the CPU
    referee for cut-point equality tests."""
    if min_size < WINDOW:
        raise ValueError(f"min_size must be >= {WINDOW}")
    mask = np.uint32((1 << avg_bits) - 1)
    table = GEAR_TABLE
    buf = np.frombuffer(data, dtype=np.uint8)
    n = len(buf)
    cuts: list[int] = []
    last = 0
    h = np.uint32(0)
    pos = 0
    with np.errstate(over="ignore"):
        while pos < n:
            h = np.uint32(h << np.uint32(1)) + table[buf[pos]]
            size = pos - last + 1
            if (size >= min_size and (h & mask) == 0) or size >= max_size:
                cuts.append(pos + 1)
                last = pos + 1
                h = np.uint32(0)
            pos += 1
    if last < n:
        cuts.append(n)
    return cuts


def chunk_stream_skipmin_ref(
    data: bytes,
    min_size: int = DEFAULT_MIN_SIZE,
    avg_bits: int = DEFAULT_AVG_BITS,
    max_size: int = DEFAULT_MAX_SIZE,
) -> list[int]:
    """Serial referee for the skip-min policy (``cdc_policy=2``).

    After each accepted cut the scanner JUMPS ``min_size - 1`` bytes and
    restarts the hash at the first eligible position — the skipped bytes
    are never hashed (that is the throughput win: ~``min/avg`` of the
    stream is skipped).  A cut lands at the first restart-hash candidate,
    or is forced at ``max_size``.  Boundaries differ from the default
    policy, so this is a distinct content-address namespace.
    """
    if min_size < 1:
        raise ValueError("min_size must be >= 1")
    if max_size < min_size:
        raise ValueError("max_size must be >= min_size")
    mask = np.uint32((1 << avg_bits) - 1)
    table = GEAR_TABLE
    buf = np.frombuffer(data, dtype=np.uint8)
    n = len(buf)
    cuts: list[int] = []
    last = 0
    with np.errstate(over="ignore"):
        while n - last > 0:
            if n - last < min_size:
                cuts.append(n)
                break
            h = np.uint32(0)
            cut = -1
            end = min(last + max_size - 1, n - 1)
            for pos in range(last + min_size - 1, end + 1):
                h = np.uint32(h << np.uint32(1)) + table[buf[pos]]
                if (h & mask) == 0:
                    cut = pos + 1
                    break
            if cut < 0:
                cut = last + max_size if n - last >= max_size else n
            cuts.append(cut)
            if cut >= n:
                break
            last = cut
    return cuts
