"""Reed-Solomon RS(k, m) erasure coding over GF(2^8), vectorized.

The erasure-coded cold tier (native/storage/ecstore.*, scrub stage 5)
encodes k data shards into m parity shards so a stripe survives any m
shard losses at (k+m)/k storage overhead instead of the N-way replica
multiple — the "lightweight metadata + cheap parity" disaster-recovery
design of arXiv:2602.22237, with the GF matrix math treated as an
accelerator kernel in the arXiv:1202.3669 storage-engine framing.

Both encode and reconstruct are the SAME primitive — a (rows x k)
GF(2^8) matrix applied to k shards of length L:

    out[r, l] = XOR_i  mul(M[r, i], shards[i, l])

so this module ships one matmul in three disciplines (the gear_cdc
layout):

- ``gf_matmul_ref``  — serial Python referee, bit-for-bit the spec.
- ``gf_matmul_np``   — tiled NumPy: the 256x256 product table turns
  field mul into a gather, XOR-reduced across the k axis; columns are
  tiled cache-sized so the (rows, k, tile) intermediate stays in L2.
- ``gf_matmul``      — jax: the same gather expressed as advanced
  indexing into the product table (a (rows, k, 256) -> (rows, k, L)
  take) + an XOR lane reduction, jit-compiled per shape bucket.  Host
  bytes stage through the shared ``staging_buffer`` pool and move with
  ``device_put`` (gear_cdc discipline: reused staging streams at link
  speed where fresh allocations pay per-buffer setup).

The generator matrix is systematic Cauchy ([I; C] with C[j][i] =
inv(x_i ^ a_j), x_i = i, a_j = k + j — tables from the generated
``gf256`` module, pinned by the fdfs_codec gf-tables golden), so every
k x k submatrix is invertible and ANY k surviving shards reconstruct
the stripe.  ``decode_matrix`` inverts the surviving rows with
Gauss-Jordan over the field (k <= 32: host-side, microseconds).

Equivalence of all three paths on adversarial shapes is asserted by
tests/test_ec.py; the C++ codec (native/storage/ecstore.cc) runs the
same tables, checked by the native storage_test RS unit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .gf256 import GF_EXP, GF_LOG, cauchy_coeff, gf_inv, gf_mul
from .gear_cdc import staging_buffer

# RS geometry bounds.  k + m <= 255 is the field limit (Cauchy points
# must be distinct bytes); the practical clamp lives in storage config
# (ec_k <= 32, ec_m <= 8) — stripes wider than that stop paying.
MAX_SHARDS = 255

# 256x256 product table: PROD[a, b] = a * b in GF(2^8).  64 KiB — built
# once at import from the generated exp/log tables, shared by the NumPy
# and jax paths (the jax path closes over it as an on-device constant).
_EXP = np.asarray(GF_EXP, dtype=np.uint8)
_LOG = np.asarray(GF_LOG, dtype=np.int32)
PROD_TABLE = _EXP[_LOG[:, None] + _LOG[None, :]]
PROD_TABLE[0, :] = 0
PROD_TABLE[:, 0] = 0

# NumPy tiling: columns per tile.  The (rows, k, tile) gather
# intermediate for the worst supported geometry (k=32, rows=40) stays
# ~40 MB at 32 KiB columns — resident in LLC on the host CPUs we run.
_NP_TILE = 32 << 10


# ---------------------------------------------------------------------------
# Generator / decode matrices (host-side, tiny)
# ---------------------------------------------------------------------------

def parity_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) systematic Cauchy parity coefficients for RS(k, m)."""
    if k <= 0 or m < 0 or k + m > MAX_SHARDS:
        raise ValueError(f"bad RS geometry k={k} m={m}")
    return np.array([[cauchy_coeff(k, j, i) for i in range(k)]
                     for j in range(m)], dtype=np.uint8)


def encode_matrix(k: int, m: int) -> np.ndarray:
    """(k+m, k) full generator [I; C]: row s of the product is shard s."""
    return np.concatenate([np.eye(k, dtype=np.uint8), parity_matrix(k, m)])


def gf_invert_matrix(a: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse of a k x k matrix over GF(2^8).

    Raises ValueError on a singular matrix — impossible for Cauchy
    submatrices, so hitting it means corrupted shard indices.
    """
    a = np.array(a, dtype=np.uint8, copy=True)
    k = a.shape[0]
    if a.shape != (k, k):
        raise ValueError(f"not square: {a.shape}")
    inv = np.eye(k, dtype=np.uint8)
    for col in range(k):
        pivot = next((r for r in range(col, k) if a[r, col]), None)
        if pivot is None:
            raise ValueError(f"singular at column {col}")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        scale = gf_inv(int(a[col, col]))
        a[col] = PROD_TABLE[scale, a[col]]
        inv[col] = PROD_TABLE[scale, inv[col]]
        for r in range(k):
            f = int(a[r, col])
            if r != col and f:
                a[r] ^= PROD_TABLE[f, a[col]]
                inv[r] ^= PROD_TABLE[f, inv[col]]
    return inv


def decode_matrix(k: int, m: int, present: "list[int]") -> np.ndarray:
    """(k, k) matrix mapping k surviving shards back to the data shards.

    ``present`` names the k surviving shard indices (0..k-1 data,
    k..k+m-1 parity), in the order their rows will be stacked.
    """
    if len(present) != k:
        raise ValueError(f"need exactly k={k} present shards, got "
                         f"{len(present)}")
    if len(set(present)) != k or not all(0 <= s < k + m for s in present):
        raise ValueError(f"bad present set {present}")
    gen = encode_matrix(k, m)
    return gf_invert_matrix(gen[np.asarray(present, dtype=np.intp)])


# ---------------------------------------------------------------------------
# The GF matmul, three ways
# ---------------------------------------------------------------------------

def gf_matmul_ref(matrix: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """Serial referee: out[r, l] = XOR_i mul(M[r, i], shards[i, l])."""
    matrix = np.asarray(matrix, dtype=np.uint8)
    shards = np.atleast_2d(np.asarray(shards, dtype=np.uint8))
    rows, k = matrix.shape
    if shards.shape[0] != k:
        raise ValueError(f"matrix k={k} vs shards {shards.shape}")
    out = np.zeros((rows, shards.shape[1]), dtype=np.uint8)
    for r in range(rows):
        for i in range(k):
            c = int(matrix[r, i])
            for col in range(shards.shape[1]):
                out[r, col] ^= gf_mul(c, int(shards[i, col]))
    return out


def gf_matmul_np(matrix: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """Tiled NumPy path: product-table gather + XOR reduce over k."""
    matrix = np.asarray(matrix, dtype=np.uint8)
    shards = np.atleast_2d(np.ascontiguousarray(shards, dtype=np.uint8))
    rows, k = matrix.shape
    if shards.shape[0] != k:
        raise ValueError(f"matrix k={k} vs shards {shards.shape}")
    length = shards.shape[1]
    out = np.empty((rows, length), dtype=np.uint8)
    for lo in range(0, length, _NP_TILE):
        tile = shards[:, lo:lo + _NP_TILE]        # (k, T)
        # (rows, k, T) product gather, XOR-reduced across the k axis
        prod = PROD_TABLE[matrix[:, :, None], tile[None, :, :]]
        out[:, lo:lo + _NP_TILE] = np.bitwise_xor.reduce(prod, axis=1)
    return out


@functools.partial(jax.jit, static_argnames=("rows", "k"))
def _gf_matmul_jit(matrix: jnp.ndarray, shards: jnp.ndarray,
                   table: jnp.ndarray, rows: int, k: int) -> jnp.ndarray:
    # (rows, k, L) gather via advanced indexing into the product table,
    # then an XOR reduction across the k axis.  Padding columns are
    # zero and mul(c, 0) == 0, so they XOR away silently.
    prod = table[matrix[:, :, None], shards[None, :, :]]
    return jax.lax.reduce(prod, np.uint8(0), jax.lax.bitwise_xor, (1,))


def _pow2_pad(n: int) -> int:
    p = 1024
    while p < n:
        p <<= 1
    return p


def gf_matmul(matrix: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """jax path: pads the shard length to a pow2 bucket (compile-once
    per geometry), stages host bytes through the shared pool, and runs
    the gather/XOR kernel on the default backend."""
    matrix = np.asarray(matrix, dtype=np.uint8)
    shards = np.atleast_2d(np.asarray(shards, dtype=np.uint8))
    rows, k = matrix.shape
    if shards.shape[0] != k:
        raise ValueError(f"matrix k={k} vs shards {shards.shape}")
    length = shards.shape[1]
    if length == 0:
        return np.zeros((rows, 0), dtype=np.uint8)
    padded = _pow2_pad(length)
    stage = staging_buffer(k * padded, slot=4).reshape(k, padded)
    stage[:, :length] = shards
    stage[:, length:] = 0
    dev = jax.device_put(stage)
    out = _gf_matmul_jit(jax.device_put(matrix), dev,
                         jax.device_put(PROD_TABLE), rows, k)
    return np.asarray(out)[:, :length]


# ---------------------------------------------------------------------------
# Stripe-level helpers (shared by tests, the Python client, and goldens)
# ---------------------------------------------------------------------------

def split_stripe(data: bytes, k: int) -> np.ndarray:
    """(k, shard_len) data shards: concatenated payload bytes split into
    k equal shards, the last zero-padded (shard_len = ceil(len/k); the
    on-disk manifest records the true data_len so padding never leaks
    back out).  Empty input yields shard_len 0."""
    if k <= 0:
        raise ValueError(f"bad k={k}")
    shard_len = -(-len(data) // k) if data else 0
    buf = np.zeros(k * shard_len, dtype=np.uint8)
    buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
    return buf.reshape(k, shard_len)


def rs_encode(data_shards: np.ndarray, m: int, path: str = "jax") -> np.ndarray:
    """(m, shard_len) parity shards for (k, shard_len) data shards."""
    data_shards = np.atleast_2d(np.asarray(data_shards, dtype=np.uint8))
    k = data_shards.shape[0]
    pm = parity_matrix(k, m)
    fn = {"ref": gf_matmul_ref, "np": gf_matmul_np, "jax": gf_matmul}[path]
    return fn(pm, data_shards)


def rs_reconstruct(present_shards: np.ndarray, present: "list[int]",
                   k: int, m: int, path: str = "jax") -> np.ndarray:
    """All k data shards from any k surviving shards.

    ``present_shards`` rows correspond 1:1 to the ``present`` indices
    (data rows 0..k-1, parity rows k..k+m-1, any order).
    """
    present_shards = np.atleast_2d(np.asarray(present_shards, dtype=np.uint8))
    dm = decode_matrix(k, m, present)
    fn = {"ref": gf_matmul_ref, "np": gf_matmul_np, "jax": gf_matmul}[path]
    return fn(dm, present_shards)
