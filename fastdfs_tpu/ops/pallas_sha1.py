"""Pallas TPU kernel for batched SHA1.

Why a kernel at all: the pure-XLA formulation in ``ops/sha1.py`` emits
~1000 elementwise HLO ops per 64-byte block whose intermediates spill to
HBM — measured ~8-9 GB/s marginal on a v5e chip.  This kernel keeps the
five state words and the 80-entry message schedule in vector registers,
so steady-state cost collapses to one streamed read of the message plus
the VPU rounds (~115 GB/s for the compress stage alone; end-to-end
throughput is then bounded by the XLA-side padding/layout passes).

Layout: chunks are packed one-per-lane onto (SUB, 128) vreg tiles —
SUB*128 chunks per grid step, so every round instruction advances
SUB*128 chunks at once.  The grid is ``(chunk_tiles, blocks)``; the block
axis iterates sequentially (TPU grid order) over one revisited state
accumulator per tile, so a tile's state never leaves VMEM between its
blocks.  Chunks with fewer blocks than the tile's max are masked per
block, which lets variable-length chunks share one fixed-shape launch.

Bit-exactness vs hashlib and vs the XLA reference is enforced by
tests/test_pallas_kernels.py (interpret mode on CPU; the real kernel
runs in bench.py and on the TPU sidecar via
DedupEngine._fingerprint_batch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_H0 = np.array([0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0],
               dtype=np.uint32)
_K = np.array([0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6], dtype=np.uint32)

LANE = 128
DEFAULT_SUB = 16  # 2048 chunks per tile; wider amortizes instruction issue


def _rotl(x, n):
    return (x << jnp.uint32(n)) | (x >> jnp.uint32(32 - n))


def _sha1_kernel(words_ref, nblocks_ref, state_ref):
    b = pl.program_id(1)

    @pl.when(b == 0)
    def _():
        for i in range(5):
            state_ref[i, 0] = jnp.full(state_ref.shape[2:], _H0[i],
                                       dtype=jnp.uint32)

    # Message schedule: 16 loaded + 64 derived words, all (SUB,128) vregs.
    w = [words_ref[0, 0, t] for t in range(16)]
    for t in range(16, 80):
        w.append(_rotl(w[t - 3] ^ w[t - 8] ^ w[t - 14] ^ w[t - 16], 1))

    a = state_ref[0, 0]
    bb = state_ref[1, 0]
    c = state_ref[2, 0]
    d = state_ref[3, 0]
    e = state_ref[4, 0]
    a0, b0, c0, d0, e0 = a, bb, c, d, e
    for t in range(80):
        if t < 20:
            f = (bb & c) | (~bb & d)
        elif t < 40:
            f = bb ^ c ^ d
        elif t < 60:
            f = (bb & c) | (bb & d) | (c & d)
        else:
            f = bb ^ c ^ d
        tmp = _rotl(a, 5) + f + e + jnp.uint32(_K[t // 20]) + w[t]
        a, bb, c, d, e = tmp, a, _rotl(bb, 30), c, d

    # Blocks past a chunk's own padded length leave its state untouched.
    active = b < nblocks_ref[0]
    upd = [a0 + a, b0 + bb, c0 + c, d0 + d, e0 + e]
    old = [a0, b0, c0, d0, e0]
    for i in range(5):
        state_ref[i, 0] = jnp.where(active, upd[i], old[i])


@functools.partial(jax.jit, static_argnames=("max_blocks", "sub", "interpret"))
def _sha1_pallas(words, nblocks, max_blocks: int, sub: int,
                 interpret: bool = False):
    """words: (T, max_blocks, 16, sub, 128) uint32 — a (tile, block) slice
    is one contiguous read, so the pipeline overlaps a single DMA per
    step; nblocks: (T, sub, 128) int32 → state (5, T, sub, 128) uint32."""
    n_tiles = words.shape[0]
    return pl.pallas_call(
        _sha1_kernel,
        grid=(n_tiles, max_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, 16, sub, LANE),
                         lambda i, b: (i, b, 0, 0, 0)),
            pl.BlockSpec((1, sub, LANE), lambda i, b: (i, 0, 0)),
        ],
        # Revisited across the (sequential) block axis: one tile's state
        # stays resident in VMEM for all of its blocks.
        out_specs=pl.BlockSpec((5, 1, sub, LANE), lambda i, b: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((5, n_tiles, sub, LANE), jnp.uint32),
        interpret=interpret,
    )(words, nblocks)


@functools.partial(jax.jit, static_argnames=("max_len", "sub", "interpret"))
def sha1_batch_pallas(data, lengths, max_len: int, sub: int = DEFAULT_SUB,
                      interpret: bool = False):
    """Pallas-path twin of ops.sha1._sha1_padded: uint8 (N, L) + int32 (N,)
    → uint32 (N, 5) digests.

    CONTRACT (same as sha1_batch): rows must be zero past their length —
    the padding pass relies on it to skip a full-array masking pass.
    """
    n = data.shape[0]
    max_blocks = (max_len + 8) // 64 + 1
    padded_len = max_blocks * 64

    buf = jnp.pad(data, ((0, 0), (0, padded_len - data.shape[1])))
    idx = jnp.arange(padded_len, dtype=jnp.int32)[None, :]
    lens = lengths.astype(jnp.int32)[:, None]
    nblk = (lens + 8) // 64 + 1
    msg_end = nblk * 64
    buf = jnp.where(idx == lens, jnp.uint8(0x80), buf)

    # 64-bit big-endian bit length in the last 8 bytes of the final block.
    bitlen_lo = lens.astype(jnp.uint32) << 3
    bitlen_hi = lens.astype(jnp.uint32) >> 29
    byte_pos = idx - (msg_end - 8)
    in_field = (byte_pos >= 0) & (byte_pos < 8)
    shift = jnp.where(byte_pos < 4, (3 - jnp.clip(byte_pos, 0, 3)) * 8,
                      (7 - jnp.clip(byte_pos, 4, 7)) * 8).astype(jnp.uint32)
    word = jnp.where(byte_pos < 4, bitlen_hi, bitlen_lo)
    len_byte = ((word >> shift) & jnp.uint32(0xFF)).astype(jnp.uint8)
    buf = jnp.where(in_field, len_byte, buf)

    # Bytes → big-endian words via one bitcast + a word-level byteswap
    # (4x fewer elements than shifting four byte planes together).
    le = jax.lax.bitcast_convert_type(
        buf.reshape(n, max_blocks, 16, 4), jnp.uint32)
    words = (((le & jnp.uint32(0xFF)) << 24) |
             ((le & jnp.uint32(0xFF00)) << 8) |
             ((le >> 8) & jnp.uint32(0xFF00)) |
             (le >> 24))  # (N, B, 16)

    # Pad the chunk axis to whole (sub,128) tiles; dummies run 1 block.
    tile = sub * LANE
    n_pad = (-n) % tile
    if n_pad:
        words = jnp.pad(words, ((0, n_pad), (0, 0), (0, 0)))
        nblk_full = jnp.concatenate(
            [nblk[:, 0], jnp.ones((n_pad,), jnp.int32)])
    else:
        nblk_full = nblk[:, 0]
    n_tiles = (n + n_pad) // tile

    # (N, B, 16) -> (T, B, 16, sub, 128): chunk n -> tile n//tile,
    # sublane (n%tile)//128, lane n%128; a (tile, block) slice is
    # contiguous.
    words_t = (words.reshape(n_tiles, sub, LANE, max_blocks, 16)
               .transpose(0, 3, 4, 1, 2))
    nblk_t = nblk_full.reshape(n_tiles, sub, LANE)
    state = _sha1_pallas(words_t, nblk_t, max_blocks, sub, interpret)
    return state.reshape(5, -1).T[:n]  # (N, 5)
