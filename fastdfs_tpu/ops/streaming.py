"""Double-buffered host→device streaming for the fingerprint pipeline.

The ingest path is a host-bandwidth problem as much as a kernel problem
(SURVEY.md §7 "hard parts"): the storage daemon receives bytes on the
host and the fingerprint kernels run on the device, so sustained
throughput requires the host→device transfer of batch ``i+1`` to
overlap the device compute of batch ``i``.  JAX transfers and
dispatches are asynchronous — ``device_put`` and a jitted call both
return futures — so double-buffering is expressed as a bounded
in-flight window: keep up to ``depth`` batches dispatched, fetch the
oldest only when the window is full.  With ``depth >= 2`` the transfer
of the next batch and the compute of the current one are concurrent by
construction; deeper windows additionally amortize per-dispatch
latency (significant on remote backends — see tools/PROFILE_r03.md).

``DedupEngine.fingerprint`` applies the same bounded-window pattern to
its bucket batches (device arrays already resident, so no ``device_put``
step); this helper is the host-sourced variant for paths that stream raw
bytes to the device — the benchmark configs (``bench_configs.py``) drive
it, and ``tests/test_pallas_kernels.py`` pins its ordering semantics.
The reference's synchronous chunked-write loop
(``storage/storage_dio.c:dio_write_file()``) is the analogue being
replaced.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterable, Iterator

import numpy as np


def stream_batches(batches: Iterable[tuple[np.ndarray, np.ndarray]],
                   step_fn: Callable,
                   depth: int = 2) -> Iterator[object]:
    """Run ``step_fn(device_batch, device_lens)`` over a host batch stream
    with up to ``depth`` batches in flight; yields fetched results in
    submission order.

    ``step_fn`` must be a jitted function (or any async-dispatching
    callable); its result pytree is fetched with ``jax.device_get``.
    """
    import jax

    if depth < 1:
        raise ValueError("depth must be >= 1")
    inflight: deque = deque()
    for batch, lens in batches:
        dev_b = jax.device_put(batch)
        dev_l = jax.device_put(lens)
        inflight.append(step_fn(dev_b, dev_l))
        if len(inflight) > depth:
            yield jax.device_get(inflight.popleft())
    while inflight:
        yield jax.device_get(inflight.popleft())
