"""Batched SHA1 on TPU lanes.

SHA1's 80 rounds are strictly sequential *within* a message, so the TPU
formulation parallelizes *across* chunks: every vector lane carries one
chunk's state and all lanes step through the rounds together (SURVEY.md §7
step 6a).  Per-chunk Merkle–Damgård padding (0x80, zeros, 64-bit bit
length) is applied with iota masks so variable-length chunks batch into one
fixed-shape call; blocks past a chunk's padded length leave its state
untouched.

Replaces the reference's per-byte scalar CRC32 loop on the upload path
(``storage/storage_dio.c:dio_write_file()``) as the exact-dedup fingerprint.
Bit-exactness against ``hashlib.sha1`` is enforced in ``tests/test_sha1.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_H0 = np.array([0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0],
               dtype=np.uint32)
_K = np.array([0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6], dtype=np.uint32)


def _rotl(x: jax.Array, n: int) -> jax.Array:
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _compress_block(state: jax.Array, words: jax.Array) -> jax.Array:
    """One SHA1 compression: ``state`` (N,5) uint32, ``words`` (N,16) uint32."""
    w = [words[:, t] for t in range(16)]
    for t in range(16, 80):
        w.append(_rotl(w[t - 3] ^ w[t - 8] ^ w[t - 14] ^ w[t - 16], 1))
    a, b, c, d, e = (state[:, i] for i in range(5))
    for t in range(80):
        if t < 20:
            f = (b & c) | (jnp.bitwise_not(b) & d)
        elif t < 40:
            f = b ^ c ^ d
        elif t < 60:
            f = (b & c) | (b & d) | (c & d)
        else:
            f = b ^ c ^ d
        tmp = _rotl(a, 5) + f + e + jnp.uint32(_K[t // 20]) + w[t]
        a, b, c, d, e = tmp, a, _rotl(b, 30), c, d
    return state + jnp.stack([a, b, c, d, e], axis=1)


@functools.partial(jax.jit, static_argnames=("max_len",))
def _sha1_padded(data: jax.Array, lengths: jax.Array, max_len: int) -> jax.Array:
    n = data.shape[0]
    max_blocks = (max_len + 8) // 64 + 1
    padded_len = max_blocks * 64

    buf = jnp.zeros((n, padded_len), dtype=jnp.uint8)
    buf = buf.at[:, : data.shape[1]].set(data)

    idx = jnp.arange(padded_len, dtype=jnp.int32)[None, :]        # (1,P)
    lens = lengths.astype(jnp.int32)[:, None]                     # (N,1)
    n_blocks = (lens + 8) // 64 + 1                               # (N,1)
    msg_end = n_blocks * 64

    buf = jnp.where(idx < lens, buf, 0)
    buf = jnp.where(idx == lens, jnp.uint8(0x80), buf)

    # 64-bit big-endian bit length in the last 8 bytes of the final block.
    bitlen_lo = (lens.astype(jnp.uint32) << 3)
    bitlen_hi = (lens.astype(jnp.uint32) >> 29)
    byte_pos = idx - (msg_end - 8)                                # 0..7 in field
    in_field = (byte_pos >= 0) & (byte_pos < 8)
    shift = jnp.where(byte_pos < 4, (3 - jnp.clip(byte_pos, 0, 3)) * 8,
                      (7 - jnp.clip(byte_pos, 4, 7)) * 8).astype(jnp.uint32)
    word = jnp.where(byte_pos < 4, bitlen_hi, bitlen_lo)
    len_byte = ((word >> shift) & jnp.uint32(0xFF)).astype(jnp.uint8)
    buf = jnp.where(in_field, len_byte, buf)

    # Pack big-endian 4-byte words: (N, max_blocks, 16).
    quads = buf.reshape(n, max_blocks, 16, 4).astype(jnp.uint32)
    words = ((quads[..., 0] << 24) | (quads[..., 1] << 16)
             | (quads[..., 2] << 8) | quads[..., 3])

    state0 = jnp.broadcast_to(jnp.asarray(_H0), (n, 5)).astype(jnp.uint32)

    def step(state, xs):
        block_idx, block_words = xs
        new_state = _compress_block(state, block_words)
        active = (block_idx < n_blocks[:, 0])[:, None]
        return jnp.where(active, new_state, state), None

    block_ids = jnp.arange(max_blocks, dtype=jnp.int32)
    final, _ = jax.lax.scan(step, state0, (block_ids, words.transpose(1, 0, 2)))
    return final


def sha1_batch(data, lengths=None) -> jax.Array:
    """SHA1 digests for a batch of chunks.

    ``data``: uint8 array ``(N, L)`` (rows zero-padded past each chunk's
    length).  ``lengths``: int array ``(N,)`` of true byte lengths (defaults
    to L for every row).  Returns uint32 ``(N, 5)`` digest words.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    if data.ndim != 2:
        raise ValueError(f"expected (N, L) batch, got shape {data.shape}")
    if lengths is None:
        lengths = jnp.full((data.shape[0],), data.shape[1], dtype=jnp.int32)
    else:
        lengths = jnp.asarray(lengths, dtype=jnp.int32)
    return _sha1_padded(data, lengths, int(data.shape[1]))


def sha1_hex(digest_words) -> str:
    """Render one (5,) uint32 digest row as the canonical 40-char hex."""
    return b"".join(int(w).to_bytes(4, "big") for w in np.asarray(digest_words)).hex()


def digest_bytes(digest_words) -> bytes:
    """(…,5) uint32 digest rows → 20-byte big-endian digests (ndarray of
    object-free bytes for the index layer)."""
    arr = np.asarray(digest_words, dtype=np.uint32)
    return arr.astype(">u4").tobytes()
