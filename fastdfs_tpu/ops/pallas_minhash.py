"""Pallas TPU kernel for MinHash signatures.

The XLA path materializes the (num_perms, L) permuted-hash plane per
chunk, so it is HBM-bound (~4 GB/s marginal on a v5e).  This kernel
streams the shingle-hash sequence once and keeps the running minima of
all permutations in registers, leaving pure VPU work: per position,
``num_perms`` multiply-add-min triples.

Masking trick: instead of a per-position validity select inside the hot
loop, the XLA prep replaces every invalid position's hash with the
chunk's position-0 hash.  MinHash is a set minimum — duplicating an
element that is already in the set changes nothing — so the kernel can
run unmasked and still produce signatures bit-identical to the masked
XLA path (enforced by tests/test_minhash.py).

Layout mirrors pallas_sha1: chunks one-per-lane on (SUB, 128) tiles,
grid ``(chunk_tiles, position_blocks)`` with the signature accumulator
revisited across the sequential position axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from fastdfs_tpu.ops.minhash import (DEFAULT_PERMS, DEFAULT_SHINGLE,
                                     _perm_constants, shingle_hashes)

LANE = 128
DEFAULT_SUB = 16
POS_BLOCK = 64  # positions consumed per grid step


def _make_kernel(num_perms: int):
    a_np, b_np = _perm_constants(num_perms)

    def kernel(h_ref, state_ref):
        pb = pl.program_id(1)

        @pl.when(pb == 0)
        def _():
            for j in range(num_perms):
                state_ref[j, 0] = jnp.full(state_ref.shape[2:], 0xFFFFFFFF,
                                           dtype=jnp.uint32)

        def body(g, sigs):
            h = h_ref[0, 0, g]
            return tuple(
                jnp.minimum(sigs[j],
                            h * jnp.uint32(a_np[j]) + jnp.uint32(b_np[j]))
                for j in range(num_perms))

        sigs = tuple(state_ref[j, 0] for j in range(num_perms))
        sigs = jax.lax.fori_loop(0, h_ref.shape[2], body, sigs)
        for j in range(num_perms):
            state_ref[j, 0] = sigs[j]

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("num_perms", "k", "sub", "interpret"))
def minhash_batch_pallas(data, lengths, num_perms: int = DEFAULT_PERMS,
                         k: int = DEFAULT_SHINGLE, sub: int = DEFAULT_SUB,
                         interpret: bool = False):
    """Pallas-path twin of ops.minhash.minhash_batch: uint8 (N, L) +
    int32 (N,) → uint32 (N, num_perms) signatures (bit-identical)."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    lengths = jnp.asarray(lengths, dtype=jnp.int32)
    n, L = data.shape

    h = jax.vmap(lambda row: shingle_hashes(row, k))(data)  # (N, L) uint32
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    lens = lengths[:, None]
    valid = pos <= (lens - k)
    valid = jnp.where(lens >= k, valid, pos < jnp.maximum(lens, 1))
    # Duplicate-element masking: invalid positions re-contribute the
    # chunk's (always-valid) position-0 hash, which cannot change the min.
    h = jnp.where(valid, h, h[:, :1])

    # Pad chunks to (sub,128) tiles and positions to POS_BLOCK multiples.
    # Padded POSITIONS reuse the same duplicate-element trick (any other
    # fill value would be permuted into arbitrary words that could win a
    # minimum); padded CHUNK rows are sliced off the result, any value.
    tile = sub * LANE
    n_pad = (-n) % tile
    l_pad = (-L) % POS_BLOCK
    if l_pad:
        h = jnp.concatenate(
            [h, jnp.broadcast_to(h[:, :1], (h.shape[0], l_pad))], axis=1)
    if n_pad:
        h = jnp.pad(h, ((0, n_pad), (0, 0)))
    n_tiles = (n + n_pad) // tile
    pb = (L + l_pad) // POS_BLOCK

    h_t = (h.reshape(n_tiles, sub, LANE, pb, POS_BLOCK)
           .transpose(0, 3, 4, 1, 2))  # (T, PB, G, sub, 128)

    out = pl.pallas_call(
        _make_kernel(num_perms),
        grid=(n_tiles, pb),
        in_specs=[pl.BlockSpec((1, 1, POS_BLOCK, sub, LANE),
                               lambda i, p: (i, p, 0, 0, 0))],
        out_specs=pl.BlockSpec((num_perms, 1, sub, LANE),
                               lambda i, p: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_perms, n_tiles, sub, LANE),
                                       jnp.uint32),
        interpret=interpret,
    )(h_t)
    return out.reshape(num_perms, -1).T[:n]  # (N, num_perms)
