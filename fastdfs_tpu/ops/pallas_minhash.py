"""Pallas TPU kernel for the MinHash survivor sketch (spec v2).

Implements stages 1-3 of ``ops/minhash.py``'s sketch — shingle hashing,
value-keyed survivor sampling, segment-min compaction — as ONE fused
kernel that reads each ingested byte exactly once.  The XLA formulation
pays ~20 HBM-bound vector ops per byte just to materialize the shingle
hashes (measured ~15-19 ms per 128 MB on a v5e; tools/PROFILE_r03.md);
this kernel keeps everything in registers and emits only the tiny
``(8, 128)`` survivor plane per chunk.

Layout: one chunk per grid step.  The chunk's bytes are viewed as a
``(R, 128)`` plane of little-endian uint32 words (position-major:
word ``q`` sits at row ``q // 128``, lane ``q % 128``).  Byte windows
are rebuilt from aligned words only — each shingle phase ``r`` (byte
offset mod 4) combines a word with its successor ``W1``, so no
byte-misaligned loads exist anywhere.  ``W1`` itself is two lane/sublane
rotations plus a select.

Unsigned-min legalization: Mosaic has no vector ``arith.minui``, so the
running minima are kept in int32 with the bias trick
(``min_u(x, y) == min_s(x ^ 0x80000000, y ^ 0x80000000) ^ 0x80000000``);
the caller un-biases with one XLA xor.

Stage 4 (the P-way permutation over the ~256 survivors) is shared
verbatim with the XLA reference (``minhash_signature``) — it touches
1/256th of the data, so it is not worth a kernel, and sharing the code
makes bit-exactness of the full pipeline structural rather than
incidental.  Enforced by tests/test_pallas_kernels.py (interpret mode on
CPU, the real kernel on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fastdfs_tpu.ops.minhash import (DEFAULT_PERMS, DEFAULT_SHINGLE, EMPTY,
                                     NUM_SEGMENTS, SAMPLE_MASK, _POLY_B,
                                     minhash_signature)

LANE = 128
_BIAS = np.int32(np.uint32(0x80000000).astype(np.int64) - (1 << 32))  # -2^31


def _survivor_kernel(k: int, R: int):
    """Kernel over one chunk: words (1, R, 128) u32 + len (1, 1) i32 →
    biased survivor plane (1, 8, 128) i32."""
    if k != 5:
        raise NotImplementedError("survivor kernel is specialized to k=5")

    def kernel(lens_ref, w_ref, out_ref):
        W = w_ref[0]                                   # (R, 128) uint32
        ln = lens_ref[pl.program_id(0)]

        # W1[q] = W[q+1] in flattened row-major word order: lane roll -1,
        # with lane 127 taking the next row's lane 0 (row+lane roll).
        r1 = jnp.concatenate([W[:, 1:], W[:, :1]], axis=1)
        rr = jnp.concatenate([W[1:, :], W[:1, :]], axis=0)
        r01 = jnp.concatenate([rr[:, 1:], rr[:, :1]], axis=1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (R, LANE), 1)
        W1 = jnp.where(lane < LANE - 1, r1, r01)
        # Wrapped garbage in the last word's windows only reaches
        # positions p >= 4*NW - 4 > len - k, which the mask excludes.

        row = jax.lax.broadcasted_iota(jnp.int32, (R, LANE), 0)
        q4 = (row * LANE + lane) * 4                   # byte position of r=0
        # Valid positions are p <= bound (scalar select only: Mosaic has no
        # vector-of-bool select): complete shingles, or the degenerate
        # hash-the-padded-window rule for chunks shorter than k.
        bound = jnp.where(ln >= k, ln - k, jnp.maximum(ln, 1) - 1)
        B = _POLY_B
        m = jnp.full((R, LANE), 0x7FFFFFFF, dtype=jnp.int32)
        for r in range(4):
            if r == 0:
                x = W
                b4 = W1 & jnp.uint32(0xFF)
            else:
                x = (W >> jnp.uint32(8 * r)) | (W1 << jnp.uint32(32 - 8 * r))
                b4 = (W1 >> jnp.uint32(8 * r)) & jnp.uint32(0xFF)
            h = x & jnp.uint32(0xFF)
            h = h * B + ((x >> jnp.uint32(8)) & jnp.uint32(0xFF))
            h = h * B + ((x >> jnp.uint32(16)) & jnp.uint32(0xFF))
            h = h * B + (x >> jnp.uint32(24))
            h = h * B + b4
            p = q4 + r
            surv = (p <= bound) & ((h & jnp.uint32(SAMPLE_MASK)) == 0)
            hb = h.astype(jnp.int32) ^ _BIAS           # biased unsigned order
            m = jnp.minimum(m, jnp.where(surv, hb, jnp.int32(0x7FFFFFFF)))

        # segment = word q mod NUM_SEGMENTS = 128 * (row mod 8) + lane.
        out_ref[0] = jnp.min(m.reshape(R // 8, 8, LANE), axis=0)

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def survivor_segmin_pallas(data, lengths, k: int = DEFAULT_SHINGLE,
                           interpret: bool = False):
    """Pallas twin of ops.minhash.survivor_segmin: uint8 (N, L) + int32 (N,)
    → uint32 (N, NUM_SEGMENTS), bit-identical.

    CONTRACT (shared with sha1_batch): rows are zero past their length.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    lengths = jnp.asarray(lengths, dtype=jnp.int32)
    n, L = data.shape
    block = 4 * NUM_SEGMENTS
    pad = (-L) % block
    if pad:
        data = jnp.pad(data, ((0, 0), (0, pad)))
    NW = (L + pad) // 4
    R = NW // LANE                                      # multiple of 8
    words = jax.lax.bitcast_convert_type(
        data.reshape(n, R, LANE, 4), jnp.uint32)        # (N, R, 128)

    out = pl.pallas_call(
        _survivor_kernel(k, R),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n,),
            in_specs=[pl.BlockSpec((1, R, LANE), lambda i, lens_ref: (i, 0, 0))],
            out_specs=pl.BlockSpec((1, 8, LANE), lambda i, lens_ref: (i, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((n, 8, LANE), jnp.int32),
        interpret=interpret,
    )(lengths, words)
    z = jax.lax.bitcast_convert_type(out, jnp.uint32) ^ jnp.uint32(0x80000000)
    return z.reshape(n, NUM_SEGMENTS)


@functools.partial(jax.jit, static_argnames=("num_perms", "k", "interpret"))
def minhash_batch_pallas(data, lengths, num_perms: int = DEFAULT_PERMS,
                         k: int = DEFAULT_SHINGLE, interpret: bool = False):
    """Pallas-path twin of ops.minhash.minhash_batch: uint8 (N, L) +
    int32 (N,) → uint32 (N, num_perms) signatures (bit-identical)."""
    z = survivor_segmin_pallas(data, lengths, k, interpret)
    return jax.vmap(
        lambda zr: minhash_signature(zr, num_perms, zr != EMPTY))(z)
