"""TPU compute kernels for the dedup engine.

These replace the scalar per-byte CRC32 loop on the reference's upload path
(``storage/storage_dio.c:dio_write_file()``) with batched, vectorized
fingerprinting: content-defined chunking (gear rolling hash), SHA1 digests,
and MinHash signatures — jax.numpy first, Pallas for the hot ops.
"""

from fastdfs_tpu.ops.gear_cdc import (  # noqa: F401
    GEAR_TABLE,
    gear_hashes,
    gear_hashes_ref,
    select_cuts,
    chunk_stream,
    chunk_stream_ref,
)
from fastdfs_tpu.ops.sha1 import sha1_batch, sha1_hex  # noqa: F401
from fastdfs_tpu.ops.minhash import (  # noqa: F401
    shingle_hashes,
    survivor_segmin,
    minhash_signature,
    minhash_batch,
    estimate_jaccard,
)
