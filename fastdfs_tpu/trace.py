"""Distributed request tracing: client-side span recording, cluster span
collection, stitching, and timeline rendering.

The pipeline (fastdfs_tpu extension; upstream FastDFS has no tracing):

1. The client starts a trace (``Tracer``) and prefixes each RPC with a
   ``TRACE_CTX`` frame (``common.protocol``: a normal header with
   cmd=TRACE_CTX whose 16-byte body is trace_id + parent span_id +
   flags).  The frame elicits no response; the daemon applies it to the
   next request on the connection.
2. Each daemon records named spans (request root + stage children:
   nio recv, fingerprint, chunk-store write, binlog append; the
   replication sender adds ``sync.ship``; recovery adds
   ``recovery.*``) into a fixed-size ring buffer
   (``native/common/trace.{h,cc}``).
3. ``collect_cluster_spans`` pulls every node's ring via the
   ``TRACE_DUMP`` opcodes, ``stitch`` groups spans by trace_id, and
   ``render_timeline`` draws one request's cross-node timeline.

The dump JSON shape is the cross-language contract (covered by the
``fdfs_codec trace-json`` golden in tests/test_trace.py):

    {"role": "storage"|"tracker", "port": N,
     "spans": [{"trace_id": "16-hex", "span_id": "8-hex",
                "parent_id": "8-hex", "name": str, "start_us": int,
                "dur_us": int, "status": int, "flags": int}]}
"""

from __future__ import annotations

import json
import secrets
import time
from contextlib import contextmanager
from dataclasses import dataclass

from fastdfs_tpu.common.protocol import (
    TRACE_CTX_LEN,
    TRACE_FLAG_SAMPLED,
    TRACE_FLAG_SLOW,
    StorageCmd,
    pack_header,
    pack_trace_ctx,
    unpack_trace_ctx,
)

__all__ = [
    "TraceContext", "Span", "Tracer", "decode_dump", "stitch",
    "render_timeline", "collect_cluster_spans", "traced_upload",
    "TRACE_FLAG_SAMPLED", "TRACE_FLAG_SLOW",
]


# ---------------------------------------------------------------------------
# context + wire frame
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TraceContext:
    """What rides the TRACE_CTX prefix frame: the trace plus the span the
    receiver's work should nest under."""

    trace_id: int
    span_id: int
    flags: int = TRACE_FLAG_SAMPLED

    def frame(self) -> bytes:
        """The full prefix frame: header(cmd=TRACE_CTX, len=16) + body.
        TrackerCmd.TRACE_CTX == StorageCmd.TRACE_CTX, so one frame works
        on either port."""
        return (pack_header(TRACE_CTX_LEN, StorageCmd.TRACE_CTX)
                + pack_trace_ctx(self.trace_id, self.span_id, self.flags))

    @classmethod
    def unpack(cls, body: bytes) -> "TraceContext":
        tid, span, flags = unpack_trace_ctx(body)
        return cls(trace_id=tid, span_id=span, flags=flags)


def _new_trace_id() -> int:
    return secrets.randbits(64) or 1


def _new_span_id() -> int:
    # High bit clear: daemon-allocated span ids set it, so client and
    # daemon ids never collide even without coordination.
    return secrets.randbits(31) or 1


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_id: int
    name: str
    start_us: int
    dur_us: int
    status: int = 0
    flags: int = 0
    node: str = ""       # "role addr" of the daemon (or "client")

    @property
    def end_us(self) -> int:
        return self.start_us + self.dur_us


def decode_dump(obj: dict, node: str = "") -> list[Span]:
    """Validate and decode one daemon's TRACE_DUMP JSON into Spans.

    Raises ValueError on shape violations so a truncated or foreign
    payload fails loudly (same discipline as monitor.decode_registry).
    """
    if not isinstance(obj, dict) or not isinstance(obj.get("spans"), list):
        raise ValueError(f"trace dump must have a spans list: {obj!r}")
    role = obj.get("role", "")
    if node == "":
        node = f"{role}:{obj.get('port', '')}"
    out: list[Span] = []
    for s in obj["spans"]:
        try:
            out.append(Span(
                trace_id=int(s["trace_id"], 16),
                span_id=int(s["span_id"], 16),
                parent_id=int(s["parent_id"], 16),
                name=str(s["name"]),
                start_us=int(s["start_us"]),
                dur_us=int(s["dur_us"]),
                status=int(s.get("status", 0)),
                flags=int(s.get("flags", 0)),
                node=node,
            ))
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed span {s!r}: {e}") from None
    return out


# ---------------------------------------------------------------------------
# client-side tracer
# ---------------------------------------------------------------------------

class Tracer:
    """One trace: client spans recorded locally, wire context derived
    from the innermost open span.  Install on an ``FdfsClient`` (its
    connection plumbing consults ``wire_ctx()``) or use the module-level
    helpers like ``traced_upload``."""

    def __init__(self, flags: int = TRACE_FLAG_SAMPLED):
        self.trace_id = _new_trace_id()
        self.flags = flags
        self.spans: list[Span] = []
        self._stack: list[int] = []

    @contextmanager
    def span(self, name: str):
        """Record a client span; nested spans parent to the enclosing
        one, and RPCs issued inside parent to the innermost span."""
        sid = _new_span_id()
        parent = self._stack[-1] if self._stack else 0
        self._stack.append(sid)
        start = int(time.time() * 1e6)
        try:
            yield TraceContext(self.trace_id, sid, self.flags)
        finally:
            self._stack.pop()
            self.spans.append(Span(
                trace_id=self.trace_id, span_id=sid, parent_id=parent,
                name=name, start_us=start,
                dur_us=int(time.time() * 1e6) - start, node="client"))

    def wire_ctx(self) -> TraceContext | None:
        """Context for the next outgoing RPC (None outside any span)."""
        if not self._stack:
            return None
        return TraceContext(self.trace_id, self._stack[-1], self.flags)


def traced_upload(client, data: bytes, ext: str = "",
                  group: str | None = None) -> tuple[str, Tracer]:
    """Upload ``data`` under a fresh trace; returns (file_id, tracer).
    The tracker query and the storage upload both carry the context, so
    their daemon spans stitch under the client.upload span."""
    tracer = Tracer()
    prev = getattr(client, "tracer", None)
    client.tracer = tracer
    try:
        with tracer.span("client.upload"):
            fid = client.upload_buffer(data, ext=ext, group=group)
    finally:
        client.tracer = prev
    return fid, tracer


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------

def collect_cluster_spans(client) -> tuple[list[Span], dict[str, str]]:
    """Pull every node's span ring through an ``FdfsClient``: each
    configured tracker plus every storage the tracker knows.  Returns
    (spans, errors-by-node); dead nodes land in errors, collection is
    best-effort like monitor.gather."""
    from fastdfs_tpu.client.storage_client import StorageClient
    from fastdfs_tpu.client.tracker_client import TrackerClient

    spans: list[Span] = []
    errors: dict[str, str] = {}
    storages: list[tuple[str, int]] = []
    for host, port in client.trackers:
        addr = f"{host}:{port}"
        try:
            with TrackerClient(host, port, client.timeout) as tc:
                spans.extend(decode_dump(tc.trace_dump(), f"tracker {addr}"))
                for g in tc.cluster_stat().get("groups", []):
                    for s in g.get("storages", []):
                        storages.append((s["ip"], s["port"]))
        except Exception as e:  # noqa: BLE001 — record, keep going
            errors[addr] = f"{type(e).__name__}: {e}"
    for ip, port in sorted(set(storages)):
        addr = f"{ip}:{port}"
        try:
            with StorageClient(ip, port, client.timeout) as sc:
                spans.extend(decode_dump(sc.trace_dump(), f"storage {addr}"))
        except Exception as e:  # noqa: BLE001
            errors[addr] = f"{type(e).__name__}: {e}"
    return spans, errors


# ---------------------------------------------------------------------------
# stitching + rendering
# ---------------------------------------------------------------------------

def _stitch_with_depths(spans: list[Span]) -> dict[int, list[tuple[Span, int]]]:
    """Group spans by trace_id; within a trace, parents sort before
    children (tree order, each paired with its nesting depth), ties
    broken by start time.  Orphans (parent span not collected — e.g.
    overwritten in a ring) sort by start time at top level, so a
    partial trace still renders."""
    by_trace: dict[int, list[Span]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)

    def order(trace: list[Span]) -> list[tuple[Span, int]]:
        ids = {s.span_id for s in trace}
        children: dict[int, list[Span]] = {}
        roots: list[Span] = []
        for s in sorted(trace, key=lambda x: (x.start_us, x.span_id)):
            if s.parent_id and s.parent_id in ids and s.parent_id != s.span_id:
                children.setdefault(s.parent_id, []).append(s)
            else:
                roots.append(s)
        out: list[tuple[Span, int]] = []
        seen: set[int] = set()

        def walk(s: Span, depth: int):
            # Cycle/defense guard: colliding span ids (e.g. two daemons'
            # rings allocating the same id) must degrade the rendering,
            # never hang it.
            if id(s) in seen or depth > 64:
                return
            seen.add(id(s))
            out.append((s, depth))
            for c in children.get(s.span_id, []):
                walk(c, depth + 1)

        for r in roots:
            walk(r, 0)
        # Anything unreachable through the tree (cycle members) still
        # shows up, flat, at the end.
        for s in trace:
            if id(s) not in seen:
                seen.add(id(s))
                out.append((s, 0))
        return out

    return {tid: order(tr) for tid, tr in by_trace.items()}


def stitch(spans: list[Span]) -> dict[int, list[Span]]:
    """Tree-ordered spans per trace_id (see _stitch_with_depths, which
    the renderer uses to also get nesting depths)."""
    return {tid: [s for s, _ in pairs]
            for tid, pairs in _stitch_with_depths(spans).items()}


def render_timeline(spans: list[Span], trace_id: int | None = None) -> str:
    """Human timeline: one trace per block, one line per span with its
    node, name, offset from trace start, duration, and a scaled bar."""
    stitched = _stitch_with_depths(spans)
    if trace_id is not None:
        stitched = {trace_id: stitched.get(trace_id, [])}
    lines: list[str] = []
    for tid, trace in sorted(stitched.items()):
        if not trace:
            lines.append(f"trace {tid:016x}: no spans collected")
            continue
        t0 = min(s.start_us for s, _ in trace)
        t1 = max(s.end_us for s, _ in trace)
        total = max(t1 - t0, 1)
        nodes = sorted({s.node for s, _ in trace})
        lines.append(f"trace {tid:016x}  spans={len(trace)} "
                     f"nodes={len(nodes)} total={total / 1000:.2f}ms")
        width = 24
        for s, depth in trace:
            off = s.start_us - t0
            lo = min(int(off * width / total), width - 1)
            hi = min(max(int((off + s.dur_us) * width / total), lo + 1), width)
            bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
            flagtxt = " SLOW" if s.flags & TRACE_FLAG_SLOW else ""
            err = f" status={s.status}" if s.status else ""
            lines.append(
                f"  [{s.node:<22}] {'  ' * depth}{s.name:<28} "
                f"|{bar}| +{off / 1000:.2f}ms {s.dur_us / 1000:.2f}ms"
                f"{err}{flagtxt}")
    return "\n".join(lines)


def spans_to_json(spans: list[Span]) -> str:
    """Machine form of a collected span set (``cli.py trace --json``)."""
    return json.dumps([{
        "trace_id": f"{s.trace_id:016x}",
        "span_id": f"{s.span_id:08x}",
        "parent_id": f"{s.parent_id:08x}",
        "name": s.name,
        "start_us": s.start_us,
        "dur_us": s.dur_us,
        "status": s.status,
        "flags": s.flags,
        "node": s.node,
    } for s in spans], indent=2)
