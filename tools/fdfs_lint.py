#!/usr/bin/env python
"""fdfs_lint — static contract and lock-discipline linter for the tree.

Five PRs of growth built correctness-critical structure that nothing
machine-checked: four cross-language contracts (opcodes, the append-only
stat blobs, conf keys, codec goldens) and a lock protocol (16-way
digest-striped chunk store with an ascending multi-stripe rule, per-slot
spin rings).  This linter makes each of them a named, fixture-tested
check instead of reviewer memory.  The runtime half of the discipline is
native/common/lockrank.h (the FDFS_LOCKRANK build); this file is the
static half.

Check classes (each provable-failable by tests/test_lint.py fixtures):

  opcode-parity      protocol.py enums == protocol_manifest.json
  header-parity      protocol_manifest.json == protocol_gen.h (enums and
                     the generated kBeatStatNames/kScrubStatNames arrays)
  stat-fields        BEAT/SCRUB stat blobs are append-only: the frozen
                     prefix pinned below may never shrink, reorder, or
                     rename
  conf-parity        every key parsed by the daemons/client appears in
                     the matching conf/*.conf sample (and the daemon keys
                     in OPERATIONS.md), and every real `key = value` line
                     in a sample is actually parsed by the code
  golden-coverage    every opcode with a wire body carries an fdfs_codec
                     golden (which must exist in codec_cli.cc and be
                     referenced by a test) or an explicit allowlist entry
  lock-raw-mutex     no raw std::mutex / pthread_mutex_t /
                     std::condition_variable in native/ outside
                     common/lockrank.h — every lock is a RankedMutex (or
                     RankedSpinLock) with a documented rank
  lock-guard-discipline  no bare .lock()/.unlock() calls on mutexes:
                     locks are taken through std::lock_guard /
                     std::unique_lock / SpinGuard only (guard variables
                     named `lk`/`ulk` may re-lock — that is still
                     guard-mediated)
  spin-region-blocking   no blocking syscalls inside a SpinGuard-held
                     region (per-slot ring spinlocks must stay
                     bounded-copy critical sections)

Usage:
  python tools/fdfs_lint.py              # lint the repo, exit 1 on findings
  python tools/fdfs_lint.py --list       # list check classes
  python tools/fdfs_lint.py --only conf-parity [--only ...]
  python tools/fdfs_lint.py --root DIR   # lint another tree (fixtures)
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass
class Finding:
    check: str
    path: str       # repo-relative
    line: int       # 1-based; 0 = whole file
    message: str

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.check}] {self.message}"


# ---------------------------------------------------------------------------
# Frozen stat-field prefixes: the blobs are APPEND-ONLY wire contracts
# (old decoders read missing tail slots as 0).  These are the fields
# shipped as of this linter's introduction; grow them only by appending
# to protocol.py AND appending the same name here.  Any rename, reorder,
# or removal of a frozen slot breaks deployed decoders and fails here.
# ---------------------------------------------------------------------------

FROZEN_BEAT_PREFIX = (
    "total_upload", "success_upload",
    "total_download", "success_download",
    "total_delete", "success_delete",
    "total_append", "success_append",
    "total_set_meta", "success_set_meta",
    "total_get_meta", "success_get_meta",
    "total_query", "success_query",
    "bytes_uploaded", "bytes_downloaded",
    "dedup_hits", "dedup_bytes_saved",
    "last_source_update",
    "connections",
    "refused_connections",
    "sync_lag_s",
    "sync_bytes_saved_wire",
    "recovery_chunks_fetched",
    "recovery_chunks_local",
    "recovery_files",
    "fetch_chunk_batches",
    "dedup_chunk_misses",
)

FROZEN_SCRUB_PREFIX = (
    "running", "passes", "pass_chunks_done", "pass_chunks_total",
    "chunks_verified", "bytes_verified", "chunks_corrupt",
    "chunks_repaired", "corrupt_unrepairable", "quarantined",
    "skipped_pinned", "gc_pending_chunks", "gc_pending_bytes",
    "chunks_reclaimed", "bytes_reclaimed", "recipes_reclaimed",
    "last_pass_unix", "last_pass_duration_us",
)

# ---------------------------------------------------------------------------
# Opcodes with a wire body but no fdfs_codec golden.  Every entry is a
# DECISION with a reason — adding an opcode without either a golden or a
# row here fails golden-coverage, which is the point: new wire surface
# must pick its pinning story in the same PR.
# ---------------------------------------------------------------------------

_FIXED_FIELDS = ("fixed header-framed fields (group/ip/int64 slots); "
                 "exercised end-to-end by the live daemon suite")
_JSON_LISTING = ("ops listing JSON consumed only by fastdfs_tpu.monitor; "
                 "shape asserted by test_monitor.py against live daemons")
_BEAT_CONTRACT = ("stat blob named by the GENERATED kBeatStatNames contract "
                  "(protocol_gen.h == BEAT_STAT_FIELDS by construction)")
_SIDE_CAR = ("sidecar-local RPC (unix socket, same-host); layout asserted "
             "by the dedup engine suite")
_REPLICATION = ("replication/recovery wire asserted byte-level by "
                "test_replication.py / test_disk_recovery.py fixtures")

# ---------------------------------------------------------------------------
# Node-local LAYOUT goldens: fdfs_codec subcommands that pin on-disk
# formats (not wire opcodes, so the manifest never names them).  Each
# must exist as a codec subcommand AND be referenced by a test, exactly
# like the wire goldens — a layout that boot rescans from raw headers is
# a cross-version contract even though it never crosses the network.
# ---------------------------------------------------------------------------

EXTRA_GOLDENS = (
    "slab-layout",  # slab slot-header + index-record encoding (ISSUE 9)
    # Thread-ledger gauge naming (thread.<name>.cpu_pct/...): not a wire
    # opcode, but monitor.thread_ledger and fdfs_top's THREADS pane
    # parse these names back apart, so the scheme is a cross-language
    # contract (ISSUE 15).
    "thread-ledger",
    # GF(2^8) arithmetic-table contract (poly 0x11D, generator 2):
    # native/common/gf256.h and fastdfs_tpu/ops/gf256.py are generated
    # from the same tool, and every RS shard on disk assumes this exact
    # field — the golden pins table CRCs + sample products (ISSUE 16).
    "gf-tables",
)

# Checked-in fixture goldens: JSON files under tests/ pinning kernel
# behavior byte-for-byte (vs the live-computed cross-language goldens
# above).  Each must exist, parse, carry the listed top-level keys, and
# be referenced by at least one test — silent drift in what they pin
# (e.g. CDC cut offsets, which are content addresses) must fail CI
# loudly (ISSUE 13).
FIXTURE_GOLDENS = {
    "tests/goldens/cdc_cuts.json": ("cdc_spec", "cases"),
}

GOLDEN_ALLOWLIST = {
    # tracker: cluster management
    "TrackerCmd.STORAGE_JOIN": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_BEAT": _BEAT_CONTRACT,
    "TrackerCmd.STORAGE_REPORT_DISK_USAGE": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_REPLICA_CHG": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_SYNC_SRC_REQ": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_SYNC_DEST_REQ": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_SYNC_NOTIFY": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_SYNC_REPORT": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_SYNC_DEST_QUERY": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_REPORT_IP_CHANGED": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_CHANGELOG_REQ": _FIXED_FIELDS,
    "TrackerCmd.STORAGE_PARAMETER_REQ": "key=value text; parsed by both "
                                        "daemons, covered by trunk tests",
    "TrackerCmd.SERVER_LIST_ONE_GROUP": _JSON_LISTING,
    "TrackerCmd.SERVER_LIST_ALL_GROUPS": _JSON_LISTING,
    "TrackerCmd.SERVER_LIST_STORAGE": _JSON_LISTING,
    "TrackerCmd.SERVER_DELETE_STORAGE": _FIXED_FIELDS,
    "TrackerCmd.SERVER_SET_TRUNK_SERVER": _FIXED_FIELDS,
    "TrackerCmd.SERVICE_QUERY_STORE_WITHOUT_GROUP_ONE": _FIXED_FIELDS,
    "TrackerCmd.SERVICE_QUERY_FETCH_ONE": _FIXED_FIELDS,
    "TrackerCmd.SERVICE_QUERY_UPDATE": _FIXED_FIELDS,
    "TrackerCmd.SERVICE_QUERY_STORE_WITH_GROUP_ONE": _FIXED_FIELDS,
    "TrackerCmd.SERVICE_QUERY_FETCH_ALL": _FIXED_FIELDS,
    "TrackerCmd.SERVICE_QUERY_STORE_WITHOUT_GROUP_ALL": _FIXED_FIELDS,
    "TrackerCmd.SERVICE_QUERY_STORE_WITH_GROUP_ALL": _FIXED_FIELDS,
    "TrackerCmd.TRACKER_GET_STATUS": _FIXED_FIELDS,
    "TrackerCmd.TRACKER_GET_SYS_FILES_START": _FIXED_FIELDS,
    "TrackerCmd.TRACKER_GET_SYS_FILES_END": _FIXED_FIELDS,
    "TrackerCmd.TRACKER_GET_ONE_SYS_FILE": _FIXED_FIELDS,
    "TrackerCmd.TRACKER_PING_LEADER": _FIXED_FIELDS,
    "TrackerCmd.TRACKER_NOTIFY_NEXT_LEADER": _FIXED_FIELDS,
    "TrackerCmd.TRACKER_COMMIT_NEXT_LEADER": _FIXED_FIELDS,
    "TrackerCmd.TRACKER_GET_TRUNK_SERVER": _FIXED_FIELDS,
    # storage: file service (upstream-shaped fixed fields)
    "StorageCmd.UPLOAD_FILE": _FIXED_FIELDS,
    "StorageCmd.DELETE_FILE": _FIXED_FIELDS,
    "StorageCmd.SET_METADATA": _FIXED_FIELDS,
    "StorageCmd.DOWNLOAD_FILE": _FIXED_FIELDS,
    "StorageCmd.GET_METADATA": _FIXED_FIELDS,
    "StorageCmd.SYNC_CREATE_FILE": _REPLICATION,
    "StorageCmd.SYNC_DELETE_FILE": _REPLICATION,
    "StorageCmd.SYNC_UPDATE_FILE": _REPLICATION,
    "StorageCmd.SYNC_CREATE_LINK": _REPLICATION,
    "StorageCmd.CREATE_LINK": _FIXED_FIELDS,
    "StorageCmd.UPLOAD_SLAVE_FILE": _FIXED_FIELDS,
    "StorageCmd.QUERY_FILE_INFO": _FIXED_FIELDS,
    "StorageCmd.UPLOAD_APPENDER_FILE": _FIXED_FIELDS,
    "StorageCmd.APPEND_FILE": _FIXED_FIELDS,
    "StorageCmd.SYNC_APPEND_FILE": _REPLICATION,
    "StorageCmd.FETCH_ONE_PATH_BINLOG": _FIXED_FIELDS,
    "StorageCmd.TRUNK_ALLOC_SPACE": "epoch-fenced trunk RPC; slot layout "
                                    "asserted by test_trunk.py",
    "StorageCmd.TRUNK_ALLOC_CONFIRM": "see TRUNK_ALLOC_SPACE",
    "StorageCmd.TRUNK_FREE_SPACE": "see TRUNK_ALLOC_SPACE",
    "StorageCmd.MODIFY_FILE": _FIXED_FIELDS,
    "StorageCmd.SYNC_MODIFY_FILE": _REPLICATION,
    "StorageCmd.TRUNCATE_FILE": _FIXED_FIELDS,
    "StorageCmd.SYNC_TRUNCATE_FILE": _REPLICATION,
    "StorageCmd.DEDUP_FINGERPRINT": _SIDE_CAR,
    "StorageCmd.DEDUP_QUERY": _SIDE_CAR,
    "StorageCmd.DEDUP_COMMIT": _SIDE_CAR,
    "StorageCmd.DEDUP_NEARDUPS": _SIDE_CAR,
    "StorageCmd.DEDUP_FINGERPRINT_CUTS": _SIDE_CAR,
    "StorageCmd.DEDUP_VERIFY": _SIDE_CAR,
    "StorageCmd.SYNC_QUERY_CHUNKS": _REPLICATION,
    "StorageCmd.SYNC_CREATE_RECIPE": _REPLICATION,
    "StorageCmd.FETCH_RECIPE": _REPLICATION,
    "StorageCmd.FETCH_CHUNK": _REPLICATION,
    "StorageCmd.SCRUB_KICK": "empty body, status-only response; asserted "
                             "by test_scrub.py",
    "StorageCmd.NEAR_DUPS": "text lines '<file_id> <score>'; asserted by "
                            "test_near_dups.py",
}

# conf keys whose parse site builds the key dynamically; map the literal
# the extractor sees to the sample key that documents the family.
_DYNAMIC_CONF_KEYS = {"store_path": "store_path0"}


# ---------------------------------------------------------------------------
# Small parsing helpers
# ---------------------------------------------------------------------------

def _read(root: str, rel: str) -> str | None:
    try:
        with open(os.path.join(root, rel), encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return None


def _need(root: str, rel: str, check: str,
          out: list[Finding]) -> str | None:
    text = _read(root, rel)
    if text is None:
        out.append(Finding(check, rel, 0, "file missing or unreadable"))
    return text


def _parse_py_enums(text: str) -> dict[str, dict[str, int]]:
    """{'TrackerCmd': {'STORAGE_JOIN': 81, ...}, ...} via AST — the
    linter never imports the tree it lints (fixture roots are plain
    text, and a broken protocol.py must fail parse, not crash us)."""
    tree = ast.parse(text)
    out: dict[str, dict[str, int]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        members: dict[str, int] = {}
        for stmt in node.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, int)):
                members[stmt.targets[0].id] = stmt.value.value
        if members:
            out[node.name] = members
    return out


def _parse_py_str_tuple(text: str, name: str) -> list[str] | None:
    tree = ast.parse(text)
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Tuple)):
            vals = []
            for elt in node.value.elts:
                if not (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)):
                    return None
                vals.append(elt.value)
            return vals
    return None


def _parse_header_enums(text: str) -> dict[str, dict[str, int]]:
    """{'TrackerCmd': {'kStorageJoin': 81, ...}} from protocol_gen.h."""
    out: dict[str, dict[str, int]] = {}
    for m in re.finditer(
            r"enum class (\w+)\s*:\s*\w+\s*\{([^}]*)\}", text):
        members = {}
        for em in re.finditer(r"(k\w+)\s*=\s*(\d+)\s*,", m.group(2)):
            members[em.group(1)] = int(em.group(2))
        out[m.group(1)] = members
    return out


def _parse_header_name_array(text: str, array: str) -> list[str] | None:
    m = re.search(re.escape(array) + r"\[[^\]]*\]\s*=\s*\{([^}]*)\}", text)
    if m is None:
        return None
    return re.findall(r'"([^"]+)"', m.group(1))


def _strip_cc_comments(text: str) -> str:
    """Drop // and /* */ comments, preserving line structure so finding
    line numbers stay meaningful."""
    text = re.sub(r"/\*.*?\*/",
                  lambda m: re.sub(r"[^\n]", " ", m.group(0)),
                  text, flags=re.S)
    return re.sub(r"//[^\n]*", "", text)


def _native_sources(root: str) -> list[str]:
    out = []
    for base, dirs, files in os.walk(os.path.join(root, "native")):
        dirs[:] = [d for d in dirs if not d.startswith("build")]
        for f in files:
            if f.endswith((".h", ".cc")):
                out.append(os.path.relpath(os.path.join(base, f), root))
    return sorted(out)


# ---------------------------------------------------------------------------
# Check classes
# ---------------------------------------------------------------------------

def check_opcode_parity(root: str) -> list[Finding]:
    """protocol.py enum members == protocol_manifest.json entries."""
    out: list[Finding] = []
    proto = _need(root, "fastdfs_tpu/common/protocol.py", "opcode-parity", out)
    mani = _need(root, "native/protocol_manifest.json", "opcode-parity", out)
    if proto is None or mani is None:
        return out
    try:
        manifest = json.loads(mani)
    except ValueError as e:
        out.append(Finding("opcode-parity", "native/protocol_manifest.json",
                           0, f"unparseable JSON: {e}"))
        return out
    py_enums = _parse_py_enums(proto)
    for enum_name in ("TrackerCmd", "StorageCmd", "StorageStatus"):
        py = py_enums.get(enum_name)
        entries = manifest.get("enums", {}).get(enum_name)
        if py is None:
            out.append(Finding("opcode-parity",
                               "fastdfs_tpu/common/protocol.py", 0,
                               f"enum {enum_name} not found"))
            continue
        if entries is None:
            out.append(Finding("opcode-parity",
                               "native/protocol_manifest.json", 0,
                               f"enum {enum_name} missing from manifest"))
            continue
        mani_vals = {e["name"]: e["value"] for e in entries}
        for name, value in py.items():
            if name not in mani_vals:
                out.append(Finding(
                    "opcode-parity", "native/protocol_manifest.json", 0,
                    f"{enum_name}.{name} in protocol.py but not in the "
                    f"manifest — run native/gen_protocol.py"))
            elif mani_vals[name] != value:
                out.append(Finding(
                    "opcode-parity", "native/protocol_manifest.json", 0,
                    f"{enum_name}.{name} = {value} in protocol.py but "
                    f"{mani_vals[name]} in the manifest"))
        for name in mani_vals:
            if name not in py:
                out.append(Finding(
                    "opcode-parity", "native/protocol_manifest.json", 0,
                    f"{enum_name}.{name} in the manifest but not in "
                    f"protocol.py"))
    return out


def check_header_parity(root: str) -> list[Finding]:
    """protocol_manifest.json == protocol_gen.h (enums + stat-name
    arrays).  Textual, so it works on fixture trees with no compiler."""
    out: list[Finding] = []
    mani = _need(root, "native/protocol_manifest.json", "header-parity", out)
    header = _need(root, "native/common/protocol_gen.h", "header-parity", out)
    if mani is None or header is None:
        return out
    try:
        manifest = json.loads(mani)
    except ValueError:
        return out  # opcode-parity reports the parse failure
    hdr_enums = _parse_header_enums(header)
    for enum_name, entries in manifest.get("enums", {}).items():
        hdr = hdr_enums.get(enum_name)
        if hdr is None:
            out.append(Finding("header-parity",
                               "native/common/protocol_gen.h", 0,
                               f"enum {enum_name} missing from header"))
            continue
        want = {e["cpp"]: e["value"] for e in entries}
        for cpp, value in want.items():
            if cpp not in hdr:
                out.append(Finding(
                    "header-parity", "native/common/protocol_gen.h", 0,
                    f"{enum_name}::{cpp} in the manifest but not the "
                    f"header — run native/gen_protocol.py"))
            elif hdr[cpp] != value:
                out.append(Finding(
                    "header-parity", "native/common/protocol_gen.h", 0,
                    f"{enum_name}::{cpp} = {hdr[cpp]} in the header but "
                    f"{value} in the manifest"))
        for cpp in hdr:
            if cpp not in want:
                out.append(Finding(
                    "header-parity", "native/common/protocol_gen.h", 0,
                    f"{enum_name}::{cpp} in the header but not the "
                    f"manifest"))
    for array, field in (("kBeatStatNames", "beat_stat_fields"),
                         ("kScrubStatNames", "scrub_stat_fields")):
        names = _parse_header_name_array(header, array)
        want = manifest.get(field, [])
        if names is None:
            out.append(Finding("header-parity",
                               "native/common/protocol_gen.h", 0,
                               f"{array} array not found"))
        elif names != want:
            out.append(Finding(
                "header-parity", "native/common/protocol_gen.h", 0,
                f"{array} != manifest {field}: {names} vs {want}"))
    return out


def check_stat_fields(root: str) -> list[Finding]:
    """The stat blobs are append-only: the frozen prefix pinned in this
    linter may never shrink, reorder, or rename (protocol.py is checked
    directly; opcode/header parity transfer the result to the other
    artifacts)."""
    out: list[Finding] = []
    proto = _need(root, "fastdfs_tpu/common/protocol.py", "stat-fields", out)
    if proto is None:
        return out
    for var, frozen in (("BEAT_STAT_FIELDS", FROZEN_BEAT_PREFIX),
                        ("SCRUB_STAT_FIELDS", FROZEN_SCRUB_PREFIX)):
        fields = _parse_py_str_tuple(proto, var)
        if fields is None:
            out.append(Finding("stat-fields",
                               "fastdfs_tpu/common/protocol.py", 0,
                               f"{var} tuple of string literals not found"))
            continue
        if tuple(fields[:len(frozen)]) != frozen:
            for i, want in enumerate(frozen):
                got = fields[i] if i < len(fields) else "<missing>"
                if got != want:
                    out.append(Finding(
                        "stat-fields", "fastdfs_tpu/common/protocol.py", 0,
                        f"{var}[{i}] is {got!r}, but the wire contract "
                        f"froze it as {want!r} — the blob is append-only "
                        f"(old decoders index by slot); append new fields "
                        f"at the end instead"))
                    break
    return out


_CONF_GET_RE = re.compile(
    r'\b(?:ini|cfg)\s*\.\s*[Gg]et(?:Str|Int|Bool|Seconds|Bytes|All|'
    r'_str|_int|_bool|_seconds|_bytes|_all)?\s*\(\s*"([a-z][a-z0-9_.]*)"')
_CONF_KEY_RE = re.compile(r"^([a-z][a-z0-9_.]*)\s*=", re.M)
_CONF_EXAMPLE_RE = re.compile(r"^# ([a-z][a-z0-9_.]*) = ", re.M)


def _parsed_conf_keys(text: str) -> set[str]:
    keys = set()
    for m in _CONF_GET_RE.finditer(text):
        keys.add(_DYNAMIC_CONF_KEYS.get(m.group(1), m.group(1)))
    return keys


def check_conf_parity(root: str) -> list[Finding]:
    """Daemon/client conf keys <-> conf/*.conf samples <-> OPERATIONS.md.

    Three rules per (parser sources, sample) pair:
      1. every parsed key appears in the sample (a live `key = value`
         line or a `# key = value` example — word match anywhere counts
         as documented);
      2. every live or example key line in the sample is actually parsed
         by the code (no dead knobs);
      3. daemon keys additionally appear in OPERATIONS.md.
    """
    out: list[Finding] = []
    ops = _need(root, "OPERATIONS.md", "conf-parity", out)
    targets = [
        ("conf/storage.conf",
         ["native/storage/config.cc"], True),
        ("conf/tracker.conf",
         ["native/tracker/main.cc"], True),
        ("conf/client.conf",
         ["fastdfs_tpu/client/client.py"], False),
    ]
    for sample_rel, src_rels, in_ops in targets:
        sample = _need(root, sample_rel, "conf-parity", out)
        if sample is None:
            continue
        parsed: set[str] = set()
        for src_rel in src_rels:
            src = _need(root, src_rel, "conf-parity", out)
            if src is not None:
                parsed |= _parsed_conf_keys(_strip_cc_comments(src)
                                            if src_rel.endswith(".cc")
                                            else src)
        if not parsed:
            continue
        sample_keys = set(_CONF_KEY_RE.findall(sample)) | set(
            _CONF_EXAMPLE_RE.findall(sample))
        for key in sorted(parsed):
            if not re.search(rf"\b{re.escape(key)}\b", sample):
                out.append(Finding(
                    "conf-parity", sample_rel, 0,
                    f"key '{key}' is parsed by {'/'.join(src_rels)} but "
                    f"never mentioned in the sample — document it (a "
                    f"commented '# {key} = ...' example counts)"))
            if in_ops and ops is not None and not re.search(
                    rf"\b{re.escape(key)}\b", ops):
                out.append(Finding(
                    "conf-parity", "OPERATIONS.md", 0,
                    f"daemon conf key '{key}' ({sample_rel}) is not "
                    f"documented in OPERATIONS.md"))
        for key in sorted(sample_keys - parsed):
            line = next((i + 1 for i, ln in
                         enumerate(sample.splitlines())
                         if re.match(rf"#? ?{re.escape(key)}\s*=", ln)), 0)
            out.append(Finding(
                "conf-parity", sample_rel, line,
                f"sample key '{key}' is parsed by none of "
                f"{'/'.join(src_rels)} — a dead knob misleads operators; "
                f"wire it up or delete the line"))
    return out


def check_golden_coverage(root: str) -> list[Finding]:
    """Every opcode with a wire body has a cross-language golden or an
    explicit allowlist entry; named goldens must exist as fdfs_codec
    subcommands and be referenced by at least one test."""
    out: list[Finding] = []
    mani = _need(root, "native/protocol_manifest.json",
                 "golden-coverage", out)
    codec = _need(root, "native/tools/codec_cli.cc", "golden-coverage", out)
    if mani is None:
        return out
    try:
        manifest = json.loads(mani)
    except ValueError:
        return out
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    if os.path.isdir(tests_dir):
        for f in sorted(os.listdir(tests_dir)):
            if f.endswith(".py"):
                tests_text += _read(root, f"tests/{f}") or ""
    checked_goldens: set[str] = set()
    for enum_name in ("TrackerCmd", "StorageCmd"):
        for e in manifest.get("enums", {}).get(enum_name, []):
            qual = f"{enum_name}.{e['name']}"
            if not e.get("wire_body"):
                continue
            golden = e.get("golden")
            if golden is None:
                if qual not in GOLDEN_ALLOWLIST:
                    out.append(Finding(
                        "golden-coverage", "native/protocol_manifest.json",
                        0,
                        f"{qual} has a wire body but neither an "
                        f"fdfs_codec golden (protocol.WIRE_GOLDENS) nor a "
                        f"GOLDEN_ALLOWLIST entry in tools/fdfs_lint.py — "
                        f"decide its pinning story"))
                continue
            if golden in checked_goldens:
                continue
            checked_goldens.add(golden)
            if codec is not None and f'"{golden}"' not in codec:
                out.append(Finding(
                    "golden-coverage", "native/tools/codec_cli.cc", 0,
                    f"golden '{golden}' ({qual}) is not an fdfs_codec "
                    f"subcommand"))
            if tests_text and golden not in tests_text:
                out.append(Finding(
                    "golden-coverage", "tests", 0,
                    f"golden '{golden}' ({qual}) is referenced by no test "
                    f"under tests/ — an unexercised golden pins nothing"))
    # Node-local layout goldens (EXTRA_GOLDENS) carry the same
    # subcommand + test-reference obligations as wire goldens.
    for golden in EXTRA_GOLDENS:
        if codec is not None and f'"{golden}"' not in codec:
            out.append(Finding(
                "golden-coverage", "native/tools/codec_cli.cc", 0,
                f"layout golden '{golden}' (EXTRA_GOLDENS) is not an "
                f"fdfs_codec subcommand"))
        if tests_text and golden not in tests_text:
            out.append(Finding(
                "golden-coverage", "tests", 0,
                f"layout golden '{golden}' (EXTRA_GOLDENS) is referenced "
                f"by no test under tests/ — an unexercised golden pins "
                f"nothing"))
    # Checked-in fixture goldens (FIXTURE_GOLDENS): must exist, parse,
    # carry their contract keys, and be exercised by a test.
    for rel, keys in FIXTURE_GOLDENS.items():
        text = _read(root, rel)
        if text is None:
            out.append(Finding(
                "golden-coverage", rel, 0,
                f"fixture golden missing (FIXTURE_GOLDENS in "
                f"tools/fdfs_lint.py expects it)"))
            continue
        try:
            blob = json.loads(text)
        except ValueError:
            out.append(Finding("golden-coverage", rel, 0,
                               "fixture golden is not valid JSON"))
            continue
        missing = [k for k in keys if k not in blob]
        if missing:
            out.append(Finding(
                "golden-coverage", rel, 0,
                f"fixture golden lacks contract keys {missing}"))
        base = os.path.basename(rel)
        if tests_text and base not in tests_text:
            out.append(Finding(
                "golden-coverage", "tests", 0,
                f"fixture golden '{base}' is referenced by no test under "
                f"tests/ — an unexercised golden pins nothing"))
    return out


_RAW_MUTEX_RE = re.compile(
    r"\b(std::(?:recursive_|shared_|timed_)?mutex\b"
    r"|pthread_mutex_t\b|pthread_spinlock_t\b"
    r"|std::condition_variable\b(?!_any))")


def check_lock_raw_mutex(root: str) -> list[Finding]:
    """Every lock in native/ is a RankedMutex/RankedSpinLock from
    common/lockrank.h — a raw mutex has no rank and silently escapes the
    FDFS_LOCKRANK checker.  (std::condition_variable is included: it
    only pairs with a raw std::mutex; use std::condition_variable_any
    over a RankedMutex.)"""
    out: list[Finding] = []
    for rel in _native_sources(root):
        if rel.endswith(os.path.join("common", "lockrank.h")):
            continue
        text = _read(root, rel)
        if text is None:
            continue
        raw_lines = text.splitlines()
        for i, line in enumerate(_strip_cc_comments(text).splitlines(), 1):
            m = _RAW_MUTEX_RE.search(line)
            if m and not _nolint(raw_lines[i - 1], "lock-raw-mutex"):
                out.append(Finding(
                    "lock-raw-mutex", rel, i,
                    f"raw {m.group(1)} — declare a RankedMutex with a "
                    f"documented rank from common/lockrank.h instead"))
    return out


_BARE_LOCK_RE = re.compile(r"\b([A-Za-z_]\w*)\s*\.\s*(lock|unlock)\s*\(\s*\)")
_PTHREAD_LOCK_RE = re.compile(r"\bpthread_(?:mutex|spin)_(?:lock|unlock|trylock)\s*\(")
# Guard objects that may legitimately re-lock/unlock mid-scope
# (std::unique_lock variables by repo convention: lk, lk2, ulk...).
_GUARD_NAME_RE = re.compile(r"^(?:lk|ulk|ul)\w*$")


def _nolint(raw_line: str, check: str) -> bool:
    """clang-tidy-style suppression: `// NOLINT(<check>)` on the line.
    Deliberate violations (the lock-rank death tests) stay visible and
    greppable instead of being silently special-cased."""
    return f"NOLINT({check})" in raw_line


def check_lock_guard_discipline(root: str) -> list[Finding]:
    """Locks are taken through scoped guards only.  A bare mu.lock()
    orphans the lock on any early return/exception, and under
    FDFS_LOCKRANK an unbalanced stack turns every later check into
    noise.  unique_lock guard variables (named lk/ulk by convention) may
    re-lock — still guard-owned."""
    out: list[Finding] = []
    for rel in _native_sources(root):
        if rel.endswith(os.path.join("common", "lockrank.h")):
            continue
        text = _read(root, rel)
        if text is None:
            continue
        raw_lines = text.splitlines()
        for i, line in enumerate(_strip_cc_comments(text).splitlines(), 1):
            if _nolint(raw_lines[i - 1], "lock-guard-discipline"):
                continue
            if _PTHREAD_LOCK_RE.search(line):
                out.append(Finding(
                    "lock-guard-discipline", rel, i,
                    "pthread mutex call — use a scoped guard over a "
                    "RankedMutex"))
            for m in _BARE_LOCK_RE.finditer(line):
                if _GUARD_NAME_RE.match(m.group(1)):
                    continue
                out.append(Finding(
                    "lock-guard-discipline", rel, i,
                    f"bare {m.group(1)}.{m.group(2)}() — take locks via "
                    f"std::lock_guard/std::unique_lock/SpinGuard so early "
                    f"returns cannot orphan them"))
    return out


_BLOCKING_CALL_RE = re.compile(
    r"\b(open|openat|close|read|write|pread|pwrite|readv|writev|fsync|"
    r"fdatasync|usleep|sleep|nanosleep|poll|select|epoll_wait|recv|send|"
    r"recvmsg|sendmsg|recvfrom|sendto|connect|accept|accept4|fopen|"
    r"fclose|fread|fwrite|fprintf|fflush|rename|unlink|mkdir|rmdir|"
    r"stat|fstat|lstat|statvfs|opendir|readdir|closedir)\s*\(")


def check_spin_region_blocking(root: str) -> list[Finding]:
    """A RankedSpinLock critical section (SpinGuard scope) busy-waits
    its contenders: a blocking syscall inside one turns every concurrent
    Record() into a spin on a descheduled holder.  Scans each SpinGuard
    declaration's enclosing brace scope for blocking calls."""
    out: list[Finding] = []
    for rel in _native_sources(root):
        text = _read(root, rel)
        if text is None:
            continue
        clean = _strip_cc_comments(text)
        lines = clean.splitlines()
        for i, line in enumerate(lines):
            if "SpinGuard" not in line:
                continue
            depth = 0
            for j in range(i, len(lines)):
                scan = lines[j]
                if j == i:
                    scan = scan[scan.index("SpinGuard"):]
                m = _BLOCKING_CALL_RE.search(scan)
                if m:
                    out.append(Finding(
                        "spin-region-blocking", rel, j + 1,
                        f"blocking call {m.group(1)}() inside the "
                        f"SpinGuard region opened at line {i + 1} — slot "
                        f"spinlocks must stay bounded-copy sections"))
                depth += scan.count("{") - scan.count("}")
                if depth < 0:
                    break
    return out


CHECKS = {
    "opcode-parity": check_opcode_parity,
    "header-parity": check_header_parity,
    "stat-fields": check_stat_fields,
    "conf-parity": check_conf_parity,
    "golden-coverage": check_golden_coverage,
    "lock-raw-mutex": check_lock_raw_mutex,
    "lock-guard-discipline": check_lock_guard_discipline,
    "spin-region-blocking": check_spin_region_blocking,
}


def run(root: str, only: list[str] | None = None) -> list[Finding]:
    names = only or list(CHECKS)
    findings: list[Finding] = []
    for name in names:
        findings.extend(CHECKS[name](root))
    return findings


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdfs_lint",
        description="static contract & lock-discipline linter")
    ap.add_argument("--root", default=REPO,
                    help="tree to lint (default: this repo)")
    ap.add_argument("--only", action="append", choices=sorted(CHECKS),
                    help="run only these check classes (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="list check classes and exit")
    args = ap.parse_args(argv)
    if args.list:
        for name in CHECKS:
            print(name)
        return 0
    findings = run(args.root, args.only)
    for f in findings:
        print(f)
    n_checks = len(args.only or CHECKS)
    if findings:
        print(f"fdfs_lint: {len(findings)} finding(s) "
              f"across {n_checks} check class(es)", file=sys.stderr)
        return 1
    print(f"fdfs_lint: OK ({n_checks} check classes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
