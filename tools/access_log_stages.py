#!/usr/bin/env python
"""Aggregate the storage daemon's per-stage access log into a stage table.

The daemon (storage.conf:use_access_log) writes one line per request to
``<base_path>/logs/access.log``:

    <epoch> <ip> <cmd> <status> <bytes> <cost_us> <recv_us> <work_us>
    <fp_us> <fp_lock_us> <cswrite_us> <binlog_us> <req_bytes>

(native/storage/server.cc:LogAccess; older 8-column logs parse too, with
zero stage splits).  This tool answers the question the raw ingest rate
can't: WHERE does an upload's time go — network receive, fingerprinting
(and how much of that is queueing on the sidecar's serialized engine),
chunk-store writes, or the binlog — the attribution SURVEY.md §3.1 marks
on the reference's ``dio_write_file()`` hot loop.

The daemon's slow-request gate (storage.conf:slow_request_threshold_ms)
additionally interleaves one compact-JSON line per slow request:

    {"event":"slow_request","role":"storage","op":...,"trace_id":...,
     "span_id":...,"start_us":...,"dur_us":...,"status":...,"peer":...,
     "bytes":...}

``aggregate`` skips those (a compact JSON line is a single token);
``slow_requests`` ingests them, and ``--slow`` renders them with the
``cli.py trace --trace-id`` command that drills into each one.

Usage:  python tools/access_log_stages.py <access.log> [--json] [--slow]
Import: ``aggregate(path) -> dict``  (bench_configs embeds the result in
its artifacts); ``slow_requests(path) -> list[dict]``.
"""

from __future__ import annotations

import argparse
import json
import sys

CMD_NAMES = {
    11: "upload", 12: "delete", 14: "download", 16: "sync_create",
    21: "upload_slave", 22: "query_info", 23: "upload_appender",
    24: "append", 26: "fetch_binlog", 34: "modify", 36: "truncate",
    124: "near_dups", 126: "sync_query_chunks", 127: "sync_recipe",
    128: "fetch_recipe", 129: "fetch_chunk",
}

STAGES = ["recv_us", "work_us", "fp_us", "fp_lock_us", "cswrite_us",
          "binlog_us"]


def _pct(sorted_vals: list[int], q: float) -> int:
    if not sorted_vals:
        return 0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def slow_requests(path: str) -> list[dict]:
    """The structured slow-request JSON lines, in file order.  Malformed
    or non-slow JSON lines are skipped (the log interleaves formats)."""
    out: list[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("event") == "slow_request":
                out.append(rec)
    return out


def aggregate(path: str) -> dict:
    """Per-command stage totals, means, and latency percentiles.
    Slow-request JSON lines are ignored here (see ``slow_requests``)."""
    per_cmd: dict[int, dict] = {}
    with open(path) as fh:
        for line in fh:
            f = line.split()
            if len(f) < 8 or f[0].startswith("{"):
                continue
            try:
                cmd, status = int(f[2]), int(f[3])
                nums = [int(x) for x in f[4:13]]
            except ValueError:
                continue
            nums += [0] * (9 - len(nums))  # older column counts
            bytes_, cost = nums[0], nums[1]
            stages = nums[2:8]
            req_bytes = nums[8]
            d = per_cmd.setdefault(cmd, {
                "count": 0, "errors": 0, "bytes": 0, "req_bytes": 0,
                "cost_us": [], **{s: 0 for s in STAGES}})
            d["count"] += 1
            d["errors"] += status != 0
            d["bytes"] += bytes_
            d["req_bytes"] += req_bytes
            d["cost_us"].append(cost)
            for name, v in zip(STAGES, stages):
                d[name] += v
    out = {}
    for cmd, d in sorted(per_cmd.items()):
        costs = sorted(d.pop("cost_us"))
        total_cost = sum(costs)
        n = d["count"]
        row = {
            "count": n, "errors": d["errors"], "bytes": d["bytes"],
            "req_bytes": d["req_bytes"],
            "total_cost_s": round(total_cost / 1e6, 3),
            "mean_us": total_cost // max(n, 1),
            "p50_us": _pct(costs, 0.50),
            "p95_us": _pct(costs, 0.95),
            "p99_us": _pct(costs, 0.99),
            "stages_s": {s: round(d[s] / 1e6, 3) for s in STAGES},
            # share of total request time per stage ("other" = dispatch,
            # response send, file-id mint, rename, ...)
            "stage_share": {},
        }
        if total_cost > 0:
            # fp_lock is a subset of fp; work contains fp+cswrite+binlog.
            # Report the orthogonal decomposition of cost_us.
            recv = d["recv_us"]
            fp = d["fp_us"]
            lock = d["fp_lock_us"]
            cs = d["cswrite_us"]
            bl = d["binlog_us"]
            other_work = max(d["work_us"] - fp - cs - bl, 0)
            pre = max(total_cost - d["recv_us"] - d["work_us"], 0)
            for name, v in [("recv", recv), ("fp_rpc", fp - lock),
                            ("fp_lock_wait", lock), ("cs_write", cs),
                            ("binlog", bl), ("work_other", other_work),
                            ("dispatch_other", pre)]:
                row["stage_share"][name] = round(v / total_cost, 4)
        out[CMD_NAMES.get(cmd, f"cmd{cmd}")] = row
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("log", help="path to access.log")
    ap.add_argument("--json", action="store_true", help="raw JSON output")
    ap.add_argument("--slow", action="store_true",
                    help="show the structured slow-request lines instead")
    args = ap.parse_args()
    if args.slow:
        slow = slow_requests(args.log)
        if args.json:
            json.dump(slow, sys.stdout, indent=2)
            print()
            return 0
        for rec in slow:
            print(f"{rec.get('role', '?')} {rec.get('op', '?')} "
                  f"dur={rec.get('dur_us', 0) / 1000:.1f}ms "
                  f"status={rec.get('status', 0)} "
                  f"peer={rec.get('peer', '')} "
                  f"trace_id={rec.get('trace_id', '')}  "
                  f"(drill in: cli.py trace <tracker> "
                  f"--trace-id {rec.get('trace_id', '')})")
        if not slow:
            print("no slow-request records")
        return 0
    agg = aggregate(args.log)
    if args.json:
        json.dump(agg, sys.stdout, indent=2)
        print()
        return 0
    for op, row in agg.items():
        print(f"{op}: n={row['count']} err={row['errors']} "
              f"bytes={row['bytes']} mean={row['mean_us']}us "
              f"p50={row['p50_us']}us p95={row['p95_us']}us "
              f"p99={row['p99_us']}us")
        shares = " ".join(f"{k}={v:.1%}" for k, v in
                          row["stage_share"].items() if v > 0.0005)
        if shares:
            print(f"  {shares}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
