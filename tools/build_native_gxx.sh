#!/bin/bash
# Fallback native build without cmake/ninja: mirrors native/CMakeLists.txt
# with plain g++ (same sources, flags, and layout — binaries land in
# native/build/ where tests/harness.py expects them).  Use when the
# environment lacks the cmake toolchain; otherwise prefer
# `cmake -S native -B native/build -G Ninja && ninja -C native/build`.
#
# Env knobs (mirroring the CMake cache options, so tools/run_sanitizers.sh
# can drive either toolchain):
#   BUILD_DIR=build-tsan       output tree under native/ (default: build)
#   SANITIZE=address|thread|undefined
#   FDFS_LOCKRANK=1            compile in the lock-rank order checker
set -euo pipefail
cd "$(dirname "$0")/../native"

BUILD_DIR="${BUILD_DIR:-build}"
FLAGS="-std=c++17 -O2 -g -Wall -Wextra -I."
if [ -n "${SANITIZE:-}" ]; then
  FLAGS="$FLAGS -fsanitize=$SANITIZE -fno-omit-frame-pointer"
  if [ "$SANITIZE" = undefined ]; then
    # UB must be loud: without this UBSan prints and continues, and a
    # "passing" ubsan leg would mean nothing.
    FLAGS="$FLAGS -fno-sanitize-recover=all"
  fi
fi
if [ -n "${FDFS_LOCKRANK:-}" ] && [ "${FDFS_LOCKRANK}" != 0 ]; then
  FLAGS="$FLAGS -DFDFS_LOCKRANK"
fi
mkdir -p "$BUILD_DIR/obj"

srcs_common="common/bytes.cc common/cdc.cc common/fileid.cc common/ini.cc
  common/lockrank.cc common/log.cc common/net.cc common/req_server.cc
  common/stats.cc common/trace.cc common/eventlog.cc common/metrog.cc
  common/sloeval.cc common/heatsketch.cc common/fsutil.cc
  common/threadreg.cc common/profiler.cc common/healthmon.cc
  common/heatwire.cc common/http_token.cc"
srcs_storage="storage/admission.cc storage/chunkstore.cc storage/slabstore.cc storage/ecstore.cc
  storage/config.cc storage/store.cc
  storage/binlog.cc storage/trunk.cc storage/hotrepl.cc storage/recovery.cc storage/rebalance.cc storage/scrub.cc storage/dedup.cc
  storage/server.cc storage/sync.cc storage/tracker_client.cc"
srcs_tracker="tracker/cluster.cc tracker/hotmap.cc tracker/placement.cc tracker/relationship.cc tracker/server.cc"

pids=""
for f in $srcs_common $srcs_storage $srcs_tracker; do
  o="$BUILD_DIR/obj/$(echo "$f" | tr / _ | sed 's/\.cc$/.o/')"
  g++ $FLAGS -c "$f" -o "$o" &
  pids="$pids $!"
done
# SHA-NI TU gets its own ISA flags (runtime cpuid gate keeps it safe on
# older hosts) — matches the fdfs_sha1ni OBJECT library in CMake.
g++ $FLAGS -msha -mssse3 -msse4.1 -c common/sha1_ni.cc \
  -o "$BUILD_DIR/obj/common_sha1_ni.o" &
pids="$pids $!"
for p in $pids; do wait "$p"; done

ar rcs "$BUILD_DIR/obj/libfdfs_common.a" "$BUILD_DIR"/obj/common_*.o
ar rcs "$BUILD_DIR/obj/libfdfs_storage.a" "$BUILD_DIR"/obj/storage_*.o
ar rcs "$BUILD_DIR/obj/libfdfs_tracker.a" "$BUILD_DIR"/obj/tracker_*.o

# -rdynamic: the sampling profiler symbolizes via backtrace_symbols,
# which reads the DYNAMIC symbol table — without this every frame in a
# PROFILE_DUMP is a bare hex address.
link() { g++ $FLAGS -rdynamic "$@" -lpthread; }
link storage/main.cc "$BUILD_DIR/obj/libfdfs_storage.a" \
  "$BUILD_DIR/obj/libfdfs_common.a" -o "$BUILD_DIR/fdfs_storaged" &
link tracker/main.cc "$BUILD_DIR/obj/libfdfs_tracker.a" \
  "$BUILD_DIR/obj/storage_admission.o" \
  "$BUILD_DIR/obj/libfdfs_common.a" -o "$BUILD_DIR/fdfs_trackerd" &
link tools/codec_cli.cc "$BUILD_DIR/obj/storage_slabstore.o" \
  "$BUILD_DIR/obj/storage_ecstore.o" \
  "$BUILD_DIR/obj/storage_admission.o" \
  "$BUILD_DIR/obj/tracker_placement.o" \
  "$BUILD_DIR/obj/tracker_cluster.o" \
  "$BUILD_DIR/obj/tracker_hotmap.o" \
  "$BUILD_DIR/obj/libfdfs_common.a" -o "$BUILD_DIR/fdfs_codec" &
link tools/load_cli.cc "$BUILD_DIR/obj/libfdfs_common.a" \
  -o "$BUILD_DIR/fdfs_load" &
link tests/common_test.cc "$BUILD_DIR/obj/libfdfs_common.a" \
  -o "$BUILD_DIR/common_test" &
link tests/storage_test.cc "$BUILD_DIR/obj/libfdfs_storage.a" \
  "$BUILD_DIR/obj/libfdfs_common.a" -o "$BUILD_DIR/storage_test" &
link tests/tracker_test.cc "$BUILD_DIR/obj/libfdfs_tracker.a" \
  "$BUILD_DIR/obj/storage_admission.o" \
  "$BUILD_DIR/obj/libfdfs_common.a" -o "$BUILD_DIR/tracker_test" &
wait
echo "native build complete: $(ls "$BUILD_DIR/fdfs_storaged" "$BUILD_DIR/fdfs_trackerd")"
