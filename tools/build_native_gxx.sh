#!/bin/bash
# Fallback native build without cmake/ninja: mirrors native/CMakeLists.txt
# with plain g++ (same sources, flags, and layout — binaries land in
# native/build/ where tests/harness.py expects them).  Use when the
# environment lacks the cmake toolchain; otherwise prefer
# `cmake -S native -B native/build -G Ninja && ninja -C native/build`.
set -euo pipefail
cd "$(dirname "$0")/../native"

FLAGS="-std=c++17 -O2 -g -Wall -Wextra -I."
mkdir -p build/obj

srcs_common="common/bytes.cc common/cdc.cc common/fileid.cc common/ini.cc
  common/log.cc common/net.cc common/req_server.cc common/stats.cc
  common/trace.cc common/eventlog.cc common/fsutil.cc common/http_token.cc"
srcs_storage="storage/chunkstore.cc storage/config.cc storage/store.cc
  storage/binlog.cc storage/trunk.cc storage/recovery.cc storage/scrub.cc storage/dedup.cc
  storage/server.cc storage/sync.cc storage/tracker_client.cc"
srcs_tracker="tracker/cluster.cc tracker/relationship.cc tracker/server.cc"

pids=""
for f in $srcs_common $srcs_storage $srcs_tracker; do
  o="build/obj/$(echo "$f" | tr / _ | sed 's/\.cc$/.o/')"
  g++ $FLAGS -c "$f" -o "$o" &
  pids="$pids $!"
done
# SHA-NI TU gets its own ISA flags (runtime cpuid gate keeps it safe on
# older hosts) — matches the fdfs_sha1ni OBJECT library in CMake.
g++ $FLAGS -msha -mssse3 -msse4.1 -c common/sha1_ni.cc \
  -o build/obj/common_sha1_ni.o &
pids="$pids $!"
for p in $pids; do wait "$p"; done

ar rcs build/obj/libfdfs_common.a build/obj/common_*.o
ar rcs build/obj/libfdfs_storage.a build/obj/storage_*.o
ar rcs build/obj/libfdfs_tracker.a build/obj/tracker_*.o

link() { g++ $FLAGS "$@" -lpthread; }
link storage/main.cc build/obj/libfdfs_storage.a build/obj/libfdfs_common.a \
  -o build/fdfs_storaged &
link tracker/main.cc build/obj/libfdfs_tracker.a build/obj/libfdfs_common.a \
  -o build/fdfs_trackerd &
link tools/codec_cli.cc build/obj/libfdfs_common.a -o build/fdfs_codec &
link tools/load_cli.cc build/obj/libfdfs_common.a -o build/fdfs_load &
link tests/common_test.cc build/obj/libfdfs_common.a -o build/common_test &
link tests/storage_test.cc build/obj/libfdfs_storage.a \
  build/obj/libfdfs_common.a -o build/storage_test &
link tests/tracker_test.cc build/obj/libfdfs_tracker.a \
  build/obj/libfdfs_common.a -o build/tracker_test &
wait
echo "native build complete: $(ls build/fdfs_storaged build/fdfs_trackerd)"
