#!/usr/bin/env python
"""Fingerprint-pipeline profile: where does the ingest GB/s go?

Times each stage of the dedup fingerprint path in isolation on the real
device (median of steady-state iters, full device_get fence), so the
headline bench number is explainable instead of guessed at.  Prints one
JSON object per stage, then a final summary object; ``--trace DIR``
additionally captures a JAX profiler trace of the fused pipeline (one
extra ``{"trace_dir": ...}`` line).  The round-3 breakdown that
justified the bench.py rewrite is checked in at tools/PROFILE_r03.md.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fence_median(fn, iters=6):
    import jax
    jax.device_get(fn())  # warm/compile
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.device_get(fn())
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default="",
                    help="also capture a JAX profiler trace of one fused "
                         "pipeline round into this directory (open with "
                         "tensorboard/xprof; SURVEY.md §5 tracing)")
    args = ap.parse_args()  # before the heavy jax import: --help stays fast

    import jax

    from fastdfs_tpu.ops.sha1 import sha1_batch
    from fastdfs_tpu.ops.minhash import minhash_batch
    from fastdfs_tpu.ops.pallas_sha1 import sha1_batch_pallas
    from fastdfs_tpu.ops.pallas_minhash import minhash_batch_pallas

    chunk_kb, n_chunks = 64, 2048
    L = chunk_kb * 1024
    total = n_chunks * L
    rng = np.random.RandomState(0)
    chunks = rng.randint(0, 256, size=(n_chunks, L), dtype=np.uint8)
    lens = np.full(n_chunks, L, dtype=np.int32)
    dc, dl = jax.device_put(chunks), jax.device_put(lens)
    jax.block_until_ready((dc, dl))

    results = {}

    def stage(name, fn):
        dt = fence_median(fn)
        results[name] = {"sec": round(dt, 5), "GBps": round(total / dt / 1e9, 3)}
        print(json.dumps({"stage": name, **results[name]}), flush=True)

    # Dispatch floor: a trivial jitted op on the same inputs.
    triv = jax.jit(lambda c: c[0, :8].astype(jnp_u32()))
    stage("dispatch_floor", lambda: triv(dc))

    # Host->device transfer of the whole batch (the streaming cost).
    def h2d():
        a = jax.device_put(chunks)
        a.block_until_ready()
        return a[0, :8]
    stage("host_to_device", h2d)

    stage("sha1_xla", lambda: sha1_batch(dc, dl))
    stage("sha1_pallas", lambda: sha1_batch_pallas(dc, dl, L))
    stage("minhash_xla", lambda: minhash_batch(dc, dl))
    stage("minhash_pallas", lambda: minhash_batch_pallas(dc, dl))

    both = jax.jit(lambda c, ln: (sha1_batch_pallas(c, ln, L),
                                  minhash_batch_pallas(c, ln)))
    stage("fused_pallas_both", lambda: both(dc, dl))

    if args.trace:
        with jax.profiler.trace(args.trace):
            jax.device_get([both(dc, dl) for _ in range(4)])
        print(json.dumps({"trace_dir": args.trace}))

    print(json.dumps({"total_bytes": total, "results": results}))


def jnp_u32():
    import jax.numpy as jnp
    return jnp.uint32


if __name__ == "__main__":
    main()
