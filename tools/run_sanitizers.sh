#!/bin/bash
# Build the native core under ASan and TSan and run the daemon-facing
# pytest suite against each build (SURVEY.md §5: "ASan/TSan CI targets
# for the C++ core" — the reference has none; the rebuild's threaded
# storage daemon needs them).
#
# Usage: tools/run_sanitizers.sh [asan|tsan|both] [pytest args...]
# The harness picks up the instrumented binaries via FDFS_NATIVE_BUILD.
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-both}"
shift || true
if [ "$#" -gt 0 ]; then
  PYTEST_ARGS=("$@")
else
  PYTEST_ARGS=(tests/test_storage_daemon.py tests/test_tracker_daemon.py
    tests/test_replication.py tests/test_trunk.py
    tests/test_chunked_storage.py tests/test_disk_recovery.py
    tests/test_multi_tracker.py tests/test_trace.py
    tests/test_dedup_upload.py tests/test_scrub.py
    tests/test_read_path.py tests/test_observability.py)
fi

run_one() {
  local san="$1" dir="native/build-$1"
  echo "=== $san: configure + build ==="
  cmake -S native -B "$dir" -G Ninja -DSANITIZE="$2" \
        -DCMAKE_BUILD_TYPE=RelWithDebInfo >/dev/null
  ninja -C "$dir"
  echo "=== $san: native unit tests (incl. trace-ring concurrency) ==="
  # common_test's TestTraceRingThreaded hammers the lock-light span ring
  # from 4 recorders + a dumping reader — the TSan run is the proof the
  # seqlock-free design is data-race-free, not just lucky.
  # TestEventLogThreaded does the same for the flight recorder, and
  # TestEventLoopLagHook/TestWorkerPoolQueueStats cover the ISSUE 6
  # saturation instrumentation (loop-lag hook, dio queue histograms).
  "$dir/common_test"
  # storage_test's TestChunkStoreStripedConcurrency hammers the
  # digest-striped chunk store + hot-chunk read cache from concurrent
  # uploaders/deleters, cached readers, pin sessions, and a
  # quarantine/GC sweeper — the TSan proof of the PR 5 lock sharding
  # and cache-coherence invariants.
  "$dir/storage_test"
  echo "=== $san: daemon suite ==="
  # halt_on_error keeps a failing daemon loud; leak detection stays on
  # for asan (daemons shut down cleanly in the harness).
  # test_dedup_upload.py's concurrent-uploads-and-deletes test is the
  # negotiated-upload session target: pin/ref races and the
  # abort-timeout sweep run under TSan here.
  # test_scrub.py's test_scrub_races_uploads_and_deletes is the
  # integrity-engine target: scrub verify/quarantine/GC passes racing
  # live uploads + eager deletes (the scrub thread vs dio workers on
  # the chunk-store lock, and the pin-vs-GcSweep probe).
  if [ "$san" = tsan ]; then
    export TSAN_OPTIONS="halt_on_error=1"
  else
    export ASAN_OPTIONS="halt_on_error=1:detect_leaks=1"
  fi
  FDFS_NATIVE_BUILD="$dir" python -m pytest "${PYTEST_ARGS[@]}" -x -q
}

case "$MODE" in
  asan) run_one asan address ;;
  tsan) run_one tsan thread ;;
  both) run_one asan address && run_one tsan thread ;;
  *) echo "usage: $0 [asan|tsan|both] [pytest args...]" >&2; exit 2 ;;
esac
echo "sanitizer suite: PASS ($MODE)"
