#!/bin/bash
# Build the native core under the sanitizer matrix and run the
# daemon-facing pytest suite against each build (SURVEY.md §5: "ASan/TSan
# CI targets for the C++ core" — the reference has none; the rebuild's
# threaded storage daemon needs them).
#
# Usage: tools/run_sanitizers.sh [asan|tsan|ubsan|lockrank|all|both] [pytest args...]
#
#   asan      heap errors + leaks
#   tsan      data races (slot rings, chunk-store stripes, worker pools)
#   ubsan     undefined behavior, -fno-sanitize-recover (first report aborts)
#   lockrank  TSan + -DFDFS_LOCKRANK: every RankedMutex acquisition checked
#             against the per-thread held-rank stack; any lock-order
#             violation aborts with both lock sites (common/lockrank.h).
#             The native leg also runs the RankedMutex death tests.
#   all       the full matrix, in the order above
#   both      legacy alias for asan + tsan
#
# The harness picks up the instrumented binaries via FDFS_NATIVE_BUILD.
# Builds use cmake/ninja when available and fall back to
# tools/build_native_gxx.sh (same sources and flags) otherwise.
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-all}"
shift || true
if [ "$#" -gt 0 ]; then
  PYTEST_ARGS=("$@")
else
  PYTEST_ARGS=(tests/test_storage_daemon.py tests/test_tracker_daemon.py
    tests/test_replication.py tests/test_trunk.py
    tests/test_chunked_storage.py tests/test_disk_recovery.py
    tests/test_multi_tracker.py tests/test_trace.py
    tests/test_dedup_upload.py tests/test_scrub.py
    tests/test_read_path.py tests/test_observability.py
    tests/test_report.py tests/test_slab.py tests/test_groups.py
    tests/test_cdc_kernels.py tests/test_profile.py tests/test_ec.py
    tests/test_health.py tests/test_serving_edge.py
    tests/test_admission.py tests/test_hot_replication.py)
fi

build_tree() {
  local dir="$1" sanitize="$2" lockrank="$3"
  if command -v cmake >/dev/null && command -v ninja >/dev/null; then
    local args=(-S native -B "$dir" -G Ninja
                -DCMAKE_BUILD_TYPE=RelWithDebInfo
                -DSANITIZE="$sanitize" -DFDFS_LOCKRANK="$lockrank")
    cmake "${args[@]}" >/dev/null
    ninja -C "$dir"
  else
    BUILD_DIR="$(basename "$dir")" SANITIZE="$sanitize" \
      FDFS_LOCKRANK="$([ "$lockrank" = ON ] && echo 1 || echo "")" \
      bash tools/build_native_gxx.sh >/dev/null
  fi
}

run_one() {
  local flavor="$1" sanitize="$2" lockrank="${3:-OFF}"
  local dir="native/build-$flavor"
  echo "=== $flavor: configure + build (sanitize=$sanitize lockrank=$lockrank) ==="
  build_tree "$dir" "$sanitize" "$lockrank"
  echo "=== $flavor: native unit tests ==="
  # common_test's TestTraceRingThreaded/TestEventLogThreaded hammer the
  # lock-light rings from concurrent recorders + a dumping reader — the
  # TSan run is the proof the design is data-race-free, not just lucky.
  # TestRankedMutexThreaded does the same for the lock-rank checker's
  # thread_local bookkeeping, and under the lockrank flavor the
  # TestRankedMutexInversionAborts death tests prove a rank inversion
  # (including a descending-stripe RefAll violation) aborts with both
  # lock sites reported.
  "$dir/common_test"
  # storage_test's TestChunkStoreStripedConcurrency hammers the
  # digest-striped chunk store + hot-chunk read cache from concurrent
  # uploaders/deleters, cached readers, pin sessions, and a
  # quarantine/GC sweeper — under lockrank this also validates the
  # ascending-stripe RefAll protocol at runtime.
  "$dir/storage_test"
  "$dir/tracker_test"
  echo "=== $flavor: daemon suite ==="
  # halt_on_error keeps a failing daemon loud; leak detection stays on
  # for asan (daemons shut down cleanly in the harness).
  case "$sanitize" in
    thread) export TSAN_OPTIONS="halt_on_error=1" ;;
    address) export ASAN_OPTIONS="halt_on_error=1:detect_leaks=1" ;;
    undefined) export UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1" ;;
  esac
  FDFS_NATIVE_BUILD="$dir" python -m pytest "${PYTEST_ARGS[@]}" -x -q
}

case "$MODE" in
  asan) run_one asan address ;;
  tsan) run_one tsan thread ;;
  ubsan) run_one ubsan undefined ;;
  lockrank) run_one lockrank thread ON ;;
  both) run_one asan address && run_one tsan thread ;;
  all) run_one asan address && run_one tsan thread \
       && run_one ubsan undefined && run_one lockrank thread ON ;;
  *) echo "usage: $0 [asan|tsan|ubsan|lockrank|all|both] [pytest args...]" >&2
     exit 2 ;;
esac
echo "sanitizer suite: PASS ($MODE)"
