#!/usr/bin/env python
"""Scale proof for the columnar ExactDigestIndex (and the LSH ref map).

The index docstring claims ~36 B/entry and "engineered for tens of
millions of entries"; this harness turns the claim into a measured
artifact: RAM per entry, insert + lookup rates, merge pauses, snapshot
size and save/load time at N synthetic chunks (default 10M — config 5's
nominal corpus is ~62M chunks across 4 nodes, so 10M+ is one node's
realistic steady state).  Pure-index run, no daemon needed.

Run:  python tools/bench_index_scale.py [--n 10000000] [--out FILE]
Writes bench_artifacts/index_scale.json by default.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def rss_mb() -> float:
    with open("/proc/self/status") as fh:
        for line in fh:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10_000_000)
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_artifacts", "index_scale.json"))
    args = ap.parse_args()

    from fastdfs_tpu.dedup.index import ExactDigestIndex

    n = args.n
    rng = np.random.RandomState(42)
    # Synthetic 20-byte digests (uniform random — the same key
    # distribution real SHA1 output has).  Generated in one array so the
    # generator's cost and RAM stay out of the index measurements.
    digs = rng.randint(0, 256, size=(n, 20), dtype=np.uint8)
    keys = digs.view("S20").ravel()

    idx = ExactDigestIndex()
    rss0 = rss_mb()

    # -- inserts (every digest new; carriers cycle over 1000 file ids) ----
    t0 = time.perf_counter()
    max_pause = 0.0
    batch = 100_000
    for start in range(0, n, batch):
        t_b = time.perf_counter()
        for i in range(start, min(start + batch, n)):
            idx.insert(bytes(keys[i]), [f"f{i % 1000}", i])
        max_pause = max(max_pause, time.perf_counter() - t_b)
    insert_s = time.perf_counter() - t0
    rss_after_insert = rss_mb()

    # -- batched lookups (the engine's judge path) -------------------------
    m = 1_000_000
    probe_hit = [bytes(keys[i]) for i in
                 rng.randint(0, n, m // 2)]
    probe_miss = [bytes(rng.randint(0, 256, 20, dtype=np.uint8))
                  for _ in range(1000)]
    t0 = time.perf_counter()
    got = idx.lookup_batch(probe_hit)
    lookup_batch_s = time.perf_counter() - t0
    assert all(r is not None for r in got)
    t0 = time.perf_counter()
    for d in probe_miss:
        idx.lookup(d)
    lookup_scalar_s = time.perf_counter() - t0

    # -- removals + merge compaction --------------------------------------
    t0 = time.perf_counter()
    for i in range(0, n, 1000):
        idx.remove(bytes(keys[i]))
    remove_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    idx._merge()
    merge_s = time.perf_counter() - t0

    # -- snapshot ----------------------------------------------------------
    with tempfile.TemporaryDirectory() as tmp:
        p = os.path.join(tmp, "exact")
        t0 = time.perf_counter()
        idx.save(p)
        save_s = time.perf_counter() - t0
        size_mb = os.path.getsize(p + ".npz") / 1e6
        t0 = time.perf_counter()
        idx2 = ExactDigestIndex.load(p)
        load_s = time.perf_counter() - t0
        assert len(idx2) == len(idx)

    out = {
        "entries": n,
        "insert_seconds": round(insert_s, 2),
        "inserts_per_sec": round(n / insert_s),
        "max_100k_batch_pause_s": round(max_pause, 3),
        "rss_before_mb": round(rss0, 1),
        "rss_after_insert_mb": round(rss_after_insert, 1),
        "index_bytes_per_entry": round(
            (rss_after_insert - rss0) * 1e6 / n, 1),
        "lookup_batch_per_sec": round(len(probe_hit) / lookup_batch_s),
        "lookup_scalar_per_sec": round(len(probe_miss) / lookup_scalar_s),
        "remove_per_sec": round((n // 1000) / remove_s),
        "final_merge_seconds": round(merge_s, 3),
        "snapshot_mb": round(size_mb, 1),
        "snapshot_save_seconds": round(save_s, 2),
        "snapshot_load_seconds": round(load_s, 2),
        "note": "synthetic uniform 20B digests; carriers interned over "
                "1000 file ids; rss delta includes the generator-side "
                "probe lists",
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(out, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
